"""Serving fleet: a replica manager behind the one socket front door.

PR 2's serving tier is one process, one model, one device; this module is
the step from "a server" to "a service" — the Poseidon shape restated at
inference time: throughput comes from composing many fast single-device
engines under a manager that owns placement, health, and staleness. Each
replica is its own :class:`BucketedExecutor` + :class:`DynamicBatcher`
(one flush thread per replica — the executors genuinely run concurrently,
pinned to distinct local devices when there are devices to pin to), and
the front door routes per-request.

Replica lifecycle (one-way into DEAD; everything else cycles)::

    WARMING ──> SERVING <──> DRAINING
                   │             │
                   └──> DEAD <───┘

- ``WARMING``  — executor buckets still AOT-compiling; never routed.
- ``SERVING``  — in the routing set.
- ``DRAINING`` — no NEW requests; admitted ones finish (rolling reload and
  graceful shutdown both pass through here).
- ``DEAD``     — failure detection tripped (dispatch error or a wedged
  flush thread); terminal, never routed, never reloaded.

Routing signal: ``load = queue_depth + inflight_rows / max_batch`` from
each replica's live batcher stats — queued requests plus the fill of the
batch currently on the device. Least-loaded wins; ties break to the lowest
replica index (deterministic).

Failover contract: a replica dying MID-REQUEST loses zero accepted
requests. The dead batcher fans its dispatch error out to every co-batched
request; each of those ``submit`` calls re-enters the router and is
re-dispatched on a surviving replica. Only explicit sheds (every serving
replica at queue capacity, or no serving replica at all) are refused, and
they are refused immediately — the PR-2 backpressure contract, fleet-wide.

Rolling hot-reload: :meth:`ReplicaManager.rolling_reload` drains and swaps
replicas ONE at a time (never more than one draining — the invariant the
chaos suite pins), so fleet capacity never dips by more than one replica
and no request is dropped or errored by a reload.

Everything here is jax-free at import (the executors own all jax state);
threads are daemon; sockets stay in server.py.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..runtime.metrics import LatencyWindow, log
from .batcher import (DeadlineError, DynamicBatcher, ShedError,
                      ShuttingDownError)

__all__ = ["Replica", "ReplicaManager", "PartialReloadError", "WARMING",
           "SERVING", "DRAINING", "DEAD", "REPLICA_STATES"]

WARMING = "WARMING"
SERVING = "SERVING"
DRAINING = "DRAINING"
DEAD = "DEAD"
REPLICA_STATES = (WARMING, SERVING, DRAINING, DEAD)


class PartialReloadError(RuntimeError):
    """A rolling pass swapped SOME replicas but not all (drain timeout or
    a refused swap). TYPED so the fleet reloader can tell "the roll ran
    and partially landed — do not re-drain the healthy replicas every
    poll" from "the load itself failed — nothing was touched, retry"."""

    def __init__(self, message: str, swapped: int, errors):
        super().__init__(message)
        self.swapped = swapped
        self.errors = list(errors)


class Replica:
    """One serving engine: executor + its private micro-batcher + health.

    The batcher exists only once the executor is attached (a WARMING
    replica has nothing to enqueue into); ``state`` transitions run
    through :meth:`ReplicaManager._transition` so the draining invariant
    and the death counters live in exactly one place."""

    def __init__(self, index: int, executor=None, device_label: str = "",
                 max_delay_s: float = 0.005, max_queue: int = 64):
        self.index = index
        self.device_label = device_label
        self.executor = executor
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        self.batcher: Optional[DynamicBatcher] = None
        self.state = WARMING
        self.reload_generation = 0
        self.routed = 0            # requests the router assigned here
        self.failures = 0          # dispatch errors / wedged-submit events
        self.death_reason: Optional[str] = None
        self._lock = threading.Lock()
        if executor is not None:
            self._attach_batcher()

    def _attach_batcher(self) -> None:
        # an executor that brings its own scheduler (GenerateExecutor's
        # ContinuousScheduler) plugs in here; routing, failover, rolling
        # reload, and stats compose unchanged — a replica that schedules
        # sequences instead of micro-batches is still just a replica
        mk = getattr(self.executor, "make_batcher", None)
        if mk is not None:
            self.batcher = mk(max_delay_s=self.max_delay_s,
                              max_queue=self.max_queue)
        else:
            self.batcher = DynamicBatcher(self.executor,
                                          max_delay_s=self.max_delay_s,
                                          max_queue=self.max_queue)

    def load(self) -> float:
        """The routing signal (see module docstring). A replica with no
        batcher yet (WARMING) is never routed, but report its load as
        +inf so even a racy read sorts it last."""
        b = self.batcher
        return b.load_score() if b is not None else float("inf")

    def snapshot(self) -> Dict:
        """One per-replica stats row (the `stats` op / metrics-endpoint
        shape; scalar leaves so the flat key=value rendering keeps them)."""
        with self._lock:
            row = {
                "state": self.state,
                "device": self.device_label,
                "reload_generation": self.reload_generation,
                "routed": self.routed,
                "failures": self.failures,
            }
            if self.death_reason:
                row["death_reason"] = self.death_reason
        b = self.batcher
        if b is not None:
            fill = b.fill_ratio()
            row.update({
                "queue_depth": b.queue_depth,
                "inflight_rows": b.inflight_rows,
                "load": round(b.load_score(), 4),
                "batch_fill": None if fill is None else round(fill, 4),
                "batches": b.batches,
                "shed": b.shed_count,
                "deadline_expired": b.deadline_expired,
                "latency": b.latency.summary(),
            })
        ex = self.executor
        if ex is not None:
            row["params_version"] = getattr(ex, "params_version", None)
            row["rows_served"] = getattr(ex, "rows_served", None)
        return row


class ReplicaManager:
    """N replicas, least-loaded routing, health states, rolling reload.

    ``executors`` are assumed warmed (a :class:`BucketedExecutor` warms at
    construction); use :meth:`build` with a factory to get real WARMING
    states. ``failure_threshold`` consecutive dispatch failures (or one
    wedged-submit timeout each) mark a replica DEAD; ``on_transition`` is
    an observer callback ``(index, old, new, reason)`` — the chaos suite's
    invariant probe. ``None`` policy knobs resolve against
    ``config.fleet_config()`` (the same late-binding idiom as
    ManagedCommConfig)."""

    def __init__(self, executors: Sequence = (), devices: Sequence = (),
                 *, max_delay_s: float = 0.005, max_queue: int = 64,
                 failure_threshold: Optional[int] = None,
                 drain_timeout_s: Optional[float] = None,
                 on_transition: Optional[Callable] = None):
        from ..config import fleet_config
        cfg = fleet_config()
        self.failure_threshold = int(failure_threshold
                                     if failure_threshold is not None
                                     else cfg.failure_threshold)
        self.drain_timeout_s = float(drain_timeout_s
                                     if drain_timeout_s is not None
                                     else cfg.drain_timeout_s)
        self.max_delay_s = max_delay_s
        self.max_queue = max_queue
        self.on_transition = on_transition
        self.latency = LatencyWindow()   # front-door submit -> reply
        # fleet counters (manager lock; replica-local ones live on Replica)
        self.routed_total = 0
        self.failovers = 0          # submits re-dispatched off a dead replica
        self.fleet_sheds = 0        # requests refused fleet-wide
        self.deaths = 0
        self.reload_generation = 0
        self.max_concurrent_draining = 0
        self._draining = 0
        # the latest rolled (generation, params): a replica that finishes
        # WARMING after a reload pass catches up from here instead of
        # serving its factory-loaded stale weights forever
        self._last_reload = None
        self._closing = False
        self._lock = threading.Lock()
        self._reload_lock = threading.Lock()   # one rolling pass at a time
        self.replicas: List[Replica] = []
        labels = list(devices) + [""] * (len(executors) - len(devices))
        for i, ex in enumerate(executors):
            rep = Replica(i, ex, device_label=str(labels[i]),
                          max_delay_s=max_delay_s, max_queue=max_queue)
            self.replicas.append(rep)
            self._transition(rep, SERVING, reason="pre-warmed executor")

    # ---- construction ---------------------------------------------------- #
    @classmethod
    def build(cls, factory: Callable, n_replicas: int,
              devices: Sequence = (), warm_async: bool = False,
              **kwargs) -> "ReplicaManager":
        """Build N replicas through ``factory(device_or_None) -> executor``
        (construction IS the warm-up: every bucket AOT-compiles inside the
        factory). Replicas are visible in WARMING while their factory
        runs; ``warm_async=True`` warms them on background threads so the
        fleet starts serving as soon as the FIRST replica is ready."""
        mgr = cls((), **kwargs)
        devs = list(devices)
        for i in range(int(n_replicas)):
            dev = devs[i % len(devs)] if devs else None
            rep = Replica(i, None, device_label=str(dev) if dev is not None
                          else "", max_delay_s=mgr.max_delay_s,
                          max_queue=mgr.max_queue)
            mgr.replicas.append(rep)

            def warm_one(rep=rep, dev=dev):
                try:
                    ex = factory(dev)
                except Exception as e:  # noqa: BLE001 — a replica that
                    # cannot warm is a DEAD replica, not a dead fleet
                    mgr._mark_dead(rep, f"warm-up failed: "
                                        f"{type(e).__name__}: {e}")
                    return
                with rep._lock:
                    rep.executor = ex
                rep._attach_batcher()
                mgr._transition(rep, SERVING, reason="warmed")
                # a reload may have rolled the fleet while this replica
                # was still compiling; transition FIRST, then catch up —
                # if a concurrent rolling pass also swaps it, both land
                # the same params (idempotent)
                mgr._catch_up_reload(rep)

            if warm_async:
                threading.Thread(target=warm_one, daemon=True).start()
            else:
                warm_one()
        return mgr

    # ---- state machine --------------------------------------------------- #
    def _transition(self, rep: Replica, new_state: str,
                    reason: str = "") -> str:
        """The only writer of ``Replica.state``. DEAD is terminal; the
        draining high-water mark (the rolling-reload invariant's witness)
        updates here."""
        with rep._lock:
            old = rep.state
            if old == new_state or old == DEAD:
                return old
            rep.state = new_state
            if new_state == DEAD:
                rep.death_reason = reason
        with self._lock:
            if new_state == DRAINING:
                self._draining += 1
                self.max_concurrent_draining = max(
                    self.max_concurrent_draining, self._draining)
            if old == DRAINING:
                self._draining -= 1
            if new_state == DEAD:
                self.deaths += 1
        log(f"serving: replica {rep.index} {old} -> {new_state}"
            + (f" ({reason})" if reason else ""))
        cb = self.on_transition
        if cb is not None:
            cb(rep.index, old, new_state, reason)
        return old

    def _mark_dead(self, rep: Replica, reason: str) -> None:
        old = self._transition(rep, DEAD, reason=reason)
        if old == DEAD:
            return
        # complete the dead replica's queued requests with ShedError so
        # their router-side submit calls wake and re-dispatch (drain=False:
        # flushing through a dead executor would just re-raise per batch)
        if rep.batcher is not None:
            rep.batcher.close(drain=False, timeout_s=5.0)

    def state_counts(self) -> Dict[str, int]:
        counts = {s: 0 for s in REPLICA_STATES}
        for rep in self.replicas:
            with rep._lock:
                counts[rep.state] += 1
        return counts

    def reference_executor(self):
        """The first live replica's executor (net/params template for
        reload loads and bench input shapes)."""
        for rep in self.replicas:
            with rep._lock:
                dead = rep.state == DEAD
            if not dead and rep.executor is not None:
                return rep.executor
        raise RuntimeError("no live replica in the fleet")

    # ---- routing + failover ---------------------------------------------- #
    def _pick(self, exclude: frozenset) -> Optional[Replica]:
        best = None
        best_key = None
        for rep in self.replicas:
            if rep.index in exclude:
                continue
            with rep._lock:
                if rep.state != SERVING:
                    continue
            key = (rep.load(), rep.index)
            if best is None or key < best_key:
                best, best_key = rep, key
        return best

    def submit(self, inputs, deadline_s: Optional[float] = None,
               timeout_s: float = 30.0):
        """Route one request to the least-loaded SERVING replica; on a
        replica death mid-request, re-dispatch on a survivor. Returns
        ``(outputs, replica)``. Raises ShedError only for explicit
        fleet-wide backpressure, DeadlineError when the request's own
        deadline expired, ValueError for a malformed request."""
        t0 = time.monotonic()
        # the request's deadline is ABSOLUTE across reroutes: each batcher
        # admission recomputes now + deadline_s, so a failover must pass
        # the REMAINING budget, never restart the clock (the single-engine
        # path's contract, fleet-wide)
        deadline = None if deadline_s is None else t0 + float(deadline_s)
        with self._lock:
            if self._closing:
                raise ShuttingDownError("fleet is shutting down")
        tried: set = set()
        queue_full = 0
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise DeadlineError(
                        f"deadline expired after "
                        f"{time.monotonic() - t0:.3f}s (rerouting)")
            rep = self._pick(frozenset(tried))
            if rep is None:
                with self._lock:
                    self.fleet_sheds += 1
                if queue_full:
                    raise ShedError(
                        f"all {queue_full} serving replicas at queue "
                        f"capacity")
                raise ShedError("no serving replica available")
            with rep._lock:
                rep.routed += 1
            with self._lock:
                self.routed_total += 1
            try:
                out = rep.batcher.submit(inputs, deadline_s=remaining,
                                         timeout_s=timeout_s)
            except DeadlineError:
                raise               # the REQUEST's deadline — not a reroute
            except ShedError as e:
                with rep._lock:
                    dead = rep.state == DEAD
                tried.add(rep.index)
                if dead:
                    # leftovers of a killed batcher, not backpressure: the
                    # request was accepted, so it reroutes, never sheds
                    with self._lock:
                        self.failovers += 1
                    continue
                if isinstance(e, ShuttingDownError):
                    raise           # fleet/server shutdown — explicit shed
                queue_full += 1
                continue            # a FULL live replica: try the others
            except ValueError:
                raise               # malformed request — the client's error
            except Exception as e:  # noqa: BLE001 — replica failure
                self._note_failure(rep, e)
                tried.add(rep.index)
                with self._lock:
                    self.failovers += 1
                continue
            self.latency.record(time.monotonic() - t0)
            return out, rep

    def _catch_up_reload(self, rep: Replica) -> None:
        """Bring a late-warming replica onto the latest rolled params —
        without this, warm_async + a reload mid-compile would leave it
        serving its factory-loaded weights with no error anywhere."""
        with self._lock:
            pending = self._last_reload
        if pending is None:
            return
        gen, params = pending
        with rep._lock:
            behind = (rep.reload_generation < gen
                      and rep.executor is not None)
        if not behind:
            return
        try:
            rep.executor.swap_params(params)
        except Exception as e:  # noqa: BLE001 — keep serving, stay visible
            log(f"serving: replica {rep.index} failed to catch up to "
                f"reload gen {gen}: {type(e).__name__}: {e}")
            return
        with rep._lock:
            if rep.reload_generation < gen:
                rep.reload_generation = gen
        log(f"serving: replica {rep.index} caught up to reload gen {gen}")

    def _note_failure(self, rep: Replica, err: BaseException) -> None:
        """Failure detection: dispatch errors and wedged-submit timeouts
        count toward ``failure_threshold``; past it the replica is DEAD
        (its queue fans out and reroutes)."""
        with rep._lock:
            rep.failures += 1
            kill = rep.failures >= self.failure_threshold
        if kill:
            self._mark_dead(rep, f"{type(err).__name__}: {err}")

    # ---- rolling hot-reload ---------------------------------------------- #
    def rolling_reload(self, new_params,
                       drain_timeout_s: Optional[float] = None) -> int:
        """Drain and swap SERVING replicas one at a time. The sequential
        loop under ``_reload_lock`` IS the invariant: at most one replica
        is ever DRAINING, so fleet capacity never dips by more than one
        and zero requests fail (admitted ones finish before the swap; the
        router already skips the draining replica). Returns how many
        replicas swapped; raises if any swap failed (survivors keep their
        old params — generation skew is visible per-replica in stats)."""
        timeout = float(drain_timeout_s if drain_timeout_s is not None
                        else self.drain_timeout_s)
        with self._reload_lock:
            with self._lock:
                self.reload_generation += 1
                gen = self.reload_generation
                # published BEFORE the loop: any replica that warms from
                # here on catches up itself (see _catch_up_reload)
                self._last_reload = (gen, new_params)
            swapped = 0
            errors: List[str] = []
            for rep in list(self.replicas):
                with rep._lock:
                    eligible = rep.state == SERVING
                if not eligible:
                    continue
                self._transition(rep, DRAINING,
                                 reason=f"rolling reload gen {gen}")
                drained = rep.batcher.wait_idle(timeout_s=timeout)
                if not drained:
                    # a replica that cannot drain is wedged — that is the
                    # failure detector's business, not the reloader's
                    self._transition(rep, SERVING,
                                     reason="drain timeout; swap skipped")
                    errors.append(f"replica {rep.index}: drain timed out "
                                  f"after {timeout}s")
                    continue
                try:
                    rep.executor.swap_params(new_params)
                except Exception as e:  # noqa: BLE001 — keep old params
                    self._transition(rep, SERVING,
                                     reason="swap failed; old params kept")
                    errors.append(f"replica {rep.index}: "
                                  f"{type(e).__name__}: {e}")
                    continue
                with rep._lock:
                    rep.reload_generation = gen
                self._transition(rep, SERVING,
                                 reason=f"reloaded gen {gen}")
                swapped += 1
            if errors:
                raise PartialReloadError(
                    f"rolling reload gen {gen}: {swapped} swapped, "
                    f"{len(errors)} failed: " + "; ".join(errors),
                    swapped=swapped, errors=errors)
            return swapped

    # ---- introspection ---------------------------------------------------- #
    def stats_snapshot(self) -> Dict:
        """Fleet totals + one row per replica (state, queue depth, batch
        fill, sheds, reload generation — which replica is sick is visible,
        not averaged away). Replica rows key by index so the flat metrics
        endpoint renders them as ``replicas.0.queue_depth=...``."""
        rows = {str(rep.index): rep.snapshot() for rep in self.replicas}
        batchers = [rep.batcher for rep in self.replicas
                    if rep.batcher is not None]
        # state_counts takes per-replica locks — outside the manager lock
        # (the transition path holds them in the opposite order)
        states = self.state_counts()
        with self._lock:
            snap = {
                "n_replicas": len(self.replicas),
                "states": states,
                "routing": {
                    "routed": self.routed_total,
                    "failovers": self.failovers,
                    "fleet_sheds": self.fleet_sheds,
                },
                "deaths": self.deaths,
                "reload_generation": self.reload_generation,
                "max_concurrent_draining": self.max_concurrent_draining,
            }
        snap["latency"] = self.latency.summary()           # front door
        snap["replica_latency"] = LatencyWindow.merged_summary(
            [b.latency for b in batchers])                 # pooled replicas
        snap["shed"] = sum(b.shed_count for b in batchers)
        snap["batches"] = sum(b.batches for b in batchers)
        snap["queue_depth"] = sum(b.queue_depth for b in batchers)
        snap["rows_served"] = sum(
            getattr(rep.executor, "rows_served", 0) or 0
            for rep in self.replicas if rep.executor is not None)
        snap["replicas"] = rows
        return snap

    # ---- shutdown --------------------------------------------------------- #
    def shutdown(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Refuse new submissions fleet-wide, then close every replica's
        batcher (with ``drain``, every admitted request completes)."""
        with self._lock:
            self._closing = True
        for rep in self.replicas:
            if rep.batcher is not None:
                rep.batcher.close(drain=drain, timeout_s=timeout_s)

    def close(self) -> None:
        self.shutdown()
