"""Paged KV-cache pool: fixed-size pages from one preallocated device pool.

The serving tier's answer to LLM decode memory (the vLLM discipline,
restated in this repo's AOT idiom): every sequence's KV cache is a list of
fixed-size PAGES drawn from one preallocated per-layer pool, and the
decode step reads them through a page-table indirection
(models/generate.py ``paged_decode_step``). Admitting or retiring a
sequence therefore touches only the host-side free list — the device
arrays never reshape, so every decode-batch rung stays AOT-compiled
forever (the BucketedExecutor lesson applied to caches instead of inputs).

Layout: one pool per layer per K/V, shaped ``(num_pages, n_heads,
page_size, d_head)``. ONE page table per sequence is shared by every
layer — page p means "page p in every layer's pool", so a sequence's
allocation is a single list of page ids. Page 0 is the reserved SCRATCH
page: inactive decode rows point their table at it, making their writes
harmless by construction (no masking inside the compiled step).

Pages are never zeroed on free. A recycled page's stale values are
unreachable: the ragged visibility mask exposes position j only after the
owning sequence has overwritten it — the same argument that makes the
prompt-bucket padding rows inert.

Admission policy: capacity for the WHOLE request (prompt + max_new,
page-aligned) is reserved at admission, so a running sequence can never
hit pool exhaustion mid-flight — no preemption machinery, at the cost of
interior fragmentation the autotuner's page-size knob trades against.

Thread model: one scheduler thread owns alloc/free/write; the lock exists
for the stats readers (``pages_free``/``snapshot`` from server handler
threads) racing those mutations.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PagedKVPool", "PoolExhausted"]


class PoolExhausted(RuntimeError):
    """Not enough free pages for an admission — the scheduler's signal to
    keep the request queued until retirements free capacity."""


class PagedKVPool:
    """Preallocated per-layer K/V page pools + the host-side allocator.

    ``cfg`` is a dense ``TransformerConfig``; ``num_pages`` counts the
    usable pages PLUS the scratch page (page 0); ``max_seq_len`` bounds
    any single sequence (prompt + generated) and fixes the page-table
    width every decode rung compiles against."""

    def __init__(self, cfg, num_pages: int, page_size: int,
                 max_seq_len: Optional[int] = None, device=None,
                 shardings=None):
        import jax
        import jax.numpy as jnp

        if num_pages < 2:
            raise ValueError(f"need >= 2 pages (one is scratch), "
                             f"got {num_pages}")
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        self.cfg = cfg
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        self.max_seq_len = int(max_seq_len or cfg.max_seq)
        # static page-table width: every rung compiles against it
        self.max_pages_per_seq = -(-self.max_seq_len // self.page_size)
        dh = cfg.d_model // cfg.n_heads
        shape = (self.num_pages, cfg.n_heads, self.page_size, dh)

        def alloc_pool():
            z = jnp.zeros(shape, jnp.float32)
            if shardings is not None:
                z = jax.device_put(z, shardings)
            elif device is not None:
                z = jax.device_put(z, device)
            return z

        self.caches: Tuple = tuple((alloc_pool(), alloc_pool())
                                   for _ in range(cfg.n_layers))
        self._lock = threading.Lock()
        # LIFO free list (recently-freed pages are cache-warm); page 0 is
        # the scratch page and never allocated
        self._free: List[int] = list(range(self.num_pages - 1, 0, -1))
        self._seq_pages: Dict[int, List[int]] = {}
        self.allocs = 0
        self.frees = 0
        self.peak_pages_used = 0
        self._scatter = None          # built lazily (jax import at use)

    # ---- capacity ------------------------------------------------------- #
    def pages_for(self, total_len: int) -> int:
        """Pages a sequence of ``total_len`` positions reserves."""
        return -(-int(total_len) // self.page_size)

    @property
    def pages_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def pages_used(self) -> int:
        with self._lock:
            return (self.num_pages - 1) - len(self._free)

    def all_free(self) -> bool:
        """The leak check: after a full drain every page is back."""
        with self._lock:
            return len(self._free) == self.num_pages - 1 \
                and not self._seq_pages

    def can_admit(self, total_len: int) -> bool:
        if total_len > self.max_seq_len:
            raise ValueError(f"sequence of {total_len} positions exceeds "
                             f"pool max_seq_len {self.max_seq_len}")
        with self._lock:
            return self.pages_for(total_len) <= len(self._free)

    # ---- alloc / free --------------------------------------------------- #
    def alloc(self, seq_id: int, total_len: int) -> List[int]:
        """Reserve every page a sequence of ``total_len`` positions will
        ever touch. Raises :class:`PoolExhausted` without allocating
        anything (all-or-nothing, so a failed admission leaks nothing)."""
        n = self.pages_for(total_len)
        with self._lock:
            if seq_id in self._seq_pages:
                raise ValueError(f"seq {seq_id} already holds pages")
            if n > len(self._free):
                raise PoolExhausted(
                    f"need {n} pages, {len(self._free)} free "
                    f"(pool {self.num_pages - 1})")
            pages = [self._free.pop() for _ in range(n)]
            self._seq_pages[seq_id] = pages
            self.allocs += 1
            used = (self.num_pages - 1) - len(self._free)
            self.peak_pages_used = max(self.peak_pages_used, used)
            return list(pages)

    def free(self, seq_id: int) -> int:
        """Retire a sequence: its pages return to the free list
        IMMEDIATELY (no zeroing — see module docstring). Idempotent."""
        with self._lock:
            pages = self._seq_pages.pop(seq_id, None)
            if pages is None:
                return 0
            self._free.extend(pages)
            self.frees += 1
            return len(pages)

    def pages_of(self, seq_id: int) -> List[int]:
        with self._lock:
            return list(self._seq_pages.get(seq_id, ()))

    # ---- page tables ----------------------------------------------------- #
    def table_row(self, seq_id: int) -> np.ndarray:
        """One sequence's page-table row, padded to the static width with
        the scratch page."""
        row = np.zeros((self.max_pages_per_seq,), np.int32)
        pages = self.pages_of(seq_id)
        row[:len(pages)] = pages
        return row

    def table(self, seq_ids: Sequence[Optional[int]]) -> np.ndarray:
        """(R, max_pages) page table for one decode dispatch; ``None``
        entries (inactive padding rows) get the all-scratch row."""
        rows = np.zeros((len(seq_ids), self.max_pages_per_seq), np.int32)
        for i, sid in enumerate(seq_ids):
            if sid is not None:
                rows[i] = self.table_row(sid)
        return rows

    # ---- prefill scatter -------------------------------------------------- #
    def write_prefill(self, seq_id: int, dense_caches) -> None:
        """Scatter a prefill's dense per-layer caches (B=1, shape
        (1, H, T, Dh) with T page-aligned) into the sequence's first
        T/page_size pages — the handoff from the prompt phase (dense,
        flash-attention prefill) to the paged decode phase."""
        import jax
        import jax.numpy as jnp

        t = int(dense_caches[0][0].shape[2])
        if t % self.page_size:
            raise ValueError(f"prefill cache length {t} is not "
                             f"page-aligned (page_size {self.page_size})")
        n = t // self.page_size
        pages = self.pages_of(seq_id)
        if n > len(pages):
            raise ValueError(f"prefill needs {n} pages, seq {seq_id} "
                             f"holds {len(pages)}")
        if self._scatter is None:
            h = self.cfg.n_heads
            dh = self.cfg.d_model // self.cfg.n_heads
            psz = self.page_size

            def scatter(pools, dense, idx):
                out = []
                for (pk, pv), (ck, cv) in zip(pools, dense):
                    npg = ck.shape[2] // psz
                    rk = ck[0].reshape(h, npg, psz, dh).transpose(1, 0, 2, 3)
                    rv = cv[0].reshape(h, npg, psz, dh).transpose(1, 0, 2, 3)
                    out.append((pk.at[idx].set(rk), pv.at[idx].set(rv)))
                return tuple(out)

            # donated pools: the scatter updates in place; shape-keyed jit
            # (one compile per prompt bucket) — serving never re-traces
            self._scatter = jax.jit(scatter, donate_argnums=(0,))
        idx = jnp.asarray(np.asarray(pages[:n], np.int32))
        self.caches = self._scatter(self.caches, dense_caches, idx)

    # ---- introspection ---------------------------------------------------- #
    def snapshot(self) -> Dict:
        with self._lock:
            used = (self.num_pages - 1) - len(self._free)
            return {
                "num_pages": self.num_pages - 1,      # usable (sans scratch)
                "page_size": self.page_size,
                "pages_used": used,
                "pages_free": len(self._free),
                "peak_pages_used": self.peak_pages_used,
                "sequences": len(self._seq_pages),
                "allocs": self.allocs,
                "frees": self.frees,
            }
