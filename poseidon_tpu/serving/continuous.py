"""Iteration-level continuous batching for LLM decode.

The serving economics shift (ROADMAP item 2): a CNN request is one
dispatch, an LLM request is a SEQUENCE of hundreds of decode steps with
wildly varying lengths. Static batching pays the straggler tax — every
admitted batch runs until its LONGEST member finishes while finished rows
ride along as padding and waiting requests queue outside. Continuous
batching re-decides membership every single decode step: finished
sequences retire immediately (their pages return to the
:class:`~poseidon_tpu.serving.kv_pool.PagedKVPool` free list), waiting
sequences admit into the freed rows, and the device never spends a step
on a row nobody needs.

Two phases per sequence, compiled separately (the prefill/decode split):

- **prefill** — the whole prompt in ONE call at a prompt-length bucket
  (flash-attention causal self-attention, O(P) HBM), producing the first
  token's logits and the prompt's K/V, which scatter into the sequence's
  pages;
- **decode** — one token per step for the whole active set at a
  decode-batch RUNG (the smallest compiled batch >= active count), through
  the page-table indirection (``models/generate.py paged_decode_step``).

:class:`ContinuousScheduler` duck-types the :class:`DynamicBatcher`
surface exactly — ``submit`` raising ``ShedError``/``DeadlineError``,
``load_score``/``idle``/``wait_idle``/``close``, the telemetry attrs — so
the fleet's router, failover, rolling reload, and the socket front door
compose UNCHANGED: a replica whose batcher schedules sequences instead of
micro-batches is still just a replica. Failover comes free: a replica
dying mid-generation fans its error to every active sequence's ``submit``,
which re-enters the fleet router and RE-PREFILLS on a survivor.

Per-sequence SLO deadlines ride the batcher deadline machinery: expired in
queue -> ``DeadlineError`` before any compute (the DynamicBatcher
contract); expired mid-generation -> the sequence is cut at the next
iteration boundary (its reply would be late regardless; its pages free
immediately for live sequences).

Thread model: ONE scheduler thread owns the active set, the pool, and the
executor's decode path. Handler threads only touch the bounded queue and
the telemetry counters — both under ``_lock`` (THR004). ``close`` flips
flags under the lock and joins; the loop thread does all cleanup so no
sequence state is ever mutated from two threads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..runtime.metrics import LatencyWindow, log
from ..runtime.tuned_plan import BUILTIN_DEFAULTS as _POLICY_DEFAULTS
from .batcher import DeadlineError, ShedError, ShuttingDownError
from .kv_pool import PagedKVPool, PoolExhausted

__all__ = ["ContinuousScheduler", "GenerateExecutor", "parse_rungs",
           "DEFAULT_PAGE_SIZE", "DEFAULT_DECODE_RUNGS",
           "DEFAULT_PROMPT_BUCKETS"]

DEFAULT_PAGE_SIZE = int(_POLICY_DEFAULTS["llm_page_size"])
DEFAULT_DECODE_RUNGS = tuple(
    int(t) for t in _POLICY_DEFAULTS["llm_decode_rungs"].split(","))
DEFAULT_PROMPT_BUCKETS = tuple(
    int(t) for t in _POLICY_DEFAULTS["llm_prompt_buckets"].split(","))


def parse_rungs(spec: str) -> Tuple[int, ...]:
    """'1,2,4,8' -> (1, 2, 4, 8), validated ascending positives."""
    try:
        rungs = tuple(sorted({int(t) for t in spec.split(",") if t}))
    except ValueError as e:
        raise ValueError(f"bad rung spec {spec!r}: {e}") from None
    if not rungs or rungs[0] < 1:
        raise ValueError(f"bad rung spec {spec!r}: need positive sizes")
    return rungs


def _align(n: int, m: int) -> int:
    return -(-int(n) // int(m)) * int(m)


# Cross-instance AOT compile memo: compiled executables are pure (params
# and caches arrive per call, donation is per-execution), so replicas
# with the same (model config, shape, placement) can share them — an
# N-replica fleet warms ONCE per admissible shape instead of N times.
# Keyed on everything that reaches the lowered program: cfg, page
# geometry, tp layout, and the concrete device/mesh placement (compiled
# executables are device-bound).
_COMPILE_MEMO: Dict[tuple, object] = {}
_COMPILE_MEMO_LOCK = threading.Lock()


# --------------------------------------------------------------------------- #
# the decode engine
# --------------------------------------------------------------------------- #


class GenerateExecutor:
    """AOT-compiled transformer decode over a paged KV pool.

    The LLM sibling of :class:`BucketedExecutor`: every admissible shape —
    each prompt bucket's prefill, each decode rung's step — compiles at
    construction with ``jit(...).lower(avals).compile()``; a request only
    ever pays (pad -> dispatch -> slice). Compiled executables are shared
    across instances through a process-wide memo (same model config,
    shape, and placement -> same executable), so an N-replica fleet warms
    once per admissible shape, not N times. The KV pool lives here (it is
    device state); the :class:`ContinuousScheduler` drives it.

    tp-sharded replicas (``mesh_cfg`` with tp > 1): params convert to the
    Megatron head-major layout (``to_tp_layout``) and land as
    ``NamedSharding`` over the PR-10 named (data, fsdp, tp) mesh per
    ``tp_param_specs``; KV pools shard on the HEAD axis (heads divide tp
    by construction), so each rank holds its own heads' pages and GSPMD
    keeps per-head attention local with one psum per block. A replica
    whose "device" is a mesh composes with fleet routing/failover/reload
    unchanged — the fleet only ever sees ``submit``/``swap_params``.
    """

    input_names = ("prompt",)

    def __init__(self, cfg, params, *,
                 page_size: int = DEFAULT_PAGE_SIZE,
                 decode_rungs: Sequence[int] = DEFAULT_DECODE_RUNGS,
                 prompt_buckets: Sequence[int] = DEFAULT_PROMPT_BUCKETS,
                 max_seq_len: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 default_max_new: int = 32,
                 mesh_cfg=None, device=None, warm: bool = True):
        import jax
        import jax.numpy as jnp

        self.cfg = cfg
        self.page_size = int(page_size)
        self.decode_rungs = tuple(sorted(set(int(r) for r in decode_rungs)))
        self.prompt_buckets = tuple(sorted(set(int(b)
                                               for b in prompt_buckets)))
        if not self.decode_rungs or self.decode_rungs[0] < 1:
            raise ValueError(f"need positive decode rungs, "
                             f"got {decode_rungs!r}")
        if not self.prompt_buckets or self.prompt_buckets[0] < 1:
            raise ValueError(f"need positive prompt buckets, "
                             f"got {prompt_buckets!r}")
        self.default_max_new = int(default_max_new)
        self.max_seq_len = int(max_seq_len or cfg.max_seq)
        if self.max_seq_len > cfg.max_seq:
            raise ValueError(f"max_seq_len {self.max_seq_len} exceeds the "
                             f"model's learned positions {cfg.max_seq}")
        if max(self.prompt_buckets) >= self.max_seq_len:
            raise ValueError(f"largest prompt bucket "
                             f"{max(self.prompt_buckets)} leaves no room "
                             f"to generate within {self.max_seq_len}")

        # ---- placement: one device, or a named mesh ---------------------- #
        self.device = device
        self.mesh = None
        self._tp_layout = False
        pool_shardings = None
        if mesh_cfg is not None and mesh_cfg.active:
            from jax.sharding import NamedSharding, PartitionSpec as P
            from ..models.transformer import to_tp_layout, tp_param_specs
            from ..parallel.spmd import named_mesh
            if device is not None:
                raise ValueError("pass device= or mesh_cfg=, not both")
            if mesh_cfg.tp > 1 and (cfg.n_heads % mesh_cfg.tp
                                    or cfg.d_ff % mesh_cfg.tp):
                raise ValueError(
                    f"n_heads={cfg.n_heads} and d_ff={cfg.d_ff} must both "
                    f"divide tp={mesh_cfg.tp}")
            self.mesh = named_mesh(mesh_cfg)
            self.mesh_cfg = mesh_cfg
            self._tp_layout = mesh_cfg.tp > 1
            if self._tp_layout:
                params_dev = to_tp_layout(
                    jax.tree_util.tree_map(jnp.asarray, params), cfg)
                specs = tp_param_specs(params_dev, tp_axis="tp")
                self._param_shardings = jax.tree_util.tree_map(
                    lambda s: NamedSharding(self.mesh, s), specs,
                    is_leaf=lambda x: isinstance(x, P))
                params_dev = jax.tree_util.tree_map(
                    jax.device_put, params_dev, self._param_shardings)
                pool_shardings = NamedSharding(
                    self.mesh, P(None, "tp", None, None))
            else:
                self._param_shardings = None
                params_dev = jax.tree_util.tree_map(
                    lambda v: jax.device_put(
                        jnp.asarray(v), NamedSharding(self.mesh, P())),
                    params)
        else:
            self.mesh_cfg = None
            self._param_shardings = None
            if device is not None:
                params_dev = jax.device_put(
                    jax.tree_util.tree_map(jnp.asarray, params), device)
            else:
                params_dev = jax.tree_util.tree_map(jnp.asarray, params)
        self._params = params_dev

        # ---- the pool ---------------------------------------------------- #
        pages_per_seq = -(-self.max_seq_len // self.page_size)
        if num_pages is None:
            # every row of the largest rung can hold a max-length sequence
            num_pages = self.decode_rungs[-1] * pages_per_seq + 1
        self.pool = PagedKVPool(cfg, num_pages=num_pages,
                                page_size=self.page_size,
                                max_seq_len=self.max_seq_len,
                                device=device, shardings=pool_shardings)

        self._swap_lock = threading.Lock()
        # make_batcher() reads this so a fleet built from stock Replica
        # plumbing can run the static A/B control arm (bench serving_llm)
        self.scheduler_mode = "continuous"
        self.params_version = 0
        self.rows_served = 0          # tokens delivered to completed rows
        self.prefills = 0
        self.decode_calls: Dict[int, int] = {r: 0 for r in self.decode_rungs}

        # ---- AOT compile every admissible shape -------------------------- #
        self._compiled_prefill: Dict[int, object] = {}
        self._compiled_decode: Dict[int, object] = {}
        if warm:
            self.warm()

    # ---- compile cache ---------------------------------------------------- #
    def _aval(self, shape, dtype, spec=None):
        import jax
        import jax.numpy as jnp
        kw = {}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            kw["sharding"] = NamedSharding(self.mesh, spec or P())
        return jax.ShapeDtypeStruct(tuple(shape), dtype, **kw)

    def warm(self) -> None:
        """AOT-compile prefill at every prompt bucket and decode at every
        rung (construction IS the warm-up, the fleet's WARMING phase)."""
        import contextlib

        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..models.generate import paged_decode_step, prefill_cached

        cfg, tp_layout = self.cfg, self._tp_layout
        params_avals = jax.tree_util.tree_map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                           sharding=v.sharding
                                           if self.mesh is not None
                                           else None),
            self._params)
        ctx = (jax.default_device(self.device) if self.device is not None
               else contextlib.nullcontext())
        head_spec = P(None, "tp", None, None) if tp_layout else P()
        if self.mesh is not None:
            placement = ("mesh", tuple(str(d) for d in
                                       self.mesh.devices.flat),
                         tuple(self.mesh.axis_names),
                         self.mesh.devices.shape)
        else:
            placement = ("dev", str(self.device
                                    if self.device is not None
                                    else jax.devices()[0]))
        base_key = (repr(cfg), self.page_size, tp_layout, placement)
        with ctx:
            for pb in self.prompt_buckets:
                if pb in self._compiled_prefill:
                    continue
                total = _align(pb, self.page_size)

                def pf(p, toks, last_idx, _total=total):
                    return prefill_cached(p, cfg, toks, last_idx, _total,
                                          tp_layout=tp_layout)

                key = base_key + ("prefill", pb, total)
                with _COMPILE_MEMO_LOCK:
                    fn = _COMPILE_MEMO.get(key)
                    if fn is None:
                        fn = jax.jit(pf).lower(
                            params_avals,
                            self._aval((1, pb), jnp.int32),
                            self._aval((1,), jnp.int32)).compile()
                        _COMPILE_MEMO[key] = fn
                self._compiled_prefill[pb] = fn
            cache_shape = tuple(self.pool.caches[0][0].shape)
            cache_aval = tuple(
                (self._aval(cache_shape, jnp.float32, head_spec),) * 2
                for _ in range(cfg.n_layers))
            for r in self.decode_rungs:
                if r in self._compiled_decode:
                    continue

                def dec(p, tok, caches, table, pos):
                    return paged_decode_step(p, cfg, tok, caches, table,
                                             pos, tp_layout=tp_layout)

                key = base_key + ("decode", r, cache_shape,
                                  self.pool.max_pages_per_seq)
                with _COMPILE_MEMO_LOCK:
                    fn = _COMPILE_MEMO.get(key)
                    if fn is None:
                        fn = jax.jit(dec, donate_argnums=(2,)).lower(
                            params_avals,
                            self._aval((r,), jnp.int32),
                            cache_aval,
                            self._aval((r, self.pool.max_pages_per_seq),
                                       jnp.int32),
                            self._aval((r,), jnp.int32)).compile()
                        _COMPILE_MEMO[key] = fn
                self._compiled_decode[r] = fn

    def prompt_bucket_for(self, p: int) -> int:
        for b in self.prompt_buckets:
            if p <= b:
                return b
        raise ValueError(f"prompt of {p} tokens exceeds the largest "
                         f"prompt bucket {self.prompt_buckets[-1]}")

    def rung_for(self, n: int) -> int:
        for r in self.decode_rungs:
            if n <= r:
                return r
        raise ValueError(f"{n} active rows exceed the largest decode "
                         f"rung {self.decode_rungs[-1]}")

    @property
    def max_batch(self) -> int:
        """Largest decode rung — the scheduler's active-set capacity (and
        the fleet router's load_score denominator)."""
        return self.decode_rungs[-1]

    def reserve_len(self, p: int, max_new: int) -> int:
        """Positions a request reserves pages for: the page-aligned
        prefill region and the last generated position, whichever is
        larger (reserve-at-admission — see kv_pool)."""
        return max(_align(self.prompt_bucket_for(p), self.page_size),
                   p + max_new)

    # ---- the two phases --------------------------------------------------- #
    def prefill(self, prompt: np.ndarray) -> np.ndarray:
        """Run one prompt (1-D int32) through the bucketed prefill and
        scatter nothing — returns (logits (V,), dense caches) for the
        scheduler to hand to ``pool.write_prefill``."""
        import jax.numpy as jnp
        p = int(prompt.shape[0])
        bucket = self.prompt_bucket_for(p)
        toks = np.zeros((1, bucket), np.int32)
        toks[0, :p] = np.asarray(prompt, np.int32)
        params = self._params           # one atomic read: swap-safe
        logits, caches = self._compiled_prefill[bucket](
            params, jnp.asarray(toks),
            jnp.asarray([p - 1], jnp.int32))
        self.prefills += 1
        return np.asarray(logits)[0], caches

    def decode(self, tok: np.ndarray, table: np.ndarray,
               pos: np.ndarray) -> np.ndarray:
        """One decode step for a full rung: tok/pos (R,), table
        (R, max_pages). Returns logits (R, V); the pool's caches update
        in place (donated)."""
        import jax.numpy as jnp
        r = int(tok.shape[0])
        if r not in self._compiled_decode:
            raise ValueError(f"no compiled decode rung of size {r} "
                             f"(rungs {self.decode_rungs})")
        params = self._params
        logits, new_caches = self._compiled_decode[r](
            params, jnp.asarray(tok, jnp.int32), self.pool.caches,
            jnp.asarray(table, jnp.int32), jnp.asarray(pos, jnp.int32))
        self.pool.caches = new_caches
        self.decode_calls[r] += 1
        return np.asarray(logits)

    # ---- the fleet hooks --------------------------------------------------- #
    def make_batcher(self, max_delay_s: float = 0.005,
                     max_queue: int = 64) -> "ContinuousScheduler":
        """Replica._attach_batcher's executor-provided batcher: an LLM
        replica schedules sequences, not micro-batches. ``max_delay_s`` is
        accepted for signature compatibility and unused — continuous
        batching re-decides membership every step, so no request ever
        waits for batch company."""
        del max_delay_s
        return ContinuousScheduler(self, max_queue=max_queue,
                                   mode=self.scheduler_mode)

    def swap_params(self, new_params: Dict) -> int:
        """Rolling-reload contract (same as BucketedExecutor): validate
        the incoming STANDARD-layout tree against the serving one, convert
        to this replica's layout/placement, swap atomically. The compiled
        executables are shape-keyed, so a swap never recompiles."""
        import jax
        import jax.numpy as jnp

        new_params = jax.tree_util.tree_map(jnp.asarray, new_params)
        if self._tp_layout:
            from ..models.transformer import to_tp_layout
            new_params = to_tp_layout(new_params, self.cfg)
        cur_leaves, cur_tree = jax.tree_util.tree_flatten(self._params)
        new_leaves, new_tree = jax.tree_util.tree_flatten(new_params)
        if cur_tree != new_tree:
            raise ValueError("params tree structure mismatch: the snapshot "
                             "was taken from a different model")
        for c, n in zip(cur_leaves, new_leaves):
            if c.shape != n.shape or c.dtype != n.dtype:
                raise ValueError(
                    f"params leaf mismatch: {n.shape}/{n.dtype} vs serving "
                    f"{c.shape}/{c.dtype}")
        if self._param_shardings is not None:
            new_params = jax.tree_util.tree_map(
                jax.device_put, new_params, self._param_shardings)
        elif self.device is not None:
            new_params = jax.device_put(new_params, self.device)
        with self._swap_lock:
            self._params = new_params
            self.params_version += 1
            return self.params_version

    def snapshot(self) -> Dict:
        return {
            "page_size": self.page_size,
            "decode_rungs": list(self.decode_rungs),
            "prompt_buckets": list(self.prompt_buckets),
            "prefills": self.prefills,
            "decode_calls": dict(self.decode_calls),
            "pool": self.pool.snapshot(),
            "mesh": (self.mesh_cfg.describe()
                     if self.mesh_cfg is not None else None),
        }


# --------------------------------------------------------------------------- #
# the scheduler
# --------------------------------------------------------------------------- #


class _GenSeq:
    """One in-flight generation request (queued or active)."""
    __slots__ = ("prompt", "max_new", "eos_id", "deadline", "enqueued",
                 "event", "result", "error", "cancelled", "stream",
                 "seq_id", "pos", "next_tok", "out_tokens")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 eos_id: Optional[int], deadline: Optional[float],
                 stream=None):
        self.prompt = prompt
        self.max_new = max_new
        self.eos_id = eos_id
        self.deadline = deadline            # absolute monotonic, or None
        self.enqueued = time.monotonic()
        self.event = threading.Event()
        self.result: Optional[Dict] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False
        self.stream = stream                # optional cumulative-tokens cb
        self.seq_id: Optional[int] = None   # set at admission
        self.pos = 0                        # abs position of next_tok
        self.next_tok = 0                   # last token, not yet fed back
        self.out_tokens: List[int] = []


class ContinuousScheduler:
    """Queue -> admit/retire every decode step -> fan results back out.

    Duck-types :class:`DynamicBatcher` (see module docstring) over a
    :class:`GenerateExecutor`. ``mode="static"`` is the A/B control arm:
    sequences admit only into an EMPTY active set and no admission happens
    until the whole batch drains — classic static batching, stragglers
    and all. Everything else (pool, deadlines, retirement) is identical,
    so the bench's continuous-vs-static delta isolates iteration-level
    scheduling itself."""

    def __init__(self, executor: GenerateExecutor, max_queue: int = 64,
                 mode: str = "continuous"):
        if mode not in ("continuous", "static"):
            raise ValueError(f"mode must be continuous|static, got {mode!r}")
        self.executor = executor
        self.max_queue = int(max_queue)
        self.max_batch = executor.max_batch
        self.mode = mode
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closing = False
        self._drain = True
        self._seq_counter = 0
        self._active: List[_GenSeq] = []    # loop-thread-owned
        self._n_active = 0                  # lock-guarded mirror for stats
        # telemetry (the DynamicBatcher surface the fleet snapshot reads)
        self.latency = LatencyWindow()
        self.shed_count = 0
        self.deadline_expired = 0
        self.batches = 0                    # decode iterations dispatched
        self.batched_rows = 0               # active rows across iterations
        self.admitted = 0
        self.retired = 0
        self._fill_sum = 0.0
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ---- submission side -------------------------------------------------- #
    def validate_request(self, inputs: Dict) -> int:
        """Admission-time validation: reject malformed requests with THEIR
        error before they hold a queue slot."""
        if "prompt" not in inputs:
            raise ValueError("request missing input 'prompt'")
        prompt = np.asarray(inputs["prompt"])
        if prompt.ndim != 1 or prompt.shape[0] < 1:
            raise ValueError(f"prompt must be a non-empty 1-D int array, "
                             f"got shape {prompt.shape}")
        p = int(prompt.shape[0])
        max_new = int(inputs.get("max_new", self.executor.default_max_new))
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        ex = self.executor
        total = ex.reserve_len(p, max_new)      # raises on oversized prompt
        if total > ex.pool.max_seq_len:
            raise ValueError(
                f"prompt {p} + max_new {max_new} exceeds the pool's "
                f"max_seq_len {ex.pool.max_seq_len}")
        if ex.pool.pages_for(total) > ex.pool.num_pages - 1:
            raise ValueError(
                f"request needs {ex.pool.pages_for(total)} pages; the "
                f"whole pool holds {ex.pool.num_pages - 1}")
        return 1

    def submit(self, inputs: Dict, deadline_s: Optional[float] = None,
               timeout_s: float = 30.0) -> Dict:
        """Enqueue one generation request and block until it completes.
        Returns ``{"tokens": (n,) int32, "n_new": n, "prompt_len": p}``.
        Raises ShedError on a full queue, DeadlineError on SLO expiry,
        ValueError on malformed inputs — the DynamicBatcher contract."""
        t0 = time.monotonic()
        self.validate_request(inputs)
        # copy, not asarray: a codec-decoded prompt is a zero-copy VIEW
        # into its receive buffer, and a queued sequence would pin that
        # whole frame for its lifetime — detach it at admission
        prompt = np.array(inputs["prompt"], np.int32)
        max_new = int(inputs.get("max_new", self.executor.default_max_new))
        eos_id = inputs.get("eos_id")
        eos_id = None if eos_id is None else int(eos_id)
        deadline = None if deadline_s is None else t0 + float(deadline_s)
        req = _GenSeq(prompt, max_new, eos_id, deadline,
                      stream=inputs.get("stream"))
        with self._lock:
            if self._closing:
                raise ShuttingDownError("scheduler is shutting down")
            if len(self._q) >= self.max_queue:
                self.shed_count += 1
                raise ShedError(
                    f"queue full ({self.max_queue} requests queued)")
            self._q.append(req)
            self._wake.notify()
        if not req.event.wait(timeout_s):
            with self._lock:
                req.cancelled = True
                try:
                    self._q.remove(req)
                except ValueError:
                    pass                # already admitted; loop skips it
            raise TimeoutError(f"no reply within {timeout_s}s "
                               f"(scheduler wedged?)")
        if req.error is not None:
            raise req.error
        self.latency.record(time.monotonic() - t0)
        return req.result

    # ---- DynamicBatcher surface ------------------------------------------- #
    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def inflight_rows(self) -> int:
        with self._lock:
            return self._n_active

    def load_score(self) -> float:
        with self._lock:
            return len(self._q) + self._n_active / self.max_batch

    def idle(self) -> bool:
        with self._lock:
            return not self._q and self._n_active == 0

    def wait_idle(self, timeout_s: float = 30.0,
                  poll_s: float = 0.005) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.idle():
                return True
            time.sleep(poll_s)
        return self.idle()

    def fill_ratio(self) -> Optional[float]:
        with self._lock:
            if not self.batches:
                return None
            return self._fill_sum / self.batches

    # ---- loop-thread internals -------------------------------------------- #
    def _complete(self, seq: _GenSeq, *, error: Optional[BaseException]
                  = None) -> None:
        """Retire one sequence: free its pages IMMEDIATELY, hand the
        submitter its result/error. Loop-thread only."""
        if seq.seq_id is not None:
            self.executor.pool.free(seq.seq_id)
        with self._lock:
            self.retired += 1
        if error is not None:
            seq.error = error
        else:
            toks = np.asarray(seq.out_tokens, np.int32)
            seq.result = {"tokens": toks, "n_new": int(toks.shape[0]),
                          "prompt_len": int(seq.prompt.shape[0])}
            self.executor.rows_served += int(toks.shape[0])
        seq.event.set()

    def _emit_stream(self, seq: _GenSeq) -> None:
        if seq.stream is None:
            return
        try:
            seq.stream(list(seq.out_tokens))
        except Exception:  # noqa: BLE001 — a broken stream sink must not
            seq.stream = None           # kill the sequence or the loop

    def _try_admit(self) -> bool:
        """Admit queued sequences into free active rows while pages last.
        Returns True if anything was admitted. Loop-thread only."""
        admitted = False
        with self._lock:
            # static mode gang-admits: a batch only FORMS into an empty
            # active set (but fills to the full rung within this round),
            # then runs to completion before the next batch — the honest
            # static-batching baseline, not a serial one
            gang_open = not self._active
        while True:
            with self._lock:
                if not self._q:
                    break
                if self.mode == "static" and not gang_open:
                    break               # a static batch is mid-flight
                if len(self._active) >= self.max_batch:
                    break
                req = self._q[0]
                if req.cancelled:
                    self._q.popleft()
                    continue
                now = time.monotonic()
                if req.deadline is not None and now > req.deadline:
                    self._q.popleft()
                    self.deadline_expired += 1
                    req.error = DeadlineError(
                        f"deadline expired after "
                        f"{now - req.enqueued:.3f}s in queue")
                    req.event.set()
                    continue
                total = self.executor.reserve_len(
                    int(req.prompt.shape[0]), req.max_new)
                if not self.executor.pool.can_admit(total):
                    break               # wait for retirements to free pages
                self._q.popleft()
                self._seq_counter += 1
                req.seq_id = self._seq_counter
            # pool alloc + prefill OUTSIDE the lock (device work)
            try:
                self.executor.pool.alloc(req.seq_id, total)
                logits, caches = self.executor.prefill(req.prompt)
                self.executor.pool.write_prefill(req.seq_id, caches)
            except PoolExhausted as e:
                # raced a stats reader's view; requeue and retry later
                self.executor.pool.free(req.seq_id)
                with self._lock:
                    self._q.appendleft(req)
                log(f"serving: admission raced the pool: {e}")
                break
            except BaseException as e:  # noqa: BLE001 — fan out, reroute
                self._complete(req, error=e)
                continue
            tok0 = int(np.argmax(logits))
            req.out_tokens.append(tok0)
            req.pos = int(req.prompt.shape[0])
            req.next_tok = tok0
            self._emit_stream(req)
            with self._lock:
                self.admitted += 1
            if (req.eos_id is not None and tok0 == req.eos_id) \
                    or req.max_new <= 1:
                self._complete(req)
            else:
                with self._lock:
                    self._active.append(req)
                    self._n_active = len(self._active)
            admitted = True
        return admitted

    def _decode_iteration(self) -> None:
        """One iteration: a single decode step for the whole active set at
        the smallest compiled rung, then per-row retirement. Loop-thread
        only."""
        act = self._active
        rung = self.executor.rung_for(len(act))
        tok = np.zeros((rung,), np.int32)
        pos = np.zeros((rung,), np.int32)
        seq_ids: List[Optional[int]] = [s.seq_id for s in act]
        seq_ids += [None] * (rung - len(act))
        for i, s in enumerate(act):
            tok[i] = s.next_tok
            pos[i] = s.pos
        table = self.executor.pool.table(seq_ids)
        try:
            logits = self.executor.decode(tok, table, pos)
        except BaseException as e:  # noqa: BLE001 — replica failure: fan
            # the error to every active sequence; each submit re-enters
            # the fleet router and re-prefills on a survivor
            for s in act:
                self._complete(s, error=e)
            with self._lock:
                self._active = []
                self._n_active = 0
            return
        with self._lock:
            self.batches += 1
            self.batched_rows += len(act)
            self._fill_sum += len(act) / rung
        now = time.monotonic()
        still: List[_GenSeq] = []
        for i, s in enumerate(act):
            new_tok = int(np.argmax(logits[i]))
            s.out_tokens.append(new_tok)
            s.pos += 1
            s.next_tok = new_tok
            self._emit_stream(s)
            if s.cancelled:
                self._complete(s, error=RuntimeError("cancelled"))
                continue
            done = (s.eos_id is not None and new_tok == s.eos_id) \
                or len(s.out_tokens) >= s.max_new
            if done:
                self._complete(s)
            elif s.deadline is not None and now > s.deadline:
                with self._lock:
                    self.deadline_expired += 1
                self._complete(s, error=DeadlineError(
                    f"SLO deadline expired mid-generation after "
                    f"{len(s.out_tokens)} tokens"))
            else:
                still.append(s)
        with self._lock:
            self._active = still
            self._n_active = len(still)

    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._q and not self._active and not self._closing:
                    self._wake.wait(timeout=0.25)
                closing, drain = self._closing, self._drain
                empty = not self._q and not self._active
            if closing and empty:
                return
            if closing and not drain:
                # complete leftovers (queued AND mid-generation) with the
                # typed shutdown shed so fleet submits reroute, free pages
                with self._lock:
                    leftovers = list(self._q)
                    self._q.clear()
                    act, self._active = self._active, []
                    self._n_active = 0
                for s in leftovers + act:
                    self._complete(s, error=ShuttingDownError(
                        "server shut down before completion"))
                return
            self._try_admit()
            if self._active:
                self._decode_iteration()

    # ---- shutdown ---------------------------------------------------------- #
    def close(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Refuse new submissions; with ``drain`` finish everything
        admitted AND queued, else complete leftovers with the shutdown
        shed. Idempotent."""
        with self._lock:
            self._closing = True
            self._drain = drain
            self._wake.notify_all()
        self._thread.join(timeout=timeout_s)

    def snapshot(self) -> Dict:
        with self._lock:
            snap = {
                "mode": self.mode,
                "queue_depth": len(self._q),
                "active": self._n_active,
                "admitted": self.admitted,
                "retired": self.retired,
                "batches": self.batches,
                "batched_rows": self.batched_rows,
                "shed": self.shed_count,
                "deadline_expired": self.deadline_expired,
            }
        snap["fill"] = self.fill_ratio()
        snap["latency"] = self.latency.summary()
        snap["executor"] = self.executor.snapshot()
        return snap
