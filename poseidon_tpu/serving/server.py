"""Threaded socket front-end for the serving tier.

Reuses the proto/wire.py length-prefixed framing and the malformed-frame
containment pattern from the async-SSP ParamService: a corrupt peer (torn
frame, garbage header, undecodable payload) gets ITS connection logged and
dropped; everyone else keeps being served. The accept loop and per-request
handling are thread-per-connection — request concurrency is what feeds the
micro-batcher.

Request protocol (pickled dicts, one frame per message):

- ``{"kind": "infer", "inputs": {name: ndarray}, "deadline_ms": float?}``
  -> ``{"ok": True, "outputs": {...}}`` on success;
  -> ``{"ok": False, "shed": True, "error": ...}`` under backpressure
  (bounded queue full, or shutting down) — explicit, immediate;
  -> ``{"ok": False, "deadline_exceeded": True, "error": ...}`` when the
  per-request deadline expired in queue;
  -> ``{"ok": False, "error": ...}`` on malformed inputs.
- ``{"kind": "generate", "inputs": {"prompt": 1-D int array, "max_new":
  int?, "eos_id": int?}, "deadline_ms": float?, "stream": bool?}`` — LLM
  decode through the continuous-batching scheduler (serving/continuous.py).
  Same reply shapes as ``infer`` (``outputs`` = tokens/n_new/prompt_len);
  with ``stream`` the reply frame is preceded by zero or more
  ``{"kind": "gen_chunk", "tokens": [...]}`` frames carrying the
  CUMULATIVE generated tokens (cumulative so a reconnect-resend or a
  failover re-prefill restarts the stream without loss).
- ``{"kind": "stats"}`` -> latency percentiles, queue depth, batch-fill
  ratio, shed count, reload count (the `/stats`-style introspection op).
- ``{"kind": "reload"}`` -> force one hot-reload poll now (when a
  reloader is attached); returns what it found.
- ``{"kind": "health"}`` -> ``{"ok": True, "draining": bool}``.
- ``{"kind": "bye"}`` -> close this connection.

Shutdown (the SIGTERM/SIGINT path): ``shutdown()`` stops accepting new
connections, lets the batcher drain every admitted request, answers the
in-flight replies, then closes. No admitted request is silently dropped.
"""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..proto.wire import (WIRE_CODEC_VERSION, FrameError, mark_codec_socket,
                          recv_frame, send_frame, wire_codec_enabled)
from ..runtime.metrics import StatsRegistry, log
from .batcher import DeadlineError, DynamicBatcher, ShedError

__all__ = ["InferenceServer"]


class InferenceServer:
    """Serve a :class:`BucketedExecutor` — or a whole
    :class:`~poseidon_tpu.serving.fleet.ReplicaManager` — over TCP
    (port 0 = ephemeral).

    Exactly one of ``executor`` / ``fleet`` must be given. The executor
    form is the PR-2 single-engine path (one private micro-batcher built
    from ``max_delay_s``/``max_queue``); the fleet form routes every
    request through the manager's least-loaded router instead — there the
    batching/admission knobs live on each REPLICA's batcher (configured
    when the fleet was built) and this constructor's ``max_delay_s``/
    ``max_queue`` are unused — and the `stats` op becomes the fleet
    health surface (per-replica rows). ``stats_refresh_s > 0`` refreshes
    the StatsRegistry "serving" section on a timer so a live metrics
    endpoint shows health without anyone calling the stats op."""

    def __init__(self, executor=None, host: str = "127.0.0.1", port: int = 0,
                 max_delay_s: float = 0.005, max_queue: int = 64,
                 default_deadline_s: Optional[float] = None,
                 reloader=None, stats: Optional[StatsRegistry] = None,
                 fleet=None, stats_refresh_s: float = 0.0):
        if (executor is None) == (fleet is None):
            raise ValueError("pass exactly one of executor= or fleet=")
        self.executor = executor
        self.fleet = fleet
        self.reloader = reloader
        self.stats = stats or StatsRegistry()
        self.default_deadline_s = default_deadline_s
        # an executor that brings its own scheduler (GenerateExecutor ->
        # ContinuousScheduler) plugs in here, same hook as
        # fleet.Replica._attach_batcher
        mk = (getattr(executor, "make_batcher", None)
              if executor is not None else None)
        self.batcher = (None if fleet is not None else
                        mk(max_delay_s=max_delay_s, max_queue=max_queue)
                        if mk is not None else
                        DynamicBatcher(executor, max_delay_s=max_delay_s,
                                       max_queue=max_queue))
        self.bad_frames = 0
        self.server_errors = 0
        self.connections = 0
        self._active_replies = 0   # requests received, reply not yet sent
        self.draining = False
        self._stop = threading.Event()
        self._done = threading.Event()     # fully shut down
        self._shutting_down = False
        self._lock = threading.Lock()
        self._srv = socket.create_server((host, port))
        self.host = host
        self.port = self._srv.getsockname()[1]
        self.addr = (host, self.port)
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        self._started = time.time()
        self._stats_refresh_s = float(stats_refresh_s)
        if self._stats_refresh_s > 0:
            threading.Thread(target=self._stats_refresh_loop,
                             daemon=True).start()

    def _stats_refresh_loop(self) -> None:
        """Keep the StatsRegistry "serving" section current for the live
        metrics endpoint — fleet health must be visible without a client
        calling the stats op."""
        while not self._stop.wait(self._stats_refresh_s):
            try:
                self.stats_snapshot()
            except Exception:  # noqa: BLE001 — telemetry never kills serving
                pass

    # ---- accept/handle --------------------------------------------------- #
    def _accept_loop(self) -> None:
        self._srv.settimeout(0.25)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                self.connections += 1
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while not self._done.is_set():
                try:
                    msg = recv_frame(conn)
                except FrameError as e:
                    # containment: a corrupt peer loses ITS connection; the
                    # server keeps serving everyone else
                    with self._lock:
                        self.bad_frames += 1
                    log(f"serving: dropping connection on bad frame: {e}")
                    return
                except (ConnectionError, EOFError, OSError):
                    return
                # a received request is owed a reply: the counter keeps
                # shutdown() from declaring the server down between a
                # drained batch completing and its replies hitting the wire
                with self._lock:
                    self._active_replies += 1
                try:
                    try:
                        reply = self._dispatch(msg, conn)
                    except (ConnectionError, OSError):
                        return
                    except (KeyError, TypeError, ValueError) as e:
                        # bad request SHAPE (missing kind/fields, wrong
                        # types): same containment as a torn frame, but the
                        # channel is intact — tell the client
                        with self._lock:
                            self.bad_frames += 1
                        reply = {"ok": False,
                                 "error": f"{type(e).__name__}: {e}"}
                    except Exception as e:  # noqa: BLE001 — OUR failure
                        # server-side failure (executor/XLA/reloader): never
                        # billed to the client as a bad frame
                        with self._lock:
                            self.server_errors += 1
                        log(f"serving: internal error: "
                            f"{type(e).__name__}: {e}")
                        reply = {"ok": False, "server_error": True,
                                 "error": f"{type(e).__name__}: {e}"}
                    if reply is None:       # bye
                        return
                    try:
                        send_frame(conn, reply)
                    except (ConnectionError, OSError):
                        return
                finally:
                    with self._lock:
                        self._active_replies -= 1
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg: Dict, conn=None) -> Optional[Dict]:
        kind = msg["kind"]
        if kind == "wire":
            # binary tensor codec negotiation: affirm iff the client
            # speaks exactly our version and the codec is enabled; the
            # infer/generate tensor payloads on this connection then skip
            # pickle entirely. An old client never sends this kind; an
            # old server answers it {"ok": False, "error": ...} through
            # the unknown-kind path — the client stays on pickle.
            ok = bool(wire_codec_enabled()
                      and msg.get("codec") == WIRE_CODEC_VERSION)
            if ok and conn is not None:
                mark_codec_socket(conn)
            return {"ok": ok, "codec": WIRE_CODEC_VERSION}
        if kind == "infer":
            return self._handle_infer(msg)
        if kind == "generate":
            return self._handle_generate(msg, conn)
        if kind == "stats":
            return {"ok": True, "stats": self.stats_snapshot()}
        if kind == "health":
            if self.fleet is not None:
                return {"ok": True, "draining": self.draining,
                        "states": self.fleet.state_counts(),
                        "reload_generation": self.fleet.reload_generation}
            return {"ok": True, "draining": self.draining,
                    "params_version": self.executor.params_version}
        if kind == "reload":
            if self.reloader is None:
                return {"ok": False, "error": "no reloader attached"}
            reloaded = self.reloader.check_now()
            reply = {"ok": True, "reloaded": reloaded,
                     "path": self.reloader.current_path,
                     "last_error": self.reloader.last_error}
            if self.fleet is not None:
                reply["reload_generation"] = self.fleet.reload_generation
            else:
                reply["params_version"] = self.executor.params_version
            return reply
        if kind == "bye":
            return None
        raise ValueError(f"unknown request kind {kind!r}")

    def _handle_infer(self, msg: Dict) -> Dict:
        deadline_ms = msg.get("deadline_ms")
        deadline_s = (float(deadline_ms) / 1e3 if deadline_ms is not None
                      else self.default_deadline_s)
        try:
            if self.fleet is not None:
                outputs, rep = self.fleet.submit(msg["inputs"],
                                                 deadline_s=deadline_s)
                return {"ok": True, "outputs": outputs,
                        "replica": rep.index,
                        "params_version": rep.executor.params_version}
            outputs = self.batcher.submit(msg["inputs"],
                                          deadline_s=deadline_s)
            return {"ok": True, "outputs": outputs,
                    "params_version": self.executor.params_version}
        except ShedError as e:
            return {"ok": False, "shed": True, "error": str(e)}
        except DeadlineError as e:
            return {"ok": False, "deadline_exceeded": True, "error": str(e)}
        except (ValueError, TimeoutError) as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def _handle_generate(self, msg: Dict, conn=None) -> Dict:
        """LLM decode: same admission/deadline error surface as ``infer``;
        the batcher behind it is a ContinuousScheduler, so the request is
        a SEQUENCE (admitted/retired per decode step), not a dispatch.

        Streaming rides the scheduler's per-token callback: each chunk
        frame carries the cumulative tokens so far, written from the
        scheduler thread while this handler thread blocks in submit (the
        final reply only goes out after the last chunk). A broken chunk
        send kills the stream, never the sequence or the loop."""
        deadline_ms = msg.get("deadline_ms")
        deadline_s = (float(deadline_ms) / 1e3 if deadline_ms is not None
                      else self.default_deadline_s)
        inputs = dict(msg["inputs"])
        if msg.get("stream") and conn is not None:
            def emit(tokens, _conn=conn):
                # int32 buffer, not a list of ints: on a codec-negotiated
                # connection the cumulative token chunk travels as one
                # raw tensor buffer (the client converts back to ints)
                send_frame(_conn, {"kind": "gen_chunk",
                                   "tokens": np.asarray(tokens, np.int32)})
            inputs["stream"] = emit
        try:
            if self.fleet is not None:
                outputs, rep = self.fleet.submit(inputs,
                                                 deadline_s=deadline_s)
                return {"ok": True, "outputs": outputs,
                        "replica": rep.index,
                        "params_version": rep.executor.params_version}
            outputs = self.batcher.submit(inputs, deadline_s=deadline_s)
            return {"ok": True, "outputs": outputs,
                    "params_version": self.executor.params_version}
        except ShedError as e:
            return {"ok": False, "shed": True, "error": str(e)}
        except DeadlineError as e:
            return {"ok": False, "deadline_exceeded": True, "error": str(e)}
        except (ValueError, TimeoutError) as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # ---- introspection ---------------------------------------------------- #
    def stats_snapshot(self) -> Dict:
        """The `/stats` payload: p50/p99 request latency, queue depth,
        batch-fill ratio, shed count — registered as a StatsRegistry
        section too, so a run-level stats.yaml dump carries it. With a
        fleet, the payload is the manager's aggregate plus one row per
        replica (state, queue depth, batch fill, sheds, reload
        generation) — the fleet health surface."""
        if self.fleet is not None:
            snap = self.fleet.stats_snapshot()
            snap.update({
                "bad_frames": self.bad_frames,
                "server_errors": self.server_errors,
                "connections": self.connections,
                "uptime_s": round(time.time() - self._started, 3),
                "draining": self.draining,
                "reloads": (0 if self.reloader is None
                            else self.reloader.reloads),
                "reloader": (None if self.reloader is None else {
                    "reloads": self.reloader.reloads,
                    "failed_reloads": self.reloader.failed_reloads,
                    "last_error": self.reloader.last_error,
                    "current_path": self.reloader.current_path,
                }),
            })
            self.stats.set_section("serving", snap)
            return snap
        b = self.batcher
        fill = b.fill_ratio()
        snap = {
            "latency": b.latency.summary(),
            "queue_depth": b.queue_depth,
            "max_queue": b.max_queue,
            "batches": b.batches,
            "batched_rows": b.batched_rows,
            "batch_fill": None if fill is None else round(fill, 4),
            "shed": b.shed_count,
            "deadline_expired": b.deadline_expired,
            "bad_frames": self.bad_frames,
            "server_errors": self.server_errors,
            "connections": self.connections,
            "rows_served": self.executor.rows_served,
            # CNN-executor-only telemetry; a GenerateExecutor reports its
            # paged/decode counters through the batcher snapshot instead
            "rows_padded": getattr(self.executor, "rows_padded", 0),
            "bucket_calls": dict(getattr(self.executor, "calls", {})),
            # per-rung fill: which compile slots dispatch real rows vs
            # padding (capacity signal for re-cutting the bucket ladder);
            # getattr: duck-typed test executors need not implement it
            "executor_bucket_fill": getattr(self.executor, "bucket_fill",
                                            lambda: None)(),
            "params_version": self.executor.params_version,
            "reloads": (0 if self.reloader is None
                        else self.reloader.reloads),
            # the reloader's full swap telemetry (hot-reload health must
            # be visible from the stats op, not only the server log)
            "reloader": (None if self.reloader is None else {
                "reloads": self.reloader.reloads,
                "failed_reloads": self.reloader.failed_reloads,
                "last_error": self.reloader.last_error,
                "current_path": self.reloader.current_path,
            }),
            "uptime_s": round(time.time() - self._started, 3),
            "draining": self.draining,
        }
        self.stats.set_section("serving", snap)
        return snap

    # ---- shutdown --------------------------------------------------------- #
    def request_stop(self) -> None:
        """Async-signal-safe stop request: flip the flags only (a signal
        handler must not join threads). The thread blocked in
        ``wait_until_stopped`` then runs the actual ``shutdown``."""
        self.draining = True
        self._stop.set()

    def wait_until_stopped(self, poll_s: float = 0.25) -> None:
        while not self._stop.wait(poll_s):
            pass

    def shutdown(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Graceful stop: refuse new connections, drain the admitted
        queue (every in-flight request gets its reply), then close.
        Idempotent; safe to call after ``request_stop``."""
        with self._lock:
            already = self._shutting_down
            self._shutting_down = True
        if already:
            self._done.wait(timeout=timeout_s)
            return
        self.draining = True
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        if self.reloader is not None:
            self.reloader.close()
        # drain: every admitted request completes and its handler thread
        # writes the reply before we declare the server down
        if self.fleet is not None:
            self.fleet.shutdown(drain=drain, timeout_s=timeout_s)
        else:
            self.batcher.close(drain=drain, timeout_s=timeout_s)
        # the batcher completing a request only SETS its event; the handler
        # thread still has to wake and write the reply frame — wait for
        # every received-but-unreplied request to hit the wire, or the
        # process exit right after shutdown() would kill the daemon
        # handlers mid-reply (a silently dropped request)
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if self._active_replies <= 0:
                    break
            time.sleep(0.005)
        self._done.set()

    def close(self) -> None:
        self.shutdown()
