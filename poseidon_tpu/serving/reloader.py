"""Checkpoint hot-reload: watch the snapshot directory, swap params live.

The training side writes ``<prefix>_iter_N.solverstate.npz`` atomically
(tmp + rename, runtime/checkpoint.snapshot); discovery reuses
``runtime/ckpt_files.latest_snapshot``, whose suffix match ignores the
``.tmp.<pid>`` litter a killed writer leaves behind — a path this reloader
sees is by construction a COMPLETE rename-landed artifact. Torn or
incompatible files are still handled: a failed load is logged, counted,
and the server keeps serving the previous params (serving availability
never depends on the health of the newest checkpoint).

The load runs on this reloader's own thread — never a request thread —
and the handoff is ``executor.swap_params``: one atomic reference swap,
validated against the serving tree. In-flight requests that already
grabbed the old params finish on them; no request is dropped or errored
by a reload (pinned by tests/test_serving.py::test_hot_reload_mid_stream).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..runtime.ckpt_files import latest_snapshot
from ..runtime.metrics import log
from .executor import load_serving_params

__all__ = ["CheckpointReloader", "FleetReloader"]


class CheckpointReloader:
    """Poll ``prefix`` for a newer solverstate and hot-swap the executor.

    ``prefix`` is the snapshot prefix exactly as the solver writes it
    (e.g. ``out/snap/lenet``); ``poll_s`` is the watch cadence. Starts its
    thread on construction; ``check_now()`` forces one poll synchronously
    (the server's ``reload`` op and the tests use it — determinism beats
    sleeping on the poll period)."""

    def __init__(self, executor, prefix: str, poll_s: float = 1.0,
                 start: bool = True, current_path: Optional[str] = None):
        """``current_path`` seeds the already-serving snapshot (the one
        --weights loaded): the first poll then only swaps to something
        strictly NEWER, instead of redundantly re-loading the snapshot
        already serving (or regressing to an older one)."""
        self.executor = executor
        self.prefix = prefix
        self.poll_s = float(poll_s)
        self.current_path = current_path
        self.reloads = 0
        self.failed_reloads = 0
        self.last_error: Optional[str] = None
        self._lock = threading.Lock()     # one load at a time
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._watch_loop,
                                            daemon=True)
            self._thread.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_now()
            except Exception as e:  # noqa: BLE001 — the watcher must survive
                # discovery itself failed (unreadable watch dir, NFS
                # outage): as loud as a failed load, or hot-reload dies
                # silently while the operator believes it is live.
                # Counter + last_error under the SAME lock check_now's
                # load-failure path uses — the unlocked twin of a locked
                # mutation loses increments (THR006)
                err = f"{type(e).__name__}: {e}"
                with self._lock:
                    changed = err != self.last_error
                    self.last_error = err
                    self.failed_reloads += 1
                if changed:
                    log(f"serving: snapshot watch on {self.prefix!r} "
                        f"failing: {err}")

    def check_now(self) -> bool:
        """One poll: if a snapshot newer than the one serving exists, load
        it off-thread and swap. Returns True iff a swap happened."""
        with self._lock:
            path = latest_snapshot(self.prefix)
            if path is None or path == self.current_path:
                return False
            if self.current_path is not None and \
                    self._iter_of(path) <= self._iter_of(self.current_path):
                return False
            try:
                params = load_serving_params(self.executor.net,
                                             self.executor._params, path)
                version = self.executor.swap_params(params)
            except Exception as e:  # noqa: BLE001 — keep serving old params
                self.failed_reloads += 1
                self.last_error = f"{type(e).__name__}: {e}"
                log(f"serving: reload of {os.path.basename(path)} failed "
                    f"({self.last_error}); keeping previous params")
                return False
            self.current_path = path
            self.reloads += 1
            self.last_error = None
            log(f"serving: hot-reloaded {os.path.basename(path)} "
                f"(params version {version})")
            return True

    @staticmethod
    def _iter_of(path: str) -> int:
        name = os.path.basename(path)
        try:
            return int(name.split("_iter_")[-1].split(".")[0])
        except ValueError:
            return -1

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)


class FleetReloader(CheckpointReloader):
    """The single-executor reloader generalized to drive a fleet: one
    snapshot discovery, one load, then :meth:`ReplicaManager.rolling_reload`
    drains and swaps replicas one at a time (never more than one draining;
    zero requests dropped or errored across a full fleet reload).

    Same watch loop, discovery rules (tmp-litter-proof, strictly-newer
    only), failure accounting, and server `reload`-op surface as the
    parent — ``reloads`` counts completed FLEET rolls; per-replica
    generations are in the manager's stats rows."""

    def __init__(self, manager, prefix: str, poll_s: float = 1.0,
                 start: bool = True, current_path: Optional[str] = None,
                 drain_timeout_s: Optional[float] = None):
        self.manager = manager
        self.drain_timeout_s = drain_timeout_s
        super().__init__(executor=None, prefix=prefix, poll_s=poll_s,
                         start=start, current_path=current_path)

    def check_now(self) -> bool:
        """One poll: if a strictly newer snapshot exists, load it ONCE
        (against the fleet's reference replica) and roll it through every
        serving replica. True iff a fleet roll completed cleanly."""
        with self._lock:
            path = latest_snapshot(self.prefix)
            if path is None or path == self.current_path:
                return False
            if self.current_path is not None and \
                    self._iter_of(path) <= self._iter_of(self.current_path):
                return False
            from .fleet import PartialReloadError
            try:
                ref = self.manager.reference_executor()
                params = load_serving_params(ref.net, ref._params, path)
                swapped = self.manager.rolling_reload(
                    params, drain_timeout_s=self.drain_timeout_s)
            except PartialReloadError as e:
                # the roll RAN: some replicas landed, the rest refused or
                # could not drain. Advance current_path anyway — retrying
                # every poll would re-drain the healthy replicas (capacity
                # dips) and stall drain_timeout_s per pass on the sick one
                # forever. The skew is visible per-replica in stats; the
                # next strictly-newer snapshot rolls again.
                self.current_path = path
                self.failed_reloads += 1
                self.last_error = f"{type(e).__name__}: {e}"
                log(f"serving: fleet reload of {os.path.basename(path)} "
                    f"partially landed ({e.swapped} swapped, "
                    f"{len(e.errors)} failed); not re-rolling until a "
                    f"newer snapshot appears")
                return False
            except Exception as e:  # noqa: BLE001 — keep serving old params
                # the LOAD failed (torn/incompatible snapshot): nothing
                # was drained or swapped, so retrying next poll is free —
                # the single-executor reloader's existing behavior
                self.failed_reloads += 1
                self.last_error = f"{type(e).__name__}: {e}"
                log(f"serving: fleet reload of {os.path.basename(path)} "
                    f"failed ({self.last_error}); replicas keep their "
                    f"current params")
                return False
            self.current_path = path
            self.reloads += 1
            self.last_error = None
            log(f"serving: fleet hot-reloaded {os.path.basename(path)} "
                f"({swapped} replicas, one drain at a time)")
            return True
