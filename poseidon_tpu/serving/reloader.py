"""Checkpoint hot-reload: watch the snapshot directory, swap params live.

The training side writes ``<prefix>_iter_N.solverstate.npz`` atomically
(tmp + rename, runtime/checkpoint.snapshot); discovery reuses
``runtime/ckpt_files.latest_snapshot``, whose suffix match ignores the
``.tmp.<pid>`` litter a killed writer leaves behind — a path this reloader
sees is by construction a COMPLETE rename-landed artifact. Torn or
incompatible files are still handled: a failed load is logged, counted,
and the server keeps serving the previous params (serving availability
never depends on the health of the newest checkpoint).

The load runs on this reloader's own thread — never a request thread —
and the handoff is ``executor.swap_params``: one atomic reference swap,
validated against the serving tree. In-flight requests that already
grabbed the old params finish on them; no request is dropped or errored
by a reload (pinned by tests/test_serving.py::test_hot_reload_mid_stream).
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from ..runtime.ckpt_files import latest_snapshot
from ..runtime.metrics import log
from .executor import load_serving_params

__all__ = ["CheckpointReloader"]


class CheckpointReloader:
    """Poll ``prefix`` for a newer solverstate and hot-swap the executor.

    ``prefix`` is the snapshot prefix exactly as the solver writes it
    (e.g. ``out/snap/lenet``); ``poll_s`` is the watch cadence. Starts its
    thread on construction; ``check_now()`` forces one poll synchronously
    (the server's ``reload`` op and the tests use it — determinism beats
    sleeping on the poll period)."""

    def __init__(self, executor, prefix: str, poll_s: float = 1.0,
                 start: bool = True, current_path: Optional[str] = None):
        """``current_path`` seeds the already-serving snapshot (the one
        --weights loaded): the first poll then only swaps to something
        strictly NEWER, instead of redundantly re-loading the snapshot
        already serving (or regressing to an older one)."""
        self.executor = executor
        self.prefix = prefix
        self.poll_s = float(poll_s)
        self.current_path = current_path
        self.reloads = 0
        self.failed_reloads = 0
        self.last_error: Optional[str] = None
        self._lock = threading.Lock()     # one load at a time
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(target=self._watch_loop,
                                            daemon=True)
            self._thread.start()

    def _watch_loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check_now()
            except Exception as e:  # noqa: BLE001 — the watcher must survive
                # discovery itself failed (unreadable watch dir, NFS
                # outage): as loud as a failed load, or hot-reload dies
                # silently while the operator believes it is live.
                # Counter + last_error under the SAME lock check_now's
                # load-failure path uses — the unlocked twin of a locked
                # mutation loses increments (THR006)
                err = f"{type(e).__name__}: {e}"
                with self._lock:
                    changed = err != self.last_error
                    self.last_error = err
                    self.failed_reloads += 1
                if changed:
                    log(f"serving: snapshot watch on {self.prefix!r} "
                        f"failing: {err}")

    def check_now(self) -> bool:
        """One poll: if a snapshot newer than the one serving exists, load
        it off-thread and swap. Returns True iff a swap happened."""
        with self._lock:
            path = latest_snapshot(self.prefix)
            if path is None or path == self.current_path:
                return False
            if self.current_path is not None and \
                    self._iter_of(path) <= self._iter_of(self.current_path):
                return False
            try:
                params = load_serving_params(self.executor.net,
                                             self.executor._params, path)
                version = self.executor.swap_params(params)
            except Exception as e:  # noqa: BLE001 — keep serving old params
                self.failed_reloads += 1
                self.last_error = f"{type(e).__name__}: {e}"
                log(f"serving: reload of {os.path.basename(path)} failed "
                    f"({self.last_error}); keeping previous params")
                return False
            self.current_path = path
            self.reloads += 1
            self.last_error = None
            log(f"serving: hot-reloaded {os.path.basename(path)} "
                f"(params version {version})")
            return True

    @staticmethod
    def _iter_of(path: str) -> int:
        name = os.path.basename(path)
        try:
            return int(name.split("_iter_")[-1].split(".")[0])
        except ValueError:
            return -1

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
