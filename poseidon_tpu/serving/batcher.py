"""Dynamic micro-batcher: flush on max-batch-size OR max-latency-deadline.

The serving analog of the data tier's prefetch pipeline, inverted: requests
arrive one at a time over sockets, the accelerator wants them in bucket-
sized batches. One flush thread owns the executor; handler threads enqueue
and block on their request's event.

Flush policy (whichever fires first):
- SIZE: queued rows reach the largest executor bucket (a full batch gains
  nothing by waiting);
- DEADLINE: the OLDEST queued request has waited ``max_delay_s`` (bounded
  queueing latency — a lone request never waits for company longer than
  the deadline).

Backpressure contract (bounded queue, explicit shed): ``submit`` on a full
queue raises :class:`ShedError` IMMEDIATELY — the caller gets an explicit
shed response, never a hang and never unbounded memory. A request whose
per-request deadline expires while queued is completed with
:class:`DeadlineError` instead of being dispatched (its reply would be
garbage to a timed-out client; spending a bucket slot on it would delay
live requests behind it).

Shutdown: ``close(drain=True)`` refuses new submissions, flushes everything
already admitted, then joins the flush thread — the graceful half of the
server's SIGTERM path. No admitted request is ever silently dropped: even
on ``drain=False`` the leftovers are completed with a shutdown error.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from ..runtime.metrics import LatencyWindow

__all__ = ["DynamicBatcher", "ShedError", "ShuttingDownError",
           "DeadlineError"]


class ShedError(RuntimeError):
    """Admission refused: the bounded queue is full (backpressure)."""


class ShuttingDownError(ShedError):
    """Admission refused because the batcher is closing — the TYPED
    marker the fleet router needs to tell a shutdown shed (surface it)
    from a queue-full shed (try another replica) without matching on
    message text."""


class DeadlineError(RuntimeError):
    """The request's deadline expired before it could be dispatched."""


class _Pending:
    __slots__ = ("inputs", "rows", "deadline", "enqueued", "event",
                 "result", "error", "cancelled")

    def __init__(self, inputs: Dict[str, np.ndarray], rows: int,
                 deadline: Optional[float]):
        self.inputs = inputs
        self.rows = rows
        self.deadline = deadline          # absolute monotonic, or None
        self.enqueued = time.monotonic()
        self.event = threading.Event()
        self.result: Optional[Dict[str, np.ndarray]] = None
        self.error: Optional[BaseException] = None
        self.cancelled = False            # submitter gave up (wait timeout)


class DynamicBatcher:
    """Queue -> micro-batch -> executor -> fan the rows back out.

    ``executor`` needs ``infer(inputs) -> outputs``, ``max_batch``, and
    ``input_names`` (duck-typed; tests drive it with fakes). ``max_queue``
    bounds ADMITTED-but-unflushed requests (admission control);
    ``max_delay_s`` bounds how long a queued request waits for batch
    company."""

    def __init__(self, executor, max_delay_s: float = 0.005,
                 max_queue: int = 64,
                 max_batch: Optional[int] = None):
        self.executor = executor
        self.max_delay_s = float(max_delay_s)
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch or executor.max_batch)
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._closing = False
        self._drain = True
        # telemetry (the /stats payload's batcher half)
        self.latency = LatencyWindow()     # submit -> reply, seconds
        self.shed_count = 0
        self.deadline_expired = 0
        self.batches = 0
        self.batched_rows = 0
        self._fill_sum = 0.0               # sum of rows/max_batch per flush
        self._inflight_rows = 0            # rows in the batch being dispatched
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ---- submission side ------------------------------------------------ #
    def submit(self, inputs: Dict[str, np.ndarray],
               deadline_s: Optional[float] = None,
               timeout_s: float = 30.0) -> Dict[str, np.ndarray]:
        """Enqueue one request (1..max_batch rows) and block until its
        micro-batch flushes. Raises ShedError on a full queue, DeadlineError
        on deadline expiry, ValueError on malformed inputs."""
        t0 = time.monotonic()
        # validate at ADMISSION, not at flush: a malformed request must be
        # rejected here with ITS error, never joined into a micro-batch
        # whose np.concatenate/dispatch failure would poison innocent
        # co-batched requests
        validate = getattr(self.executor, "validate_request", None)
        if validate is not None:
            rows = int(validate(inputs))
        else:
            first = self.executor.input_names[0]
            if first not in inputs:
                raise ValueError(f"request missing input {first!r}")
            rows = int(np.shape(inputs[first])[0])
            if rows < 1:
                raise ValueError("empty request")
        if rows > self.max_batch:
            raise ValueError(f"request of {rows} rows exceeds max batch "
                             f"{self.max_batch}; split it client-side")
        deadline = None if deadline_s is None else t0 + float(deadline_s)
        req = _Pending(inputs, rows, deadline)
        with self._lock:
            if self._closing:
                raise ShuttingDownError("server is shutting down")
            if len(self._q) >= self.max_queue:
                self.shed_count += 1
                raise ShedError(
                    f"queue full ({self.max_queue} requests queued)")
            self._q.append(req)
            self._wake.notify()
        if not req.event.wait(timeout_s):
            # the submitter gives up: free the admission slot if still
            # queued, and mark cancelled so an already-popped copy is
            # skipped instead of burning bucket rows on an unread result
            with self._lock:
                req.cancelled = True
                try:
                    self._q.remove(req)
                except ValueError:
                    pass
            raise TimeoutError(f"no reply within {timeout_s}s "
                               f"(batcher wedged?)")
        if req.error is not None:
            raise req.error
        self.latency.record(time.monotonic() - t0)
        return req.result

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._q)

    @property
    def inflight_rows(self) -> int:
        """Rows in the micro-batch currently on the executor (0 between
        flushes)."""
        with self._lock:
            return self._inflight_rows

    def load_score(self) -> float:
        """The fleet router's signal: queued requests plus the in-flight
        batch's fill fraction. 0.0 = idle; +1 per queued request; the
        fractional part is how full the batch on the device is — two
        replicas with empty queues still order by who is dispatching
        more."""
        with self._lock:
            return len(self._q) + self._inflight_rows / self.max_batch

    def idle(self) -> bool:
        """Nothing queued AND nothing on the executor — the rolling
        reloader's swap-is-safe condition (paired read: both halves from
        one lock hold)."""
        with self._lock:
            return not self._q and self._inflight_rows == 0

    def wait_idle(self, timeout_s: float = 30.0,
                  poll_s: float = 0.005) -> bool:
        """Block until :meth:`idle` (the drain half of drain-and-swap).
        Returns False on timeout — a batcher that cannot drain is wedged,
        which is the failure detector's business, not the reloader's."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.idle():
                return True
            time.sleep(poll_s)
        return self.idle()

    def fill_ratio(self) -> Optional[float]:
        """Mean rows/max_batch over all flushed micro-batches."""
        with self._lock:      # paired read: both fields from one flush
            if not self.batches:
                return None
            return self._fill_sum / self.batches

    # ---- flush side ------------------------------------------------------ #
    def _take_batch(self) -> Optional[List[_Pending]]:
        """Block until a flush trigger fires; return the batch (oldest
        first, up to max_batch rows) or None on shutdown-without-drain /
        empty-drain."""
        with self._lock:
            while True:
                if self._q:
                    oldest = self._q[0]
                    queued_rows = sum(r.rows for r in self._q)
                    now = time.monotonic()
                    age = now - oldest.enqueued
                    if (queued_rows >= self.max_batch
                            or age >= self.max_delay_s or self._closing):
                        batch: List[_Pending] = []
                        rows = 0
                        while self._q and \
                                rows + self._q[0].rows <= self.max_batch:
                            r = self._q.popleft()
                            batch.append(r)
                            rows += r.rows
                        # counted under the SAME lock hold that popped the
                        # queue: idle() can never observe "queue empty,
                        # nothing in flight" while popped requests are
                        # still owed results
                        self._inflight_rows = rows
                        return batch
                    self._wake.wait(timeout=self.max_delay_s - age)
                elif self._closing:
                    return None
                else:
                    self._wake.wait(timeout=0.25)

    def _loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                return
            now = time.monotonic()
            live: List[_Pending] = []
            for r in batch:
                if r.cancelled:
                    continue        # submitter timed out; nobody listens
                if r.deadline is not None and now > r.deadline:
                    # counter shared with the handler threads' /stats
                    # reads and submit's shed accounting — same lock as
                    # the rest of the telemetry (THR004)
                    with self._lock:
                        self.deadline_expired += 1
                    r.error = DeadlineError(
                        f"deadline expired after "
                        f"{now - r.enqueued:.3f}s in queue")
                    r.event.set()
                else:
                    live.append(r)
            if not live:
                with self._lock:
                    self._inflight_rows = 0
                continue
            rows = sum(r.rows for r in live)
            try:
                joined = {
                    name: np.concatenate(
                        [np.asarray(r.inputs[name]) for r in live], axis=0)
                    for name in self.executor.input_names}
                out = self.executor.infer(joined)
            except BaseException as e:  # noqa: BLE001 — fan the error out
                for r in live:
                    r.error = e
                    r.event.set()
                with self._lock:
                    self._inflight_rows = 0
                continue
            # flush-thread counters race the /stats handler threads (and
            # fill_ratio's two-field read) without the lock: a lost
            # increment here understates load forever (THR004)
            with self._lock:
                self.batches += 1
                self.batched_rows += rows
                self._fill_sum += rows / self.max_batch
            off = 0
            for r in live:
                r.result = {
                    k: (v[off:off + r.rows]
                        if np.ndim(v) >= 1 and np.shape(v)[0] == rows
                        else v)
                    for k, v in out.items()}
                off += r.rows
                r.event.set()
            with self._lock:
                self._inflight_rows = 0

    # ---- shutdown -------------------------------------------------------- #
    def close(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Refuse new submissions; with ``drain`` flush everything already
        admitted, otherwise complete leftovers with ShedError. Idempotent."""
        with self._lock:
            self._closing = True
            self._drain = drain
            if not drain:
                leftovers = list(self._q)
                self._q.clear()
            else:
                leftovers = []
            self._wake.notify_all()
        for r in leftovers:
            r.error = ShuttingDownError("server shut down before dispatch")
            r.event.set()
        self._thread.join(timeout=timeout_s)
