"""Blocking serving client + the load generator shared by bench and tests.

Transport recovery rides ``runtime/retry.retry_with_backoff`` (capped
exponential backoff, full jitter — the same policy the async-SSP client
uses): a connection that dies mid-request is redialed and the request
RESENT, which is safe because ``infer`` is read-only/idempotent — the
kill-mid-request chaos test pins exactly this path. Application-level
refusals are NOT retried here: a shed response is the server's explicit
backpressure signal and surfaces to the caller as :class:`ServingError`
with ``shed=True`` — retrying into a full queue is the caller's policy
decision, not the transport's.
"""

from __future__ import annotations

import random
import socket
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..proto.wire import (WIRE_CODEC_VERSION, mark_codec_socket, recv_frame,
                          send_frame, wire_codec_enabled)
from ..runtime.metrics import LatencyWindow
from ..runtime.retry import retry_with_backoff

__all__ = ["ServingClient", "ServingError", "run_load"]


class ServingError(RuntimeError):
    """A structured refusal from the server (shed / deadline / bad
    request). ``shed`` and ``deadline_exceeded`` mirror the reply flags."""

    def __init__(self, message: str, *, shed: bool = False,
                 deadline_exceeded: bool = False):
        super().__init__(message)
        self.shed = shed
        self.deadline_exceeded = deadline_exceeded


class ServingClient:
    """One connection, blocking RPCs, transparent reconnect-and-resend."""

    def __init__(self, addr: Tuple[str, int], connect_deadline_s: float = 10.0,
                 retry_deadline_s: float = 10.0,
                 backoff_base_s: float = 0.02, backoff_cap_s: float = 0.5,
                 rng: Optional[random.Random] = None):
        self.addr = tuple(addr)
        self.retry_deadline_s = retry_deadline_s
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = rng or random.Random()
        self.reconnects = 0
        self._sock = retry_with_backoff(
            self._dial, deadline=connect_deadline_s, base=backoff_base_s,
            cap=backoff_cap_s, rng=self._rng, retry_on=(OSError, EOFError))

    def _dial(self) -> socket.socket:
        sk = socket.create_connection(self.addr, timeout=5.0)
        # binary tensor codec negotiation (re-run per dial — marking is
        # per socket). The offer itself is pickle; an old server answers
        # {"ok": False} through its unknown-kind path and this client
        # simply stays on the pickle wire — same frames as today.
        if wire_codec_enabled():
            try:
                send_frame(sk, {"kind": "wire",
                                "codec": WIRE_CODEC_VERSION}, codec=False)
                ack = recv_frame(sk)
                if isinstance(ack, dict) and ack.get("ok") \
                        and ack.get("codec") == WIRE_CODEC_VERSION:
                    mark_codec_socket(sk)
            except BaseException:
                sk.close()
                raise
        sk.settimeout(None)   # established: block (slow != dead)
        return sk

    def _rpc(self, msg: Dict) -> Dict:
        try:
            send_frame(self._sock, msg)
            return recv_frame(self._sock)
        except (OSError, EOFError) as e:
            # dead channel mid-request: redial and RESEND (idempotent ops
            # only ride this client), with backoff + jitter
            first_err = e

        def attempt() -> Dict:
            sk = self._dial()
            try:
                send_frame(sk, msg)
                out = recv_frame(sk)
            except BaseException:
                sk.close()
                raise
            old, self._sock = self._sock, sk
            try:
                old.close()
            except OSError:
                pass
            return out

        try:
            reply = retry_with_backoff(
                attempt, deadline=self.retry_deadline_s,
                base=self.backoff_base_s, cap=self.backoff_cap_s,
                rng=self._rng, retry_on=(OSError, EOFError))
        except (OSError, EOFError) as e:
            raise ConnectionError(
                f"server unreachable after {self.retry_deadline_s}s "
                f"(first error: {type(first_err).__name__}: {first_err})"
            ) from e
        self.reconnects += 1
        return reply

    def _stream_rpc(self, msg: Dict, on_tokens: Callable) -> Dict:
        """Send one request and consume ``gen_chunk`` frames until the
        final reply. Reconnect-and-RESEND is still safe mid-stream: each
        chunk carries the CUMULATIVE tokens, so a restarted generation
        just re-plays the prefix through ``on_tokens``."""
        def exchange(sock: socket.socket) -> Dict:
            send_frame(sock, msg)
            while True:
                reply = recv_frame(sock)
                if isinstance(reply, dict) and \
                        reply.get("kind") == "gen_chunk":
                    try:
                        # chunks may arrive as int32 buffers (codec wire)
                        # or lists (old servers) — callers always see ints
                        on_tokens([int(t) for t in reply["tokens"]])
                    except Exception:  # noqa: BLE001 — a broken sink must
                        pass           # not kill the stream consumption
                    continue
                return reply

        try:
            return exchange(self._sock)
        except (OSError, EOFError) as e:
            first_err = e

        def attempt() -> Dict:
            sk = self._dial()
            try:
                out = exchange(sk)
            except BaseException:
                sk.close()
                raise
            old, self._sock = self._sock, sk
            try:
                old.close()
            except OSError:
                pass
            return out

        try:
            reply = retry_with_backoff(
                attempt, deadline=self.retry_deadline_s,
                base=self.backoff_base_s, cap=self.backoff_cap_s,
                rng=self._rng, retry_on=(OSError, EOFError))
        except (OSError, EOFError) as e:
            raise ConnectionError(
                f"server unreachable after {self.retry_deadline_s}s "
                f"(first error: {type(first_err).__name__}: {first_err})"
            ) from e
        self.reconnects += 1
        return reply

    # ---- ops -------------------------------------------------------------- #
    def generate(self, prompt, max_new: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 on_tokens: Optional[Callable] = None) -> Dict:
        """LLM decode: returns ``{"tokens", "n_new", "prompt_len"}``.
        ``on_tokens`` (optional) turns on streaming — called with the
        cumulative generated-token list as decode progresses."""
        inputs: Dict = {"prompt": np.asarray(prompt, np.int32)}
        if max_new is not None:
            inputs["max_new"] = int(max_new)
        if eos_id is not None:
            inputs["eos_id"] = int(eos_id)
        msg: Dict = {"kind": "generate", "inputs": inputs}
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        if on_tokens is not None:
            msg["stream"] = True
            reply = self._stream_rpc(msg, on_tokens)
        else:
            reply = self._rpc(msg)
        if not reply.get("ok"):
            raise ServingError(
                str(reply.get("error", "request refused")),
                shed=bool(reply.get("shed")),
                deadline_exceeded=bool(reply.get("deadline_exceeded")))
        return reply["outputs"]

    def infer(self, inputs: Dict[str, np.ndarray],
              deadline_ms: Optional[float] = None) -> Dict[str, np.ndarray]:
        msg: Dict = {"kind": "infer", "inputs": inputs}
        if deadline_ms is not None:
            msg["deadline_ms"] = float(deadline_ms)
        reply = self._rpc(msg)
        if not reply.get("ok"):
            raise ServingError(
                str(reply.get("error", "request refused")),
                shed=bool(reply.get("shed")),
                deadline_exceeded=bool(reply.get("deadline_exceeded")))
        return reply["outputs"]

    def stats(self) -> Dict:
        reply = self._rpc({"kind": "stats"})
        if not reply.get("ok"):
            raise ServingError(str(reply.get("error", "stats refused")))
        return reply["stats"]

    def health(self) -> Dict:
        return self._rpc({"kind": "health"})

    def reload(self) -> Dict:
        return self._rpc({"kind": "reload"})

    def close(self) -> None:
        try:
            send_frame(self._sock, {"kind": "bye"})
        except (OSError, EOFError):
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# --------------------------------------------------------------------------- #
# load generator (bench.py serving mode, `bench_serve`, and the tests)
# --------------------------------------------------------------------------- #

def run_load(addr: Tuple[str, int],
             make_inputs: Callable[[int], Dict[str, np.ndarray]],
             n_requests: int = 200, concurrency: int = 4,
             deadline_ms: Optional[float] = None,
             retry_deadline_s: float = 10.0,
             offered_rps: Optional[float] = None,
             op: str = "infer") -> Dict:
    """Drive ``n_requests`` inferences through ``concurrency`` persistent
    client connections; returns p50/p99/goodput plus shed/error counts.

    Two load models:

    - **closed loop** (``offered_rps=None``, the default): each worker
      fires its next request the moment the previous reply lands. Load
      self-throttles to whatever the server sustains — fine for a latency
      floor, useless for a saturation curve (an overloaded server slows
      the generator down instead of being measured as overloaded).
    - **open loop** (``offered_rps=R``): request i has the fixed arrival
      time ``t0 + i/R``, independent of completions. A worker sleeps
      until its request's slot; a worker still waiting on a reply when
      its next slot passes fires late and is COUNTED (``late_fires`` —
      nonzero means concurrency is too low to realize the offered rate,
      i.e. the generator partially closed the loop). Goodput-vs-offered-
      load is measurable: offer 2x capacity and goodput saturates while
      sheds/deadlines absorb the rest.

    ``make_inputs(i)`` builds request i's input dict (vary batch sizes to
    exercise the bucket ladder). Sheds are counted, not retried — a bench
    that silently retried its way around backpressure would report a
    throughput the server cannot actually sustain.

    ``op="generate"`` drives the LLM decode op instead: ``make_inputs(i)``
    then returns ``generate`` keyword arguments (prompt/max_new/eos_id)
    and the summary gains ``tokens`` + ``goodput_tps`` (generated tokens
    per second over accepted requests — the LLM serving goodput unit)."""
    if op not in ("infer", "generate"):
        raise ValueError(f"op must be infer|generate, got {op!r}")
    if offered_rps is not None and offered_rps <= 0:
        # a zero rate would ZeroDivisionError inside every worker thread
        # (which dies silently) — refuse it loudly at the call site
        raise ValueError(f"offered_rps must be > 0, got {offered_rps}")
    lat = LatencyWindow(maxlen=max(2048, n_requests))
    counters = {"ok": 0, "shed": 0, "deadline": 0, "error": 0}
    tokens = {"v": 0}
    late = {"v": 0}
    counters_lock = threading.Lock()
    next_i = {"v": 0}
    t_start = time.monotonic()

    def worker() -> None:
        cli = ServingClient(addr, retry_deadline_s=retry_deadline_s)
        try:
            while True:
                with counters_lock:
                    i = next_i["v"]
                    if i >= n_requests:
                        return
                    next_i["v"] = i + 1
                if offered_rps is not None:
                    slot = t_start + i / offered_rps
                    lag = time.monotonic() - slot
                    if lag < 0:
                        time.sleep(-lag)
                    elif lag > 0.5 / offered_rps:
                        # past its slot by over half a period: the open
                        # loop is partially closed — count it
                        with counters_lock:
                            late["v"] += 1
                t0 = time.monotonic()
                try:
                    if op == "generate":
                        out = cli.generate(deadline_ms=deadline_ms,
                                           **make_inputs(i))
                        with counters_lock:
                            tokens["v"] += int(out.get("n_new", 0))
                    else:
                        cli.infer(make_inputs(i), deadline_ms=deadline_ms)
                    lat.record(time.monotonic() - t0)
                    key = "ok"
                except ServingError as e:
                    key = ("shed" if e.shed else
                           "deadline" if e.deadline_exceeded else "error")
                except (ConnectionError, OSError):
                    key = "error"
                with counters_lock:
                    counters[key] += 1
        finally:
            cli.close()

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(max(1, concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = max(time.monotonic() - t_start, 1e-9)
    summary = lat.summary()
    out = {
        **counters,
        "requests": n_requests,
        "concurrency": concurrency,
        "wall_s": round(wall, 4),
        "throughput_rps": round(counters["ok"] / wall, 2),
        "goodput_rps": round(counters["ok"] / wall, 2),
        "p50_ms": summary.get("p50_ms"),
        "p99_ms": summary.get("p99_ms"),
        "mean_ms": summary.get("mean_ms"),
    }
    if op == "generate":
        out.update({
            "tokens": tokens["v"],
            "goodput_tps": round(tokens["v"] / wall, 2),
        })
    if offered_rps is not None:
        sent = sum(counters.values())
        out.update({
            "offered_rps": round(float(offered_rps), 2),
            "achieved_rps": round(sent / wall, 2),
            "late_fires": late["v"],
        })
    return out
