"""Minimal protobuf *wire format* codec (proto2), no protoc required.

Used for binary compatibility with the reference's serialized artifacts:
``Datum`` records inside LMDB/LevelDB databases, ``BlobProto`` mean files,
``.caffemodel`` nets and ``.solverstate`` snapshots
(schema: ``/root/reference/src/caffe/proto/caffe.proto``).

Only the wire-level primitives plus hand-rolled (de)serializers for the handful
of messages we exchange with Caffe-format files — plus the length-prefixed
socket framing shared by every host-driven socket tier (the async-SSP
parameter service and the serving front-end).
"""

from __future__ import annotations

import io
import pickle
import socket
import struct
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

WIRETYPE_VARINT = 0
WIRETYPE_64BIT = 1
WIRETYPE_LEN = 2
WIRETYPE_32BIT = 5


class WireError(ValueError):
    pass


# --------------------------------------------------------------------------- #
# Length-prefixed socket framing (the host socket tier's wire format):
# 8-byte big-endian length + pickled payload over TCP on the launcher's
# control network (trusted, same trust domain as jax.distributed's own
# channel). Containment contract: a malformed or truncated frame raises
# FrameError so the receiving service can log and drop ONE connection
# instead of dying in its handler.
# --------------------------------------------------------------------------- #

class FrameError(ConnectionError):
    """Malformed or truncated wire frame (mid-message EOF, oversized
    length, undecodable pickle). A ConnectionError subclass so client
    recovery treats it like any other dead-channel signal, while the
    service can log it distinctly instead of dying in the handler."""


class FrameTooLargeError(ValueError):
    """SEND-side refusal of an over-cap frame. Deliberately NOT a
    ConnectionError/FrameError: the failure is deterministic and local
    (re-dialing and re-sending the same oversized pickle can never
    succeed), so it must surface loudly to the caller immediately —
    reconnect-and-replay machinery retrying it for the whole backoff
    deadline would bury the one error message that names the fix
    (POSEIDON_MAX_FRAME_BYTES on both ends)."""


# A garbage 8-byte header read as a length is astronomically large (ASCII
# bytes decode to ~10^16); cap frames so it fails fast as a FrameError
# BEFORE any allocation instead of an attempted multi-petabyte recv. The
# cap is configurable (PROTO207 found the original hard-coded 1<<32: a
# hostile or corrupt header still bought a multi-gigabyte allocation
# attempt): the default 1 GiB comfortably covers the largest real frame
# (a dense AlexNet anchor pull is ~240 MB) while an LM-sized deployment
# can raise it explicitly — a deliberate capacity decision, never a
# garbage header's.
DEFAULT_MAX_FRAME = 1 << 30          # 1 GiB
MAX_FRAME_ENV = "POSEIDON_MAX_FRAME_BYTES"
_max_frame_override: Optional[int] = None


def max_frame_bytes() -> int:
    """The active frame cap: explicit :func:`set_max_frame_bytes` wins,
    then the ``POSEIDON_MAX_FRAME_BYTES`` env (the launcher's channel,
    same distribution as the auth token), then the 1 GiB default."""
    if _max_frame_override is not None:
        return _max_frame_override
    import os
    env = os.environ.get(MAX_FRAME_ENV)
    if env:
        try:
            n = int(env)
        except ValueError:
            n = -1
        if n > 0:
            return n
        # an operator who SET the knob must not be silently told to set
        # it: warn once (warnings dedups) and fall back to the default
        import warnings
        warnings.warn(
            f"{MAX_FRAME_ENV}={env!r} is not a positive integer byte "
            f"count; using the default {DEFAULT_MAX_FRAME}",
            RuntimeWarning, stacklevel=2)
    return DEFAULT_MAX_FRAME


def set_max_frame_bytes(n: Optional[int]) -> None:
    """Process-wide override (None restores env/default resolution)."""
    global _max_frame_override
    if n is not None and n <= 0:
        raise ValueError(f"frame cap must be positive, got {n}")
    _max_frame_override = n


# --------------------------------------------------------------------------- #
# Zero-copy binary tensor codec (wire codec v1). A codec payload is
#
#   CODEC_MAGIC(4) | u32 skeleton_len | skeleton | raw tensor buffers
#
# The skeleton is a tiny pickle-free tag encoding of the message tree
# (dicts/lists/tuples/scalars); every ndarray leaf is replaced by a
# dtype-name + shape reference, and the array BYTES travel after the
# skeleton, concatenated in reference order — offsets are implied by the
# cumulative dtype/shape sizes, so there is no offset table to trust.
# Send is scatter-gather (``sendmsg`` over memoryviews of the live
# arrays — no serialization copy); receive fills ONE preallocated
# buffer sized by the cap-checked length prefix, and decoded arrays are
# ``np.frombuffer`` views into it (zero-copy; the buffer lives as long
# as any view). CODEC_MAGIC cannot collide with a pickle payload (those
# start with b"\x80"), so a receiver auto-detects the codec per frame
# and old-peer pickle frames keep working unchanged. Whether a SENDER
# may use the codec is negotiated per connection (the "wire" message
# kind in the async-SSP and serving tiers) and recorded here in a
# process-wide WeakSet of sockets. Byte order is native little-endian
# on both ends (the x86/TPU-host fleet; the skeleton itself is
# endian-explicit).
# --------------------------------------------------------------------------- #

CODEC_MAGIC = b"PTC\x01"        # version baked into the 4th byte
WIRE_CODEC_VERSION = 1
WIRE_CODEC_ENV = "POSEIDON_WIRE_CODEC"
_codec_override: Optional[bool] = None
# sockets whose PEER affirmed the codec during negotiation; WeakSet so a
# closed socket's entry dies with it (no unbounded registry growth)
_codec_socks: "weakref.WeakSet" = weakref.WeakSet()

_wire_stats_lock = threading.Lock()
_wire_stats = {
    "frames_encoded": 0, "encode_ns": 0, "encoded_bytes": 0,
    "frames_decoded": 0, "decode_ns": 0, "decoded_bytes": 0,
    "pickle_frames_sent": 0, "pickle_frames_recv": 0,
}


def wire_stats() -> Dict[str, int]:
    """Process-wide codec telemetry (encode/decode time and bytes) for
    ``bench.py comms``'s ``wire_encode_ms``/``wire_decode_ms`` lines.
    Timers cover ONLY (de)serialization — socket time is excluded, so
    the numbers compare against link transfer time directly."""
    with _wire_stats_lock:
        return dict(_wire_stats)


def reset_wire_stats() -> None:
    with _wire_stats_lock:
        for k in _wire_stats:
            _wire_stats[k] = 0


def wire_codec_enabled() -> bool:
    """Codec kill-switch: explicit :func:`set_wire_codec` wins, then the
    ``POSEIDON_WIRE_CODEC`` env, then ON. Off means negotiation is never
    offered/accepted and every frame is byte-for-byte the pickle wire."""
    if _codec_override is not None:
        return _codec_override
    import os
    env = os.environ.get(WIRE_CODEC_ENV)
    if env is not None:
        return env.strip().lower() not in ("0", "off", "false", "no", "")
    return True


def set_wire_codec(on: Optional[bool]) -> None:
    """Process-wide codec override (None restores env/default)."""
    global _codec_override
    _codec_override = on


def mark_codec_socket(sock: socket.socket) -> None:
    """Record that the peer on ``sock`` negotiated wire codec v1 — from
    here on :func:`send_frame` encodes this socket's frames binary."""
    _codec_socks.add(sock)


def socket_uses_codec(sock: socket.socket) -> bool:
    return sock in _codec_socks


class _CodecUnsupported(Exception):
    """Message contains something the skeleton cannot carry — the frame
    falls back to whole-message pickle (auto-detected by the receiver)."""


_dtype_name_cache: Dict[str, Optional[np.dtype]] = {}


def _dtype_from_name(name: str) -> np.dtype:
    """Resolve a wire dtype NAME (names, not ``.str``, because extension
    dtypes like bfloat16 all stringify as ``<V2``)."""
    dt = _dtype_name_cache.get(name)
    if dt is None:
        try:
            dt = np.dtype(name)
        except TypeError:
            # extension dtypes register their names only once their
            # package is imported (ml_dtypes for the bf16 wire)
            import ml_dtypes  # noqa: F401
            dt = np.dtype(name)
        _dtype_name_cache[name] = dt
    return dt


def _dtype_wire_ok(dt: np.dtype) -> bool:
    """A dtype rides the codec iff its NAME round-trips to itself."""
    ok = _dtype_name_cache.get("ok:" + dt.name)
    if ok is None:
        try:
            ok = (not dt.hasobject) and _dtype_from_name(dt.name) == dt
        except Exception:  # noqa: BLE001 — unknown name → pickle fallback
            ok = False
        _dtype_name_cache["ok:" + dt.name] = ok  # type: ignore[assignment]
    return bool(ok)


_MAX_SKELETON_DEPTH = 64


def _enc_skeleton(obj, out: bytearray, arrays: List[np.ndarray],
                  depth: int) -> None:
    if depth > _MAX_SKELETON_DEPTH:
        raise _CodecUnsupported("nesting too deep")
    if obj is None:
        out += b"N"
    elif obj is True:
        out += b"T"
    elif obj is False:
        out += b"F"
    elif type(obj) is int:
        try:
            out += b"i" + struct.pack("!q", obj)
        except struct.error:
            raise _CodecUnsupported("int out of i64 range") from None
    elif type(obj) is float:
        out += b"f" + struct.pack("!d", obj)
    elif type(obj) is str:
        raw = obj.encode("utf-8")
        out += b"s" + struct.pack("!I", len(raw))
        out += raw
    elif type(obj) is bytes:
        out += b"y" + struct.pack("!I", len(obj))
        out += obj
    elif isinstance(obj, np.ndarray):
        if not _dtype_wire_ok(obj.dtype) or obj.ndim > 255:
            raise _CodecUnsupported(f"array dtype {obj.dtype}")
        nm = obj.dtype.name.encode("ascii")
        out += b"a" + struct.pack("!B", len(nm)) + nm
        out += struct.pack("!B", obj.ndim)
        for d in obj.shape:
            out += struct.pack("!Q", d)
        arrays.append(obj)
    elif isinstance(obj, np.generic):
        dt = np.asarray(obj).dtype
        if not _dtype_wire_ok(dt):
            raise _CodecUnsupported(f"scalar dtype {dt}")
        nm = dt.name.encode("ascii")
        raw = obj.tobytes()
        out += b"z" + struct.pack("!B", len(nm)) + nm
        out += struct.pack("!B", len(raw))
        out += raw
    elif type(obj) in (list, tuple):
        out += (b"l" if type(obj) is list else b"t")
        out += struct.pack("!I", len(obj))
        for item in obj:
            _enc_skeleton(item, out, arrays, depth + 1)
    elif type(obj) is dict:
        out += b"d" + struct.pack("!I", len(obj))
        for k, v in obj.items():
            _enc_skeleton(k, out, arrays, depth + 1)
            _enc_skeleton(v, out, arrays, depth + 1)
    else:
        raise _CodecUnsupported(type(obj).__name__)


def _array_wire_view(arr: np.ndarray) -> memoryview:
    """A zero-copy byte view of the array's buffer. Extension dtypes
    (bfloat16) refuse the buffer protocol directly, so view through
    uint8; a non-contiguous leaf costs one compaction copy here."""
    arr = np.ascontiguousarray(arr)
    return memoryview(arr.reshape(-1).view(np.uint8))


def encode_codec_payload(obj):
    """Encode ``obj`` as a codec payload. Returns ``(parts, nbytes)`` —
    ``parts`` is a scatter-gather list (header bytes + live array
    views, NO concatenation copy) — or None when the message holds
    something the skeleton cannot carry (caller falls back to pickle)."""
    out = bytearray()
    arrays: List[np.ndarray] = []
    try:
        _enc_skeleton(obj, out, arrays, 0)
    except _CodecUnsupported:
        return None
    if len(out) > 0xFFFFFFFF:
        return None
    parts: List = [CODEC_MAGIC + struct.pack("!I", len(out)) + bytes(out)]
    total = len(parts[0])
    for arr in arrays:
        mv = _array_wire_view(arr)
        parts.append(mv)
        total += len(mv)
    return parts, total


class _DecCursor:
    """Bounds-checked cursors over one received payload: ``pos`` walks
    the skeleton, ``data`` walks the trailing tensor region. Every read
    is length-checked BEFORE it happens — a truncated or lying skeleton
    raises FrameError instead of reading a neighbour's bytes."""

    __slots__ = ("mv", "pos", "skel_end", "data", "end")

    def __init__(self, mv: memoryview, skel_end: int):
        self.mv = mv
        self.pos = 8
        self.skel_end = skel_end
        self.data = skel_end
        self.end = len(mv)

    def take(self, n: int) -> memoryview:
        if self.pos + n > self.skel_end:
            raise FrameError("codec skeleton truncated")
        v = self.mv[self.pos:self.pos + n]
        self.pos += n
        return v

    def take_data(self, n: int) -> memoryview:
        if self.data + n > self.end:
            raise FrameError("codec tensor data truncated")
        v = self.mv[self.data:self.data + n]
        self.data += n
        return v


def _dec_skeleton(cur: _DecCursor, depth: int):
    if depth > _MAX_SKELETON_DEPTH:
        raise FrameError("codec skeleton too deep")
    tag = bytes(cur.take(1))
    if tag == b"N":
        return None
    if tag == b"T":
        return True
    if tag == b"F":
        return False
    if tag == b"i":
        return struct.unpack("!q", cur.take(8))[0]
    if tag == b"f":
        return struct.unpack("!d", cur.take(8))[0]
    if tag == b"s":
        (n,) = struct.unpack("!I", cur.take(4))
        return bytes(cur.take(n)).decode("utf-8")
    if tag == b"y":
        (n,) = struct.unpack("!I", cur.take(4))
        return bytes(cur.take(n))
    if tag == b"a":
        (nml,) = struct.unpack("!B", cur.take(1))
        dt = _dtype_from_name(bytes(cur.take(nml)).decode("ascii"))
        (nd,) = struct.unpack("!B", cur.take(1))
        shape = tuple(struct.unpack("!Q", cur.take(8))[0]
                      for _ in range(nd))
        count = 1
        for d in shape:
            count *= d
        raw = cur.take_data(count * dt.itemsize)
        # zero-copy: the array is a view into the receive buffer
        # (writable — the buffer is a per-frame bytearray, never reused)
        return np.frombuffer(raw, dtype=dt).reshape(shape)
    if tag == b"z":
        (nml,) = struct.unpack("!B", cur.take(1))
        dt = _dtype_from_name(bytes(cur.take(nml)).decode("ascii"))
        (n,) = struct.unpack("!B", cur.take(1))
        return np.frombuffer(bytes(cur.take(n)), dtype=dt)[0]
    if tag in (b"l", b"t"):
        (n,) = struct.unpack("!I", cur.take(4))
        items = [_dec_skeleton(cur, depth + 1) for _ in range(n)]
        return items if tag == b"l" else tuple(items)
    if tag == b"d":
        (n,) = struct.unpack("!I", cur.take(4))
        return {_dec_skeleton(cur, depth + 1): _dec_skeleton(cur, depth + 1)
                for _ in range(n)}
    raise FrameError(f"unknown codec skeleton tag {tag!r}")


def decode_codec_payload(buf) -> object:
    """Decode one codec payload (the receive buffer INCLUDING the magic).
    Rejects any mismatch between the skeleton's claimed tensor extents
    and the actual payload size — truncated AND oversized frames both
    raise FrameError, nothing is silently padded or dropped."""
    mv = memoryview(buf)
    if len(mv) < 8 or bytes(mv[:4]) != CODEC_MAGIC:
        raise FrameError("not a codec payload")
    (skel_len,) = struct.unpack("!I", mv[4:8])
    if 8 + skel_len > len(mv):
        raise FrameError("codec skeleton overruns frame")
    cur = _DecCursor(mv, 8 + skel_len)
    try:
        obj = _dec_skeleton(cur, 0)
    except FrameError:
        raise
    except Exception as e:  # noqa: BLE001 — any malformed skeleton
        raise FrameError(
            f"bad codec skeleton: {type(e).__name__}: {e}") from e
    if cur.pos != cur.skel_end:
        raise FrameError("codec skeleton has trailing bytes")
    if cur.data != cur.end:
        raise FrameError(
            f"codec frame size mismatch: skeleton consumed "
            f"{cur.data - cur.skel_end} tensor bytes of "
            f"{cur.end - cur.skel_end} in the frame")
    return obj


_SENDMSG_BATCH = 64  # stay far under IOV_MAX for one sendmsg call


def _sendmsg_all(sock: socket.socket, parts: List) -> None:
    """sendall() for a scatter-gather buffer list: loop ``sendmsg`` over
    ≤64-buffer batches, resuming cleanly after partial sends."""
    bufs = [p if isinstance(p, memoryview) else memoryview(p)
            for p in parts]
    if not hasattr(sock, "sendmsg"):  # exotic socket-likes: plain sends
        for b in bufs:
            sock.sendall(b)
        return
    while bufs:
        n = sock.sendmsg(bufs[:_SENDMSG_BATCH])
        while bufs and n >= len(bufs[0]):
            n -= len(bufs[0])
            bufs.pop(0)
        if bufs and n:
            bufs[0] = bufs[0][n:]


def send_frame(sock: socket.socket, obj, codec: Optional[bool] = None) -> int:
    """Send one frame; returns the ACTUAL wire bytes (header + payload) so
    bandwidth-budgeted callers (the managed-communication token bucket) can
    account what the link really carried, not an estimate. Refuses frames
    over the configured cap LOUDLY — the peer would drop the connection
    at its own cap check, and a send-side error names the knob.

    ``codec=None`` resolves per socket (set during the "wire" negotiation);
    a codec frame is the zero-copy binary tensor encoding, anything else —
    codec off, un-negotiated peer, or a message the skeleton cannot carry —
    is today's pickle wire, byte for byte."""
    if codec is None:
        codec = socket_uses_codec(sock)
    if codec and wire_codec_enabled():
        t0 = time.perf_counter_ns()
        enc = encode_codec_payload(obj)
        dt = time.perf_counter_ns() - t0
        if enc is not None:
            parts, n = enc
            cap = max_frame_bytes()
            if n > cap:
                raise FrameTooLargeError(
                    f"refusing to send a {n}-byte frame over the "
                    f"{cap}-byte cap (raise {MAX_FRAME_ENV} or "
                    f"set_max_frame_bytes on BOTH ends for frames this "
                    f"large)")
            _sendmsg_all(sock, [struct.pack("!Q", n)] + parts)
            with _wire_stats_lock:
                _wire_stats["frames_encoded"] += 1
                _wire_stats["encode_ns"] += dt
                _wire_stats["encoded_bytes"] += n
            return n + 8
    buf = io.BytesIO()
    pickle.dump(obj, buf, protocol=pickle.HIGHEST_PROTOCOL)
    data = buf.getvalue()
    cap = max_frame_bytes()
    if len(data) > cap:
        raise FrameTooLargeError(
            f"refusing to send a {len(data)}-byte frame over the "
            f"{cap}-byte cap (raise {MAX_FRAME_ENV} or "
            f"set_max_frame_bytes on BOTH ends for frames this large)")
    sock.sendall(struct.pack("!Q", len(data)) + data)
    with _wire_stats_lock:
        _wire_stats["pickle_frames_sent"] += 1
    return len(data) + 8


def recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    want = n
    while want:
        c = sock.recv(min(want, 1 << 20))
        if not c:
            if want == n:
                raise ConnectionError("peer closed")
            raise FrameError(f"mid-message EOF ({n - want}/{n} bytes)")
        chunks.append(c)
        want -= len(c)
    return b"".join(chunks)


def _recv_into_exact(sock: socket.socket, buf: bytearray) -> None:
    """Fill the whole preallocated buffer (the codec's single receive
    allocation — decoded arrays alias it, so it is fresh per frame)."""
    view = memoryview(buf)
    n = len(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], min(n - got, 1 << 20))
        if r == 0:
            raise FrameError(f"mid-message EOF in payload ({got}/{n} bytes)")
        got += r


def recv_frame_sized(sock: socket.socket):
    """Receive one frame; returns (obj, wire_bytes) — wire_bytes is the
    actual header + payload byte count, the pull-path input to the managed-
    communication bandwidth accounting. The payload buffer is allocated
    ONCE, sized by the cap-checked length prefix; codec frames are
    auto-detected by magic (pickle cannot start with it), so a receiver
    needs no negotiation state and old-peer pickle frames always work."""
    (n,) = struct.unpack("!Q", recv_exact(sock, 8))
    cap = max_frame_bytes()
    if n > cap:
        # reject BEFORE any payload allocation: a garbage or hostile
        # header must cost a log line, not a multi-gigabyte recv buffer
        raise FrameError(
            f"frame length {n} exceeds cap {cap} (garbage header, or a "
            f"legitimately huge frame — raise {MAX_FRAME_ENV} on both "
            f"ends if it is the latter)")
    payload = bytearray(n)
    try:
        _recv_into_exact(sock, payload)
    except FrameError:
        raise
    except ConnectionError as e:
        # header arrived, payload did not: mid-message, not a clean close
        raise FrameError(f"mid-message EOF in payload ({e})") from e
    if n >= len(CODEC_MAGIC) and payload[:4] == CODEC_MAGIC:
        t0 = time.perf_counter_ns()
        obj = decode_codec_payload(payload)
        with _wire_stats_lock:
            _wire_stats["frames_decoded"] += 1
            _wire_stats["decode_ns"] += time.perf_counter_ns() - t0
            _wire_stats["decoded_bytes"] += n
        return obj, n + 8
    try:
        obj = pickle.loads(bytes(payload))
    except Exception as e:  # noqa: BLE001 — any undecodable payload
        raise FrameError(f"bad frame payload: {type(e).__name__}: {e}") from e
    with _wire_stats_lock:
        _wire_stats["pickle_frames_recv"] += 1
    return obj, n + 8


def recv_frame(sock: socket.socket):
    return recv_frame_sized(sock)[0]


# --------------------------------------------------------------------------- #
# Shared-secret connection handshake. The frame payloads are PICKLES —
# arbitrary code execution for whoever can reach (or spoof) the port — so
# an auth-enabled tier authenticates every connection MUTUALLY, over raw
# bytes (never pickle), before either side's recv_frame parses a thing:
#
#   server -> client : MAGIC + nonce_s                 (challenge)
#   client -> server : HMAC(token, nonce_s) + nonce_c  (proof + challenge)
#   server -> client : HMAC(token, nonce_c + b"srv")   (proof)
#
# The server proves possession too — a spoofed/MITM'd service that only
# replays the magic cannot produce the second digest, so a worker never
# feeds bytes from an unauthenticated peer to its pickle loader either.
# Digests are compared in constant time. The token rides the launcher env
# (POSEIDON_ASYNC_TOKEN) — same trust distribution as jax.distributed's
# coordinator address.
# --------------------------------------------------------------------------- #

AUTH_MAGIC = b"PSDNAUTH"
AUTH_NONCE_LEN = 16
AUTH_DIGEST_LEN = 32  # sha256
_AUTH_SERVER_TAG = b"srv"


class AuthError(ConnectionError):
    """Handshake failed: bad token, wrong protocol bytes, or a peer that
    speaks frames at an auth-required service."""


def _hmac_digest(token: str, nonce: bytes) -> bytes:
    import hashlib
    import hmac as hmac_mod
    return hmac_mod.new(token.encode("utf-8"), nonce,
                        hashlib.sha256).digest()


def server_handshake(sock: socket.socket, token: str,
                     timeout_s: float = 5.0) -> bool:
    """Authenticate one inbound connection (and prove our own token back).
    Returns True on success; False (after which the caller must CLOSE the
    socket without reading a single frame) on any mismatch, timeout, or
    protocol violation."""
    import hmac as hmac_mod
    nonce = __import__("os").urandom(AUTH_NONCE_LEN)
    prev = sock.gettimeout()
    sock.settimeout(timeout_s)
    try:
        sock.sendall(AUTH_MAGIC + nonce)
        got = recv_exact(sock, AUTH_DIGEST_LEN + AUTH_NONCE_LEN)
        digest, nonce_c = got[:AUTH_DIGEST_LEN], got[AUTH_DIGEST_LEN:]
        if not hmac_mod.compare_digest(digest, _hmac_digest(token, nonce)):
            return False
        sock.sendall(_hmac_digest(token, nonce_c + _AUTH_SERVER_TAG))
        return True
    except (OSError, ConnectionError, socket.timeout):
        return False
    finally:
        try:
            sock.settimeout(prev)
        except OSError:
            pass


def client_handshake(sock: socket.socket, token: str,
                     timeout_s: float = 5.0) -> None:
    """Answer the server's challenge AND verify the server's proof before
    the caller parses any frame. Raises AuthError on protocol mismatch
    (e.g. the service runs without a token and sent a frame header
    instead of the challenge) or on a server that cannot prove the
    token (spoofed endpoint)."""
    import hmac as hmac_mod
    prev = sock.gettimeout()
    sock.settimeout(timeout_s)
    try:
        head = recv_exact(sock, len(AUTH_MAGIC) + AUTH_NONCE_LEN)
        if not head.startswith(AUTH_MAGIC):
            raise AuthError("peer did not offer an auth challenge "
                            "(token configured on one side only?)")
        nonce_s = head[len(AUTH_MAGIC):]
        nonce_c = __import__("os").urandom(AUTH_NONCE_LEN)
        sock.sendall(_hmac_digest(token, nonce_s) + nonce_c)
        proof = recv_exact(sock, AUTH_DIGEST_LEN)
        if not hmac_mod.compare_digest(
                proof, _hmac_digest(token, nonce_c + _AUTH_SERVER_TAG)):
            raise AuthError("peer failed to prove the shared token "
                            "(spoofed service?)")
    finally:
        try:
            sock.settimeout(prev)
        except OSError:
            pass


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise WireError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise WireError("varint too long")


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value &= (1 << 64) - 1
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return


def iter_fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a serialized message.

    LEN fields yield raw bytes; VARINT yields int; 32/64-bit yield raw ints.
    """
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == WIRETYPE_VARINT:
            val, pos = _read_varint(buf, pos)
        elif wtype == WIRETYPE_64BIT:
            val = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wtype == WIRETYPE_LEN:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            if len(val) != ln:
                raise WireError("truncated length-delimited field")
            pos += ln
        elif wtype == WIRETYPE_32BIT:
            val = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:
            raise WireError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _as_float(wtype: int, val) -> float:
    if wtype == WIRETYPE_32BIT:
        return struct.unpack("<f", val.to_bytes(4, "little"))[0]
    raise WireError("expected 32-bit float field")


def _packed_floats(val: bytes) -> np.ndarray:
    return np.frombuffer(val, dtype="<f4")


def _emit_tag(out: bytearray, fnum: int, wtype: int) -> None:
    _write_varint(out, (fnum << 3) | wtype)


def emit_varint_field(out: bytearray, fnum: int, value: int) -> None:
    _emit_tag(out, fnum, WIRETYPE_VARINT)
    _write_varint(out, value)


def emit_bytes_field(out: bytearray, fnum: int, value: bytes) -> None:
    _emit_tag(out, fnum, WIRETYPE_LEN)
    _write_varint(out, len(value))
    out.extend(value)


def emit_packed_floats(out: bytearray, fnum: int, values: np.ndarray) -> None:
    emit_bytes_field(out, fnum, np.asarray(values, dtype="<f4").tobytes())


def emit_float_field(out: bytearray, fnum: int, value: float) -> None:
    _emit_tag(out, fnum, WIRETYPE_32BIT)
    out.extend(struct.pack("<f", value))


# --------------------------------------------------------------------------- #
# Datum
# --------------------------------------------------------------------------- #

@dataclass
class Datum:
    channels: int = 0
    height: int = 0
    width: int = 0
    data: bytes = b""
    label: int = 0
    float_data: Optional[np.ndarray] = None

    def to_array(self) -> np.ndarray:
        """(C, H, W) float32 array (uint8 bytes NOT mean-subtracted/scaled)."""
        if self.float_data is not None and len(self.float_data):
            return np.asarray(self.float_data, np.float32).reshape(
                self.channels, self.height, self.width)
        arr = np.frombuffer(self.data, dtype=np.uint8)
        return arr.reshape(self.channels, self.height, self.width).astype(np.float32)


def decode_datum(buf: bytes) -> Datum:
    d = Datum()
    floats: List[float] = []
    packed: Optional[np.ndarray] = None
    for fnum, wtype, val in iter_fields(buf):
        if fnum == 1:
            d.channels = val
        elif fnum == 2:
            d.height = val
        elif fnum == 3:
            d.width = val
        elif fnum == 4:
            d.data = val
        elif fnum == 5:
            d.label = val
        elif fnum == 6:
            if wtype == WIRETYPE_LEN:
                packed = _packed_floats(val)
            else:
                floats.append(_as_float(wtype, val))
    if packed is not None:
        d.float_data = packed
    elif floats:
        d.float_data = np.asarray(floats, np.float32)
    return d


def encode_datum(d: Datum) -> bytes:
    out = bytearray()
    emit_varint_field(out, 1, d.channels)
    emit_varint_field(out, 2, d.height)
    emit_varint_field(out, 3, d.width)
    if d.data:
        emit_bytes_field(out, 4, d.data)
    emit_varint_field(out, 5, d.label)
    if d.float_data is not None and len(d.float_data):
        emit_packed_floats(out, 6, d.float_data)
    return bytes(out)


# --------------------------------------------------------------------------- #
# BlobProto
# --------------------------------------------------------------------------- #

@dataclass
class BlobProtoWire:
    num: int = 0
    channels: int = 0
    height: int = 0
    width: int = 0
    data: Optional[np.ndarray] = None
    diff: Optional[np.ndarray] = None

    @property
    def shape(self) -> Tuple[int, int, int, int]:
        return (self.num, self.channels, self.height, self.width)

    def to_array(self) -> np.ndarray:
        return np.asarray(self.data, np.float32).reshape(self.shape)


def decode_blob(buf: bytes) -> BlobProtoWire:
    b = BlobProtoWire()
    data_parts: List[np.ndarray] = []
    diff_parts: List[np.ndarray] = []
    for fnum, wtype, val in iter_fields(buf):
        if fnum == 1:
            b.num = val
        elif fnum == 2:
            b.channels = val
        elif fnum == 3:
            b.height = val
        elif fnum == 4:
            b.width = val
        elif fnum == 5:
            data_parts.append(_packed_floats(val) if wtype == WIRETYPE_LEN
                              else np.asarray([_as_float(wtype, val)], np.float32))
        elif fnum == 6:
            diff_parts.append(_packed_floats(val) if wtype == WIRETYPE_LEN
                              else np.asarray([_as_float(wtype, val)], np.float32))
    if data_parts:
        b.data = np.concatenate(data_parts)
    if diff_parts:
        b.diff = np.concatenate(diff_parts)
    return b


def encode_blob(arr: np.ndarray, diff: Optional[np.ndarray] = None) -> bytes:
    from ..core.blob import nchw
    shape = nchw(tuple(arr.shape))
    out = bytearray()
    emit_varint_field(out, 1, shape[0])
    emit_varint_field(out, 2, shape[1])
    emit_varint_field(out, 3, shape[2])
    emit_varint_field(out, 4, shape[3])
    emit_packed_floats(out, 5, np.asarray(arr, np.float32).ravel())
    if diff is not None:
        emit_packed_floats(out, 6, np.asarray(diff, np.float32).ravel())
    return bytes(out)


def read_blob_file(path: str) -> np.ndarray:
    """Read a .binaryproto BlobProto file (e.g. an image-mean file)."""
    with open(path, "rb") as f:
        return decode_blob(f.read()).to_array()


# --------------------------------------------------------------------------- #
# NetParameter-level (.caffemodel): only name + layers{name,type,blobs} matter
# for weight exchange.
# --------------------------------------------------------------------------- #

@dataclass
class LayerBlobs:
    name: str
    blobs: List[BlobProtoWire] = field(default_factory=list)


def decode_caffemodel(buf: bytes) -> Dict[str, List[np.ndarray]]:
    """Extract {layer_name: [blob arrays]} from a serialized NetParameter.

    Handles the V1 `layers`(2) field; layer name is LayerParameter field 4,
    blobs are field 6.
    """
    weights: Dict[str, List[np.ndarray]] = {}
    for fnum, wtype, val in iter_fields(buf):
        if fnum == 2 and wtype == WIRETYPE_LEN:
            name = ""
            blobs: List[BlobProtoWire] = []
            for lf, lw, lv in iter_fields(val):
                if lf == 4 and lw == WIRETYPE_LEN:
                    name = lv.decode("utf-8", "replace")
                elif lf == 6 and lw == WIRETYPE_LEN:
                    blobs.append(decode_blob(lv))
            if name:
                weights[name] = [b.to_array() for b in blobs]
    return weights


def encode_caffemodel(net_name: str, layer_weights: Dict[str, List[np.ndarray]],
                      layer_types: Optional[Dict[str, int]] = None) -> bytes:
    """Serialize weights as a NetParameter binary that Caffe can ingest."""
    out = bytearray()
    emit_bytes_field(out, 1, net_name.encode())
    for lname, blobs in layer_weights.items():
        layer = bytearray()
        emit_bytes_field(layer, 4, lname.encode())
        if layer_types and lname in layer_types:
            emit_varint_field(layer, 5, layer_types[lname])
        for arr in blobs:
            emit_bytes_field(layer, 6, encode_blob(arr))
        emit_bytes_field(out, 2, bytes(layer))
    return bytes(out)
