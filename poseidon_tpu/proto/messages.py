"""Typed configuration schema mirroring the reference's caffe.proto surface.

Field names, defaults and enum tokens follow the reference schema
(``/root/reference/src/caffe/proto/caffe.proto``) so that the in-repo model zoo
prototxts parse unchanged. Both the V1 format (``layers { type: CONVOLUTION }``
with ``blobs_lr``/``weight_decay`` multiplier lists) and the V2 format
(``layer { type: "Convolution" }`` with ``param { lr_mult }`` specs) are accepted
and normalized to one internal representation.

These are plain dataclasses built from :class:`~poseidon_tpu.proto.prototxt.Node`
trees by a generic, type-hint-driven builder — no protoc involved.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, List, Optional, get_args, get_origin, get_type_hints

from .prototxt import Node, PrototxtError, parse_file, parse


def _coerce(value: Any, typ: Any, fname: str) -> Any:
    if typ is float:
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            return float(value)
    elif typ is int:
        if isinstance(value, bool):
            raise PrototxtError(f"field {fname}: expected int, got bool")
        if isinstance(value, int):
            return value
        if isinstance(value, float) and value.is_integer():
            return int(value)
    elif typ is bool:
        if isinstance(value, bool):
            return value
        if isinstance(value, int) and value in (0, 1):
            return bool(value)
    elif typ is str:
        if isinstance(value, str):
            return value
    elif dataclasses.is_dataclass(typ):
        if isinstance(value, Node):
            return build(typ, value)
    raise PrototxtError(f"field {fname}: cannot convert {value!r} to {typ}")


def build(cls, node: Node):
    """Build dataclass ``cls`` from a parsed Node, checking types and arity."""
    hints = get_type_hints(cls)
    known = {f.name for f in dataclasses.fields(cls)}
    aliases = getattr(cls, "_aliases", {})
    kwargs = {}
    unknown = [k for k in node.keys() if k not in known and k not in aliases]
    if unknown:
        raise PrototxtError(f"{cls.__name__}: unknown field(s) {sorted(set(unknown))}")
    for f in dataclasses.fields(cls):
        names = [f.name] + [a for a, target in aliases.items() if target == f.name]
        values = []
        for n in names:
            values.extend(node.get_all(n))
        if not values:
            continue
        typ = hints[f.name]
        if get_origin(typ) is list:
            (elem,) = get_args(typ)
            kwargs[f.name] = [_coerce(v, elem, f.name) for v in values]
        else:
            if get_origin(typ) is Optional or (get_origin(typ) is type(None)):
                pass
            args = get_args(typ)
            if args and type(None) in args:  # Optional[X]
                typ = next(a for a in args if a is not type(None))
            if len(values) > 1:
                values = values[-1:]  # proto2 semantics: last value wins
            kwargs[f.name] = _coerce(values[0], typ, f.name)
    return cls(**kwargs)


# --------------------------------------------------------------------------- #
# Fillers / blobs / state
# --------------------------------------------------------------------------- #

@dataclass
class FillerParameter:
    type: str = "constant"
    value: float = 0.0
    min: float = 0.0
    max: float = 1.0
    mean: float = 0.0
    std: float = 1.0
    sparse: int = -1


@dataclass
class BlobProto:
    num: int = 0
    channels: int = 0
    height: int = 0
    width: int = 0
    data: List[float] = field(default_factory=list)
    diff: List[float] = field(default_factory=list)
    blob_mode: str = "LOCAL"
    global_id: int = -1


@dataclass
class NetState:
    phase: str = "TEST"
    level: int = 0
    stage: List[str] = field(default_factory=list)


@dataclass
class NetStateRule:
    phase: Optional[str] = None
    min_level: Optional[int] = None
    max_level: Optional[int] = None
    stage: List[str] = field(default_factory=list)
    not_stage: List[str] = field(default_factory=list)

    def matches(self, state: NetState) -> bool:
        if self.phase is not None and self.phase != state.phase:
            return False
        if self.min_level is not None and state.level < self.min_level:
            return False
        if self.max_level is not None and state.level > self.max_level:
            return False
        for s in self.stage:
            if s not in state.stage:
                return False
        for s in self.not_stage:
            if s in state.stage:
                return False
        return True


@dataclass
class TransformationParameter:
    scale: float = 1.0
    mirror: bool = False
    crop_size: int = 0
    mean_file: str = ""
    mean_value: List[float] = field(default_factory=list)


# --------------------------------------------------------------------------- #
# Per-layer parameter messages
# --------------------------------------------------------------------------- #

@dataclass
class AccuracyParameter:
    top_k: int = 1


@dataclass
class ArgMaxParameter:
    out_max_val: bool = False
    top_k: int = 1


@dataclass
class ConcatParameter:
    concat_dim: int = 1
    _aliases = {"axis": "concat_dim"}


@dataclass
class ContrastiveLossParameter:
    margin: float = 1.0


@dataclass
class ConvolutionParameter:
    num_output: int = 0
    bias_term: bool = True
    pad: int = 0
    pad_h: int = 0
    pad_w: int = 0
    kernel_size: int = 0
    kernel_h: int = 0
    kernel_w: int = 0
    group: int = 1
    stride: int = 1
    stride_h: int = 0
    stride_w: int = 0
    weight_filler: FillerParameter = field(default_factory=FillerParameter)
    bias_filler: FillerParameter = field(default_factory=FillerParameter)
    engine: str = "DEFAULT"


@dataclass
class DataParameter:
    source: str = ""
    batch_size: int = 0
    rand_skip: int = 0
    backend: str = "LEVELDB"
    shared_file_system: bool = False
    scale: float = 1.0
    mean_file: str = ""
    crop_size: int = 0
    mirror: bool = False


@dataclass
class DropoutParameter:
    dropout_ratio: float = 0.5


@dataclass
class DummyDataParameter:
    data_filler: List[FillerParameter] = field(default_factory=list)
    num: List[int] = field(default_factory=list)
    channels: List[int] = field(default_factory=list)
    height: List[int] = field(default_factory=list)
    width: List[int] = field(default_factory=list)


@dataclass
class EltwiseParameter:
    operation: str = "SUM"
    coeff: List[float] = field(default_factory=list)
    stable_prod_grad: bool = True


@dataclass
class ThresholdParameter:
    threshold: float = 0.0


@dataclass
class HDF5DataParameter:
    source: str = ""
    batch_size: int = 0


@dataclass
class HDF5OutputParameter:
    file_name: str = ""


@dataclass
class HingeLossParameter:
    norm: str = "L1"


@dataclass
class ImageDataParameter:
    source: str = ""
    batch_size: int = 0
    rand_skip: int = 0
    shuffle: bool = False
    new_height: int = 0
    new_width: int = 0
    shared_file_system: bool = False
    scale: float = 1.0
    mean_file: str = ""
    crop_size: int = 0
    mirror: bool = False
    root_folder: str = ""


@dataclass
class InfogainLossParameter:
    source: str = ""


@dataclass
class InnerProductParameter:
    num_output: int = 0
    bias_term: bool = True
    weight_filler: FillerParameter = field(default_factory=FillerParameter)
    bias_filler: FillerParameter = field(default_factory=FillerParameter)


@dataclass
class LRNParameter:
    local_size: int = 5
    alpha: float = 1.0
    beta: float = 0.75
    norm_region: str = "ACROSS_CHANNELS"
    k: float = 1.0  # reference vintage hardcodes k=1; field accepted for compat


@dataclass
class MemoryDataParameter:
    batch_size: int = 0
    channels: int = 0
    height: int = 0
    width: int = 0


@dataclass
class MVNParameter:
    normalize_variance: bool = True
    across_channels: bool = False


@dataclass
class PoolingParameter:
    pool: str = "MAX"
    pad: int = 0
    pad_h: int = 0
    pad_w: int = 0
    kernel_size: int = 0
    kernel_h: int = 0
    kernel_w: int = 0
    stride: int = 1
    stride_h: int = 0
    stride_w: int = 0
    engine: str = "DEFAULT"
    global_pooling: bool = False


@dataclass
class PowerParameter:
    power: float = 1.0
    scale: float = 1.0
    shift: float = 0.0


@dataclass
class ReLUParameter:
    negative_slope: float = 0.0
    engine: str = "DEFAULT"


@dataclass
class SigmoidParameter:
    engine: str = "DEFAULT"


@dataclass
class SliceParameter:
    slice_dim: int = 1
    slice_point: List[int] = field(default_factory=list)
    _aliases = {"axis": "slice_dim"}


@dataclass
class SoftmaxParameter:
    engine: str = "DEFAULT"


@dataclass
class TanHParameter:
    engine: str = "DEFAULT"


@dataclass
class WindowDataParameter:
    source: str = ""
    scale: float = 1.0
    mean_file: str = ""
    batch_size: int = 0
    crop_size: int = 0
    mirror: bool = False
    fg_threshold: float = 0.5
    bg_threshold: float = 0.5
    fg_fraction: float = 0.25
    context_pad: int = 0
    crop_mode: str = "warp"


# --------------------------------------------------------------------------- #
# LayerParameter
# --------------------------------------------------------------------------- #

# V2 string type names -> V1 enum tokens (canonical internal keys).
V2_TYPE_TO_V1 = {
    "AbsVal": "ABSVAL", "Accuracy": "ACCURACY", "ArgMax": "ARGMAX", "BNLL": "BNLL",
    "Concat": "CONCAT", "ContrastiveLoss": "CONTRASTIVE_LOSS",
    "Convolution": "CONVOLUTION", "Data": "DATA", "Dropout": "DROPOUT",
    "DummyData": "DUMMY_DATA", "EuclideanLoss": "EUCLIDEAN_LOSS",
    "Eltwise": "ELTWISE", "Flatten": "FLATTEN", "HDF5Data": "HDF5_DATA",
    "HDF5Output": "HDF5_OUTPUT", "HingeLoss": "HINGE_LOSS", "Im2col": "IM2COL",
    "ImageData": "IMAGE_DATA", "InfogainLoss": "INFOGAIN_LOSS",
    "InnerProduct": "INNER_PRODUCT", "LRN": "LRN", "MemoryData": "MEMORY_DATA",
    "MultinomialLogisticLoss": "MULTINOMIAL_LOGISTIC_LOSS", "MVN": "MVN",
    "Pooling": "POOLING", "Power": "POWER", "ReLU": "RELU", "Sigmoid": "SIGMOID",
    "SigmoidCrossEntropyLoss": "SIGMOID_CROSS_ENTROPY_LOSS", "Silence": "SILENCE",
    "Softmax": "SOFTMAX", "SoftmaxWithLoss": "SOFTMAX_LOSS", "Split": "SPLIT",
    "Slice": "SLICE", "TanH": "TANH", "WindowData": "WINDOW_DATA",
    "Threshold": "THRESHOLD",
}
V1_TYPES = set(V2_TYPE_TO_V1.values()) | {"NONE"}


@dataclass
class ParamSpec:
    """V2-style per-blob spec; V1 blobs_lr/weight_decay lists normalize to this."""
    name: str = ""
    lr_mult: float = 1.0
    decay_mult: float = 1.0
    share_mode: str = "STRICT"


@dataclass
class LayerParameter:
    name: str = ""
    type: str = "NONE"
    bottom: List[str] = field(default_factory=list)
    top: List[str] = field(default_factory=list)
    include: List[NetStateRule] = field(default_factory=list)
    exclude: List[NetStateRule] = field(default_factory=list)
    blobs: List[BlobProto] = field(default_factory=list)
    param: List[Any] = field(default_factory=list)  # str (V1 names) or ParamSpec (V2)
    blob_share_mode: List[str] = field(default_factory=list)
    blobs_lr: List[float] = field(default_factory=list)
    weight_decay: List[float] = field(default_factory=list)
    loss_weight: List[float] = field(default_factory=list)

    accuracy_param: AccuracyParameter = field(default_factory=AccuracyParameter)
    argmax_param: ArgMaxParameter = field(default_factory=ArgMaxParameter)
    concat_param: ConcatParameter = field(default_factory=ConcatParameter)
    contrastive_loss_param: ContrastiveLossParameter = field(default_factory=ContrastiveLossParameter)
    convolution_param: ConvolutionParameter = field(default_factory=ConvolutionParameter)
    data_param: DataParameter = field(default_factory=DataParameter)
    dropout_param: DropoutParameter = field(default_factory=DropoutParameter)
    dummy_data_param: DummyDataParameter = field(default_factory=DummyDataParameter)
    eltwise_param: EltwiseParameter = field(default_factory=EltwiseParameter)
    hdf5_data_param: HDF5DataParameter = field(default_factory=HDF5DataParameter)
    hdf5_output_param: HDF5OutputParameter = field(default_factory=HDF5OutputParameter)
    hinge_loss_param: HingeLossParameter = field(default_factory=HingeLossParameter)
    image_data_param: ImageDataParameter = field(default_factory=ImageDataParameter)
    infogain_loss_param: InfogainLossParameter = field(default_factory=InfogainLossParameter)
    inner_product_param: InnerProductParameter = field(default_factory=InnerProductParameter)
    lrn_param: LRNParameter = field(default_factory=LRNParameter)
    memory_data_param: MemoryDataParameter = field(default_factory=MemoryDataParameter)
    mvn_param: MVNParameter = field(default_factory=MVNParameter)
    pooling_param: PoolingParameter = field(default_factory=PoolingParameter)
    power_param: PowerParameter = field(default_factory=PowerParameter)
    relu_param: ReLUParameter = field(default_factory=ReLUParameter)
    sigmoid_param: SigmoidParameter = field(default_factory=SigmoidParameter)
    softmax_param: SoftmaxParameter = field(default_factory=SoftmaxParameter)
    slice_param: SliceParameter = field(default_factory=SliceParameter)
    tanh_param: TanHParameter = field(default_factory=TanHParameter)
    threshold_param: ThresholdParameter = field(default_factory=ThresholdParameter)
    window_data_param: WindowDataParameter = field(default_factory=WindowDataParameter)
    transform_param: TransformationParameter = field(default_factory=TransformationParameter)
    blob_mode: str = "GLOBAL"  # Poseidon extension on LayerParameter level

    def canonical_type(self) -> str:
        t = self.type
        if t in V1_TYPES:
            return t
        if t in V2_TYPE_TO_V1:
            return V2_TYPE_TO_V1[t]
        raise PrototxtError(f"layer {self.name!r}: unknown type {t!r}")

    def param_spec(self, blob_index: int) -> ParamSpec:
        """Effective (lr_mult, decay_mult) for param blob i, merging V1/V2 forms."""
        spec = ParamSpec()
        v2 = [p for p in self.param if isinstance(p, ParamSpec)]
        names = [p for p in self.param if isinstance(p, str)]
        if v2:
            if blob_index < len(v2):
                spec = v2[blob_index]
        else:
            if blob_index < len(names):
                spec = ParamSpec(name=names[blob_index])
        if blob_index < len(self.blobs_lr):
            spec = dataclasses.replace(spec, lr_mult=self.blobs_lr[blob_index])
        if blob_index < len(self.weight_decay):
            spec = dataclasses.replace(spec, decay_mult=self.weight_decay[blob_index])
        return spec


def _build_layer(node: Node) -> LayerParameter:
    # `param` is polymorphic: V1 repeated string names, V2 ParamSpec submessages.
    params: List[Any] = []
    clean = Node()
    for k, v in node:
        if k == "param":
            params.append(build(ParamSpec, v) if isinstance(v, Node) else str(v))
        else:
            clean.add(k, v)
    layer = build(LayerParameter, clean)
    layer.param = params
    return layer


# --------------------------------------------------------------------------- #
# NetParameter / SolverParameter
# --------------------------------------------------------------------------- #

@dataclass
class NetParameter:
    name: str = ""
    layers: List[LayerParameter] = field(default_factory=list)
    input: List[str] = field(default_factory=list)
    input_dim: List[int] = field(default_factory=list)
    force_backward: bool = False
    state: NetState = field(default_factory=NetState)


def _build_net(node: Node) -> NetParameter:
    clean = Node()
    layer_nodes = []
    for k, v in node:
        if k in ("layers", "layer"):
            layer_nodes.append(v)
        else:
            clean.add(k, v)
    from .upgrade_v0 import net_needs_v0_upgrade, upgrade_v0_layers
    if net_needs_v0_upgrade(layer_nodes):
        layer_nodes = upgrade_v0_layers(layer_nodes)
    net = build(NetParameter, clean)
    net.layers = [_build_layer(n) for n in layer_nodes]
    for lp in net.layers:
        _upgrade_data_transform(lp)
    return net


def _upgrade_data_transform(lp: LayerParameter) -> None:
    """NetNeedsDataUpgrade/UpgradeNetDataTransformation: early V1 nets put
    scale/mean_file/crop_size/mirror inside the data-layer params; the
    pipeline reads transform_param, so migrate them (explicit
    transform_param fields win)."""
    src = {"DATA": lp.data_param, "IMAGE_DATA": lp.image_data_param,
           "WINDOW_DATA": lp.window_data_param}.get(
               lp.type if lp.type in V1_TYPES
               else V2_TYPE_TO_V1.get(lp.type, ""))
    if src is None:
        return
    t = lp.transform_param
    if getattr(src, "scale", 1.0) != 1.0 and t.scale == 1.0:
        t.scale = src.scale
    if getattr(src, "mean_file", "") and not t.mean_file:
        t.mean_file = src.mean_file
    if getattr(src, "crop_size", 0) and not t.crop_size:
        t.crop_size = src.crop_size
    if getattr(src, "mirror", False) and not t.mirror:
        t.mirror = src.mirror


@dataclass
class SolverParameter:
    net: str = ""
    net_param: Optional[NetParameter] = None
    train_net: str = ""
    test_net: List[str] = field(default_factory=list)
    train_net_param: Optional[NetParameter] = None
    test_net_param: List[NetParameter] = field(default_factory=list)
    train_state: NetState = field(default_factory=lambda: NetState(phase="TRAIN"))
    test_state: List[NetState] = field(default_factory=list)
    test_iter: List[int] = field(default_factory=list)
    test_interval: int = 0
    test_compute_loss: bool = False
    test_initialization: bool = True
    base_lr: float = 0.0
    display: int = 0
    max_iter: int = 0
    lr_policy: str = "fixed"
    gamma: float = 0.0
    power: float = 0.0
    momentum: float = 0.0
    weight_decay: float = 0.0
    regularization_type: str = "L2"
    stepsize: int = 0
    stepvalue: List[int] = field(default_factory=list)
    snapshot: int = 0
    snapshot_prefix: str = ""
    snapshot_diff: bool = False
    snapshot_after_train: bool = True
    solver_mode: str = "GPU"
    device_id: str = "0"
    random_seed: int = -1
    solver_type: str = "SGD"
    delta: float = 1e-8
    debug_info: bool = False
    iter_size: int = 1


def _build_solver(node: Node) -> SolverParameter:
    clean = Node()
    net_param = None
    train_net_param = None
    test_net_params: List[Node] = []
    for k, v in node:
        if k == "net_param":
            net_param = v
        elif k == "train_net_param":
            train_net_param = v
        elif k == "test_net_param":
            test_net_params.append(v)
        else:
            clean.add(k, v)
    solver = build(SolverParameter, clean)
    if net_param is not None:
        solver.net_param = _build_net(net_param)
    if train_net_param is not None:
        solver.train_net_param = _build_net(train_net_param)
    solver.test_net_param = [_build_net(n) for n in test_net_params]
    return solver


def load_net(path: str) -> NetParameter:
    return _build_net(parse_file(path))


def load_net_from_string(text: str) -> NetParameter:
    return _build_net(parse(text))


def load_solver(path: str) -> SolverParameter:
    return _build_solver(parse_file(path))


def load_solver_from_string(text: str) -> SolverParameter:
    return _build_solver(parse(text))


# --------------------------------------------------------------------------- #
# Serialization back to prototxt (zoo compatibility: our programmatic models
# export to text Caffe itself would parse).
# --------------------------------------------------------------------------- #

# Fields whose values are enum identifiers (emitted unquoted); everything else
# stringy is a quoted string.
_ENUM_FIELDS = {
    "LayerParameter": {"type", "blob_mode", "blob_share_mode"},
    "BlobProto": {"blob_mode"},
    "PoolingParameter": {"pool", "engine"},
    "ConvolutionParameter": {"engine"},
    "ReLUParameter": {"engine"},
    "SigmoidParameter": {"engine"},
    "SoftmaxParameter": {"engine"},
    "TanHParameter": {"engine"},
    "EltwiseParameter": {"operation"},
    "HingeLossParameter": {"norm"},
    "LRNParameter": {"norm_region"},
    "DataParameter": {"backend"},
    "NetState": {"phase"},
    "NetStateRule": {"phase"},
    "SolverParameter": {"solver_mode", "solver_type"},
}


def _is_default(value: Any, default: Any) -> bool:
    try:
        return value == default
    except Exception:
        return False


def to_node(msg: Any) -> Node:
    """Generic dataclass -> Node, omitting default-valued fields."""
    from .prototxt import Enum
    cls_name = type(msg).__name__
    enum_fields = _ENUM_FIELDS.get(cls_name, set())
    defaults = type(msg)()
    node = Node()

    def emit(name: str, value: Any) -> None:
        if dataclasses.is_dataclass(value):
            sub = to_node(value)
            if sub.fields:
                node.add(name, sub)
        elif isinstance(value, str) and name in enum_fields:
            node.add(name, Enum(value))
        else:
            node.add(name, value)

    for f in dataclasses.fields(msg):
        value = getattr(msg, f.name)
        if isinstance(value, list):
            if f.name == "param" and cls_name == "LayerParameter":
                for p in value:
                    emit("param", p)
                continue
            for v in value:
                emit(f.name, v)
        else:
            default = getattr(defaults, f.name, None)
            if dataclasses.is_dataclass(value):
                if value != default:
                    emit(f.name, value)
            elif not _is_default(value, default):
                emit(f.name, value)
    return node


def net_to_prototxt(net: NetParameter) -> str:
    from .prototxt import dumps
    node = Node()
    if net.name:
        node.add("name", net.name)
    for i, inp in enumerate(net.input):
        node.add("input", inp)
    for d in net.input_dim:
        node.add("input_dim", d)
    if net.force_backward:
        node.add("force_backward", True)
    for lp in net.layers:
        node.add("layers", to_node(lp))
    return dumps(node) + "\n"


def solver_to_prototxt(sp: SolverParameter) -> str:
    from .prototxt import dumps
    return dumps(to_node(sp)) + "\n"
