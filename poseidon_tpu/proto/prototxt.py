"""Prototxt (protobuf text-format) parser and printer.

Parses the Caffe text format used by the reference's model zoo
(``/root/reference/models/*/*.prototxt``, schema ``src/caffe/proto/caffe.proto``)
into a generic tree of :class:`Node` objects, without requiring protoc or the
protobuf runtime. Typed adaptation into dataclasses lives in ``messages.py``.

Grammar (the subset the text format actually uses):

    message := field*
    field   := IDENT ':' scalar | IDENT '{' message '}' | IDENT ':' '{' message '}'
    scalar  := NUMBER | STRING | BOOL | ENUM_IDENT | '[' scalar (',' scalar)* ']'

Repeated fields appear as repeated keys.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field as dc_field
from typing import Any, Iterator, List, Tuple, Union


class PrototxtError(ValueError):
    pass


@dataclass
class Node:
    """A parsed message: ordered multimap of field name -> scalar or Node."""

    fields: List[Tuple[str, Any]] = dc_field(default_factory=list)

    def add(self, name: str, value: Any) -> None:
        self.fields.append((name, value))

    def get_all(self, name: str) -> List[Any]:
        return [v for k, v in self.fields if k == name]

    def get(self, name: str, default: Any = None) -> Any:
        for k, v in self.fields:
            if k == name:
                return v
        return default

    def has(self, name: str) -> bool:
        return any(k == name for k, _ in self.fields)

    def keys(self) -> List[str]:
        return [k for k, _ in self.fields]

    def __iter__(self) -> Iterator[Tuple[str, Any]]:
        return iter(self.fields)


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|\#[^\n]*)
  | (?P<string>"(?:\\.|[^"\\])*"|'(?:\\.|[^'\\])*')
  | (?P<number>[-+]?(?:\d+\.\d*|\.\d+|\d+)(?:[eE][-+]?\d+)?|[-+]?inf|nan)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<punct>[{}:\[\],;])
    """,
    re.VERBOSE,
)

_ESCAPES = {
    "n": "\n", "t": "\t", "r": "\r", "\\": "\\", "'": "'", '"': '"',
    "a": "\a", "b": "\b", "f": "\f", "v": "\v", "0": "\0",
}


def _unquote(tok: str) -> str:
    body = tok[1:-1]
    out = []
    i = 0
    while i < len(body):
        c = body[i]
        if c == "\\" and i + 1 < len(body):
            out.append(_ESCAPES.get(body[i + 1], body[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def tokenize(text: str) -> List[Tuple[str, str]]:
    tokens: List[Tuple[str, str]] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            line = text.count("\n", 0, pos) + 1
            raise PrototxtError(f"line {line}: unexpected character {text[pos]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        tokens.append((kind, m.group()))
    return tokens


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.tokens = tokens
        self.i = 0

    def peek(self) -> Union[Tuple[str, str], None]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def next(self) -> Tuple[str, str]:
        tok = self.peek()
        if tok is None:
            raise PrototxtError("unexpected end of input")
        self.i += 1
        return tok

    def expect_punct(self, p: str) -> None:
        kind, val = self.next()
        if kind != "punct" or val != p:
            raise PrototxtError(f"expected {p!r}, got {val!r}")

    def parse_message(self, terminator: Union[str, None]) -> Node:
        node = Node()
        while True:
            tok = self.peek()
            if tok is None:
                if terminator is None:
                    return node
                raise PrototxtError(f"unexpected end of input, expected {terminator!r}")
            if tok == ("punct", terminator):
                self.next()
                return node
            kind, name = self.next()
            if kind != "ident":
                raise PrototxtError(f"expected field name, got {name!r}")
            tok = self.peek()
            if tok == ("punct", "{"):
                self.next()
                node.add(name, self.parse_message("}"))
            elif tok == ("punct", ":"):
                self.next()
                tok = self.peek()
                if tok == ("punct", "{"):
                    self.next()
                    node.add(name, self.parse_message("}"))
                elif tok == ("punct", "["):
                    self.next()
                    for v in self.parse_list():
                        node.add(name, v)
                else:
                    node.add(name, self.parse_scalar())
            else:
                raise PrototxtError(f"expected ':' or '{{' after {name!r}")
            # optional separators between fields
            while self.peek() in (("punct", ","), ("punct", ";")):
                self.next()

    def parse_list(self) -> List[Any]:
        out: List[Any] = []
        if self.peek() == ("punct", "]"):
            self.next()
            return out
        while True:
            out.append(self.parse_scalar())
            kind, val = self.next()
            if (kind, val) == ("punct", "]"):
                return out
            if (kind, val) != ("punct", ","):
                raise PrototxtError(f"expected ',' or ']' in list, got {val!r}")

    def parse_scalar(self) -> Any:
        kind, val = self.next()
        if kind == "string":
            s = _unquote(val)
            # adjacent string literals concatenate (proto text format rule)
            while self.peek() is not None and self.peek()[0] == "string":
                s += _unquote(self.next()[1])
            return s
        if kind == "number":
            low = val.lower()
            if "inf" in low or "nan" in low or "." in val or "e" in low:
                return float(val)
            return int(val)
        if kind == "ident":
            if val == "true":
                return True
            if val == "false":
                return False
            return val  # enum identifier, kept as string
        raise PrototxtError(f"expected value, got {val!r}")


def parse(text: str) -> Node:
    return _Parser(tokenize(text)).parse_message(None)


def parse_file(path: str) -> Node:
    with open(path, "r") as f:
        return parse(f.read())


def _format_scalar(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, str):
        # Heuristic: enum identifiers round-trip unquoted only via Node printing
        # of values stored as Enum marker; plain strings are quoted.
        escaped = v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
        return f'"{escaped}"'
    if isinstance(v, float):
        return repr(v)
    return str(v)


class Enum(str):
    """Marker for enum identifiers so dumps() emits them unquoted."""


def dumps(node: Node, indent: int = 0) -> str:
    pad = "  " * indent
    lines = []
    for name, value in node:
        if isinstance(value, Node):
            lines.append(f"{pad}{name} {{")
            lines.append(dumps(value, indent + 1))
            lines.append(f"{pad}}}")
        elif isinstance(value, Enum):
            lines.append(f"{pad}{name}: {value}")
        else:
            lines.append(f"{pad}{name}: {_format_scalar(value)}")
    return "\n".join(l for l in lines if l != "")
