from .prototxt import Node, PrototxtError, parse, parse_file, dumps  # noqa: F401
from .messages import (  # noqa: F401
    LayerParameter, NetParameter, NetState, SolverParameter,
    load_net, load_net_from_string, load_solver, load_solver_from_string,
)
