"""V0 legacy prototxt upgrade (upgrade_proto.cpp:15-506 semantics).

The oldest Caffe format nests a flat ``V0LayerParameter`` under each
connection: ``layers { layer { name: "c1" type: "conv" num_output: 96 ... }
bottom: "data" top: "c1" }``. The reference upgrades these in two passes
(``UpgradeV0Net``):

1. ``UpgradeV0PaddingLayers`` — V0 modeled padding as a separate "padding"
   layer feeding a conv/pool; the upgrade deletes it, folds its ``pad`` into
   the consumer, and rewires the consumer's bottom to the padding layer's
   input.
2. ``UpgradeLayerParameter`` — scatter the flat V0 fields into the typed V1
   parameter messages (num_output -> convolution/inner_product_param, pad/
   kernelsize/stride -> convolution/pooling_param, scale/meanfile/cropsize/
   mirror -> transform_param, source/batchsize -> the per-backend data
   params, det_* -> window_data_param, ...), and map the lowercase type
   strings to V1 enum names (``UpgradeV0LayerType``).

Scoped to the fields the reference's V0 path actually rewrites; unknown V0
fields raise rather than silently dropping (the reference logs
is_fully_compatible=false — we fail loudly instead).
"""

from __future__ import annotations

from typing import List

from .prototxt import Node, PrototxtError

# UpgradeV0LayerType (upgrade_proto.cpp:453-506)
V0_TYPE_TO_V1 = {
    "accuracy": "ACCURACY",
    "bnll": "BNLL",
    "concat": "CONCAT",
    "conv": "CONVOLUTION",
    "data": "DATA",
    "dropout": "DROPOUT",
    "euclidean_loss": "EUCLIDEAN_LOSS",
    "flatten": "FLATTEN",
    "hdf5_data": "HDF5_DATA",
    "hdf5_output": "HDF5_OUTPUT",
    "im2col": "IM2COL",
    "images": "IMAGE_DATA",
    "infogain_loss": "INFOGAIN_LOSS",
    "innerproduct": "INNER_PRODUCT",
    "lrn": "LRN",
    "multinomial_logistic_loss": "MULTINOMIAL_LOGISTIC_LOSS",
    "pool": "POOLING",
    "relu": "RELU",
    "sigmoid": "SIGMOID",
    "softmax": "SOFTMAX",
    "softmax_loss": "SOFTMAX_LOSS",
    "split": "SPLIT",
    "tanh": "TANH",
    "window_data": "WINDOW_DATA",
}

# flat V0 field -> (sub-message field name, {v0_type: param block name})
# (UpgradeLayerParameter's long if-chain, upgrade_proto.cpp:139-449)
_SCATTER = {
    "num_output": ("num_output", {"conv": "convolution_param",
                                  "innerproduct": "inner_product_param"}),
    "biasterm": ("bias_term", {"conv": "convolution_param",
                               "innerproduct": "inner_product_param"}),
    "weight_filler": ("weight_filler", {"conv": "convolution_param",
                                        "innerproduct":
                                        "inner_product_param"}),
    "bias_filler": ("bias_filler", {"conv": "convolution_param",
                                    "innerproduct": "inner_product_param"}),
    "pad": ("pad", {"conv": "convolution_param", "pool": "pooling_param"}),
    "kernelsize": ("kernel_size", {"conv": "convolution_param",
                                   "pool": "pooling_param"}),
    "group": ("group", {"conv": "convolution_param"}),
    "stride": ("stride", {"conv": "convolution_param",
                          "pool": "pooling_param"}),
    "pool": ("pool", {"pool": "pooling_param"}),
    "dropout_ratio": ("dropout_ratio", {"dropout": "dropout_param"}),
    "local_size": ("local_size", {"lrn": "lrn_param"}),
    "alpha": ("alpha", {"lrn": "lrn_param"}),
    "beta": ("beta", {"lrn": "lrn_param"}),
    "k": ("k", {"lrn": "lrn_param"}),
    "source": ("source", {"data": "data_param",
                          "hdf5_data": "hdf5_data_param",
                          "images": "image_data_param",
                          "window_data": "window_data_param",
                          "infogain_loss": "infogain_loss_param"}),
    "batchsize": ("batch_size", {"data": "data_param",
                                 "hdf5_data": "hdf5_data_param",
                                 "images": "image_data_param",
                                 "window_data": "window_data_param"}),
    "rand_skip": ("rand_skip", {"data": "data_param",
                                "images": "image_data_param"}),
    "shuffle_images": ("shuffle", {"images": "image_data_param"}),
    "new_height": ("new_height", {"images": "image_data_param"}),
    "new_width": ("new_width", {"images": "image_data_param"}),
    "concat_dim": ("concat_dim", {"concat": "concat_param"}),
    "det_fg_threshold": ("fg_threshold", {"window_data":
                                          "window_data_param"}),
    "det_bg_threshold": ("bg_threshold", {"window_data":
                                          "window_data_param"}),
    "det_fg_fraction": ("fg_fraction", {"window_data": "window_data_param"}),
    "det_context_pad": ("context_pad", {"window_data": "window_data_param"}),
    "det_crop_mode": ("crop_mode", {"window_data": "window_data_param"}),
}

# scattered into transform_param regardless of layer type
_TRANSFORM = {"scale": "scale", "meanfile": "mean_file",
              "cropsize": "crop_size", "mirror": "mirror"}

# copied through at the layer level
_PASSTHROUGH = {"name", "blobs", "blobs_lr", "weight_decay", "blob_mode"}


def net_needs_v0_upgrade(layer_nodes: List[Node]) -> bool:
    """NetNeedsUpgrade: any connection with a nested ``layer`` block."""
    return any(n.has("layer") for n in layer_nodes)


def upgrade_v0_layers(layer_nodes: List[Node]) -> List[Node]:
    """Both passes, at the parse-tree level: fold padding layers, then
    rewrite each V0 connection into a V1-shaped Node that the normal
    ``_build_layer`` path consumes."""
    return [_upgrade_layer(n) for n in _fold_padding(layer_nodes)]


def _v0_type(conn: Node) -> str:
    layer = conn.get("layer")
    return str(layer.get("type", "")) if layer is not None else ""


def _fold_padding(layer_nodes: List[Node]) -> List[Node]:
    """UpgradeV0PaddingLayers (upgrade_proto.cpp:51-110): drop "padding"
    layers, push their pad into the consuming conv/pool, rewire bottoms."""
    if not any(_v0_type(n) == "padding" for n in layer_nodes):
        return layer_nodes
    # blob name -> producing layer node (last writer wins, like the ref map)
    producer = {}
    out: List[Node] = []
    for conn in layer_nodes:
        lp = conn.get("layer")
        if _v0_type(conn) != "padding":
            new_conn = Node()
            for k, v in conn:
                if k != "bottom":
                    new_conn.add(k, v)
            for bottom in conn.get_all("bottom"):
                src = producer.get(str(bottom))
                if src is not None and _v0_type(src) == "padding":
                    t = _v0_type(conn)
                    if t not in ("conv", "pool"):
                        raise PrototxtError(
                            f"padding layer feeds non-conv/pool layer "
                            f"type {t!r} (undefined in Caffe)")
                    if len(src.get_all("bottom")) != 1 or \
                            len(src.get_all("top")) != 1:
                        raise PrototxtError(
                            "padding layer must have one bottom and one top")
                    # the consumer must be single-bottom too
                    # (upgrade_proto.cpp CHECK_EQ(bottom_size(), 1)):
                    # folding pad into a multi-input layer is undefined
                    if len(conn.get_all("bottom")) != 1:
                        raise PrototxtError(
                            f"layer consuming padding output must have "
                            f"exactly one bottom, got "
                            f"{len(conn.get_all('bottom'))}")
                    lp.add("pad", src.get("layer").get("pad"))
                    new_conn.add("bottom", src.get("bottom"))
                else:
                    new_conn.add("bottom", bottom)
            out.append(new_conn)
            conn = new_conn
        for top in conn.get_all("top"):
            producer[str(top)] = conn
    return out


def _upgrade_layer(conn: Node) -> Node:
    """UpgradeLayerParameter for one connection Node -> V1-shaped Node."""
    if not conn.has("layer"):
        return conn  # already V1 (mixed nets upgrade per layer)
    v0 = conn.get("layer")
    out = Node()
    for k, v in conn:
        if k != "layer":
            out.add(k, v)  # bottom / top / (stray V1 fields)

    vtype = str(v0.get("type", ""))
    params: dict = {}       # param block name -> Node
    transform: Node = Node()

    def block(name: str) -> Node:
        if name not in params:
            params[name] = Node()
        return params[name]

    for k, v in v0:
        if k == "type":
            if vtype not in V0_TYPE_TO_V1:
                raise PrototxtError(f"unknown V0 layer type {vtype!r}")
            out.add("type", V0_TYPE_TO_V1[vtype])
        elif k in _PASSTHROUGH:
            out.add(k, v)
        elif k in _TRANSFORM:
            transform.add(_TRANSFORM[k], v)
        elif k in _SCATTER:
            field_name, by_type = _SCATTER[k]
            if vtype not in by_type:
                raise PrototxtError(
                    f"V0 field {k!r} is not valid for layer type {vtype!r}")
            block(by_type[vtype]).add(field_name, v)
        elif k == "hdf5_output_param":
            out.add("hdf5_output_param", v)
        else:
            raise PrototxtError(
                f"V0 layer field {k!r} has no upgrade mapping")

    for name, node in params.items():
        out.add(name, node)
    if transform.fields:
        out.add("transform_param", transform)
    return out
