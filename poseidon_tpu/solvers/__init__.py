from .updates import SolverState, init_state, learning_rate, make_update_fn  # noqa: F401
