"""Caffe-exact optimizer update rules as pure, jit-able transforms.

Spec: ``/root/reference/src/caffe/solver.cpp``
- LR policies fixed/step/exp/inv/poly    (GetLearningRate, solver.cpp:758-790)
- SGD:      g' = g + decay*reg(w); h = m*h + local_lr*g'; w -= h
            (ComputeUpdateValue, solver.cpp:815-900)
- Nesterov: h' = m*h + local_lr*g'; w -= (1+m)*h' - m*h     (solver.cpp:1013)
- AdaGrad:  h += g'^2; w -= local_lr * g' / (sqrt(h)+delta) (solver.cpp:1240)
Regularization: L2 adds decay*w to the gradient, L1 adds decay*sign(w);
local_lr = base_rate * lr_mult, local_decay = weight_decay * decay_mult.

Iteration is carried as a traced scalar so the whole update compiles into the
training step; LR schedules use only XLA-friendly math.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp

from ..proto.messages import SolverParameter


def learning_rate(sp: SolverParameter, it: jax.Array) -> jax.Array:
    it = it.astype(jnp.float32)
    policy = sp.lr_policy
    base = jnp.float32(sp.base_lr)
    if policy == "fixed":
        return base
    if policy == "step":
        current_step = jnp.floor(it / sp.stepsize)
        return base * jnp.power(sp.gamma, current_step)
    if policy == "exp":
        return base * jnp.power(sp.gamma, it)
    if policy == "inv":
        return base * jnp.power(1.0 + sp.gamma * it, -sp.power)
    if policy == "poly":
        return base * jnp.power(1.0 - it / sp.max_iter, sp.power)
    if policy == "sigmoid":
        return base * (1.0 / (1.0 + jnp.exp(-sp.gamma * (it - sp.stepsize))))
    if policy == "multistep":
        # number of stepvalues passed so far
        steps = jnp.asarray(sp.stepvalue, jnp.float32)
        current_step = jnp.sum(it >= steps).astype(jnp.float32)
        return base * jnp.power(sp.gamma, current_step)
    raise ValueError(f"unknown lr_policy {policy!r}")


class SolverState(NamedTuple):
    it: jax.Array           # current iteration (traced scalar, int32)
    history: Dict           # momentum / accumulated squared grads, like params


def _regularized(g, w, local_decay: float, reg_type: str):
    if local_decay == 0.0:
        return g
    if reg_type == "L2":
        return g + local_decay * w
    if reg_type == "L1":
        return g + local_decay * jnp.sign(w)
    raise ValueError(f"unknown regularization_type {reg_type!r}")


def _leafwise_update(sp: SolverParameter, mults, rate, params, grads,
                     history):
    """One optimizer step over a per-leaf tree (the classic path; also the
    per-leaf remainder — SFB/TOPK/LOCAL opt-outs — of an arena step)."""
    solver_type = sp.solver_type
    momentum = sp.momentum
    weight_decay = sp.weight_decay
    reg_type = sp.regularization_type
    delta = sp.delta
    new_params = {}
    new_hist = {}
    for lname, lparams in params.items():
        new_params[lname] = {}
        new_hist[lname] = {}
        for pname, w in lparams.items():
            g = grads[lname][pname]
            lr_mult, decay_mult = mults[lname][pname]
            local_rate = rate * lr_mult
            local_decay = weight_decay * decay_mult
            h = history[lname][pname]
            g = _regularized(g.astype(jnp.float32), w, local_decay, reg_type)
            if solver_type == "SGD":
                h_new = momentum * h + local_rate * g
                step = h_new
            elif solver_type == "NESTEROV":
                h_new = momentum * h + local_rate * g
                step = (1.0 + momentum) * h_new - momentum * h
            elif solver_type == "ADAGRAD":
                h_new = h + g * g
                step = local_rate * g / (jnp.sqrt(h_new) + delta)
            else:
                raise ValueError(f"unknown solver_type {solver_type!r}")
            new_params[lname][pname] = (w - step).astype(w.dtype)
            new_hist[lname][pname] = h_new
    return new_params, new_hist


def make_update_fn(sp: SolverParameter, mults: Dict[str, Dict[str, tuple]]):
    """Build update(params, grads, state) -> (params, state).

    ``mults`` maps layer -> param name -> (lr_mult, decay_mult), from the
    net's ParamDefs (the reference's blobs_lr / weight_decay lists).
    """
    def update(params, grads, state: SolverState):
        # scoped so one profiled step attributes the whole optimizer pass
        # as "optimizer_update" instead of leaking per-leaf fusions into
        # the attribution residual (runtime/attribution.py)
        with jax.named_scope("optimizer_update"):
            rate = learning_rate(sp, state.it)
            new_params, new_hist = _leafwise_update(sp, mults, rate, params,
                                                    grads, state.history)
            return new_params, SolverState(it=state.it + 1, history=new_hist)

    return update


def make_flat_update_rule(sp: SolverParameter):
    """The fused flat update rule with the multiplier vectors as ARGUMENTS:
    fused(flat_w, flat_g, flat_h, rate, lr_vec, decay_vec) ->
    (flat_w', flat_h'). ``make_fused_update_fn`` binds the arena layout's
    precomputed full-buffer vectors; the SPMD sharded step
    (parallel/spmd.py) instead feeds each device its fsdp SHARD of the
    vectors, so the update touches 1/fsdp of the buffer per device with
    identical elementwise math."""
    solver_type = sp.solver_type
    momentum = sp.momentum
    reg_type = sp.regularization_type
    delta = sp.delta
    if solver_type not in ("SGD", "NESTEROV", "ADAGRAD"):
        raise ValueError(f"unknown solver_type {solver_type!r}")
    if reg_type not in ("L2", "L1"):
        raise ValueError(f"unknown regularization_type {reg_type!r}")

    def fused(flat_w, flat_g, flat_h, rate, lr_vec, decay_vec):
        local_rate = rate * lr_vec
        g = flat_g.astype(jnp.float32)
        if solver_type == "SGD" and reg_type == "L2":
            from ..ops.pallas_kernels import maybe_fused_sgd
            r = maybe_fused_sgd(flat_w, g, flat_h, local_rate, decay_vec,
                                momentum)
            if r is not None:
                return r
        reg = flat_w if reg_type == "L2" else jnp.sign(flat_w)
        # the elementwise form of _regularized's local_decay == 0 skip:
        # untouched gradient where the segment's decay is zero
        g = jnp.where(decay_vec == 0.0, g, g + decay_vec * reg)
        if solver_type == "SGD":
            h_new = momentum * flat_h + local_rate * g
            step = h_new
        elif solver_type == "NESTEROV":
            h_new = momentum * flat_h + local_rate * g
            step = (1.0 + momentum) * h_new - momentum * flat_h
        else:  # ADAGRAD
            h_new = flat_h + g * g
            step = local_rate * g / (jnp.sqrt(h_new) + delta)
        return (flat_w - step).astype(flat_w.dtype), h_new

    return fused


def make_fused_update_fn(sp: SolverParameter, layout):
    """One fused elementwise pass over the flat arena buffer — the same
    SGD/Nesterov/AdaGrad rule as ``_leafwise_update``, with the per-leaf
    lr_mult / decay_mult scalars expanded into the layout's precomputed
    arena-resident multiplier segments. Bit-identical to the per-leaf loop:
    every scalar is rounded to f32 exactly where the per-leaf path rounds
    it (see ArenaLayout.mult_vectors), the zero-decay skip becomes an
    elementwise select of the untouched gradient, and the operation order
    is unchanged.

    Returns fused(flat_w, flat_g, flat_h, rate) -> (flat_w', flat_h').
    The SGD+momentum+L2 shape (the Caffe default) can additionally route
    through the Pallas kernel variant (ops/pallas_kernels.fused_sgd) —
    opt-in via POSEIDON_PALLAS_UPDATE=1, same math, one VMEM pass."""
    rule = make_flat_update_rule(sp)
    lr_np, decay_np = layout.mult_vectors(sp.weight_decay)

    def fused(flat_w, flat_g, flat_h, rate):
        return rule(flat_w, flat_g, flat_h, rate, jnp.asarray(lr_np),
                    jnp.asarray(decay_np))

    return fused


def make_arena_update_fn(sp: SolverParameter, mults, layout):
    """The arena step's optimizer update: the fused flat pass for arena
    leaves + the per-leaf rule for opt-outs, one iteration bump.

    update(flat_w, flat_g, excl_params, excl_grads, state)
        -> (new_params_tree, new_state)

    ``state.history`` is the CANONICAL per-leaf tree at every step boundary
    (snapshots never see the packed form); it is packed here for the fused
    pass and unpacked into the returned state."""
    fused = make_fused_update_fn(sp, layout)

    def update(flat_w, flat_g, excl_params, excl_grads, state: SolverState):
        with jax.named_scope("optimizer_update"):
            rate = learning_rate(sp, state.it)
            flat_h = layout.pack(state.history)
            new_flat_w, new_flat_h = fused(flat_w, flat_g, flat_h, rate)
            excl_hist = layout.residual(state.history)
            new_excl, new_excl_hist = _leafwise_update(
                sp, mults, rate, excl_params, excl_grads, excl_hist)
            new_params = layout.merge(layout.unpack(new_flat_w), new_excl)
            new_hist = layout.merge(layout.unpack(new_flat_h), new_excl_hist)
            return new_params, SolverState(it=state.it + 1, history=new_hist)

    return update


def init_state(params) -> SolverState:
    history = jax.tree_util.tree_map(jnp.zeros_like, params)
    return SolverState(it=jnp.zeros((), jnp.int32), history=history)
