"""Caffe-exact optimizer update rules as pure, jit-able transforms.

Spec: ``/root/reference/src/caffe/solver.cpp``
- LR policies fixed/step/exp/inv/poly    (GetLearningRate, solver.cpp:758-790)
- SGD:      g' = g + decay*reg(w); h = m*h + local_lr*g'; w -= h
            (ComputeUpdateValue, solver.cpp:815-900)
- Nesterov: h' = m*h + local_lr*g'; w -= (1+m)*h' - m*h     (solver.cpp:1013)
- AdaGrad:  h += g'^2; w -= local_lr * g' / (sqrt(h)+delta) (solver.cpp:1240)
Regularization: L2 adds decay*w to the gradient, L1 adds decay*sign(w);
local_lr = base_rate * lr_mult, local_decay = weight_decay * decay_mult.

Iteration is carried as a traced scalar so the whole update compiles into the
training step; LR schedules use only XLA-friendly math.
"""

from __future__ import annotations

from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp

from ..proto.messages import SolverParameter


def learning_rate(sp: SolverParameter, it: jax.Array) -> jax.Array:
    it = it.astype(jnp.float32)
    policy = sp.lr_policy
    base = jnp.float32(sp.base_lr)
    if policy == "fixed":
        return base
    if policy == "step":
        current_step = jnp.floor(it / sp.stepsize)
        return base * jnp.power(sp.gamma, current_step)
    if policy == "exp":
        return base * jnp.power(sp.gamma, it)
    if policy == "inv":
        return base * jnp.power(1.0 + sp.gamma * it, -sp.power)
    if policy == "poly":
        return base * jnp.power(1.0 - it / sp.max_iter, sp.power)
    if policy == "sigmoid":
        return base * (1.0 / (1.0 + jnp.exp(-sp.gamma * (it - sp.stepsize))))
    if policy == "multistep":
        # number of stepvalues passed so far
        steps = jnp.asarray(sp.stepvalue, jnp.float32)
        current_step = jnp.sum(it >= steps).astype(jnp.float32)
        return base * jnp.power(sp.gamma, current_step)
    raise ValueError(f"unknown lr_policy {policy!r}")


class SolverState(NamedTuple):
    it: jax.Array           # current iteration (traced scalar, int32)
    history: Dict           # momentum / accumulated squared grads, like params


def _regularized(g, w, local_decay: float, reg_type: str):
    if local_decay == 0.0:
        return g
    if reg_type == "L2":
        return g + local_decay * w
    if reg_type == "L1":
        return g + local_decay * jnp.sign(w)
    raise ValueError(f"unknown regularization_type {reg_type!r}")


def make_update_fn(sp: SolverParameter, mults: Dict[str, Dict[str, tuple]]):
    """Build update(params, grads, state) -> (params, state).

    ``mults`` maps layer -> param name -> (lr_mult, decay_mult), from the
    net's ParamDefs (the reference's blobs_lr / weight_decay lists).
    """
    solver_type = sp.solver_type
    momentum = sp.momentum
    weight_decay = sp.weight_decay
    reg_type = sp.regularization_type
    delta = sp.delta

    def update(params, grads, state: SolverState):
        rate = learning_rate(sp, state.it)
        new_params = {}
        new_hist = {}
        for lname, lparams in params.items():
            new_params[lname] = {}
            new_hist[lname] = {}
            for pname, w in lparams.items():
                g = grads[lname][pname]
                lr_mult, decay_mult = mults[lname][pname]
                local_rate = rate * lr_mult
                local_decay = weight_decay * decay_mult
                h = state.history[lname][pname]
                g = _regularized(g.astype(jnp.float32), w, local_decay, reg_type)
                if solver_type == "SGD":
                    h_new = momentum * h + local_rate * g
                    step = h_new
                elif solver_type == "NESTEROV":
                    h_new = momentum * h + local_rate * g
                    step = (1.0 + momentum) * h_new - momentum * h
                elif solver_type == "ADAGRAD":
                    h_new = h + g * g
                    step = local_rate * g / (jnp.sqrt(h_new) + delta)
                else:
                    raise ValueError(f"unknown solver_type {solver_type!r}")
                new_params[lname][pname] = (w - step).astype(w.dtype)
                new_hist[lname][pname] = h_new
        return new_params, SolverState(it=state.it + 1, history=new_hist)

    return update


def init_state(params) -> SolverState:
    history = jax.tree_util.tree_map(jnp.zeros_like, params)
    return SolverState(it=jnp.zeros((), jnp.int32), history=history)
