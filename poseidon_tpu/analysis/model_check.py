"""Bounded model checking of the SSP/managed-communication protocol.

The async-SSP tier's correctness story now spans four interacting
mechanisms — durable-clock read gates (PR 12), magnitude-prioritized
partial pushes with a residual force-flushed every ``staleness+1`` clocks
(PR 12), elastic admit/retire (PR 6), and exactly-once replay over a
per-worker seq high-water mark (PR 1) — and its hardest bugs are
*interleaving* bugs chaos tests sample but never enumerate. This module
states the protocol as a small pure-Python transition system and
EXHAUSTIVELY explores every interleaving for bounded configurations
(2–3 workers x staleness 0–2 x one admit + one retire + a crash/rejoin,
lost-ack and leader-failover schedule), checking on every edge:

- **No deadlock**: in every reachable non-terminal state some action is
  enabled (a gate that can never unblock is found, with its trace).
- **Durable-clock sandwich**: ``durable <= raw <= durable + s + 1`` for
  every member, always — the bound the partial-push machinery promises.
- **Exactly-once**: a (worker, clock) delta is applied at most once; a
  replayed push whose ack was lost must dedup, never re-apply.
- **Read-gate safety**: whenever a gate ADMITS a reader at clock ``c``,
  every gated-on peer's DURABLE clock is ``>= c - s - 1`` — the SSP
  contract stated over bytes actually in the anchor, not raw clocks.
- **Failover completeness** (two-tier fabric, parallel/fabric.py): a
  worker here is granularity-agnostic — under ``max_failovers > 0`` it
  models a whole SPMD slice whose LEADER process dies mid-window. A
  correct successor re-derives the acked floor from the service and
  carries the ledgered residual; the seeded mutations drop the residual
  (``leader_failover_loses_residual`` — caught by the completeness
  monitor at the next full flush) or restart the seq stream
  (``double_apply_across_leaders`` — caught by the exactly-once
  monitor).

The gate *predicate* and the invariant *monitor* are deliberately
separate code paths, so a seeded mutation of the predicate (gate on raw
clocks instead of durable — exactly the bug PR 12 existed to prevent) is
CAUGHT by the monitor rather than silently agreed with. ``selftest``
verifies every seeded mutation is caught; a mutation the checker stops
catching is a regression in the checker itself.

Model states are canonical tuples, hashed into a visited set; DFS visits
each state once, so the reported ``states`` count is the exact size of
the reachable state space — a regression pin in its own right (a model
edit that silently prunes interleavings shows up as a count change).

**Scope / non-goals** (kept honest by the trace-conformance harness
below): the model abstracts payload *values* away (a delta is a token),
models the network as atomic request/reply with at most one outstanding
lost ack per worker, does not model the adarevision server rule, and
bounds elasticity to one admit + one retire per run. It is a model of
the PROTOCOL, not the numerics — the bitwise parity suites
(tests/test_managed_comm.py) own the values. ``conform_service_events``
replays a REAL tier's recorded event log (``ParamService(record_events=
True)``) through the same service-state rules, failing if the
implementation ever takes a step the model calls illegal — the standard
defense against verifying a fiction.

Everything here is stdlib-only and jax-free: the checker runs in CI on
CPU in seconds (`--model-check smoke`).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

__all__ = [
    "Config", "Result", "Violation", "explore", "smoke_configs",
    "run_level", "selftest_mutations", "MUTATIONS",
    "conform_service_events", "conform_gate_events", "is_boundary",
]

# worker status values (kept as small ints for cheap state tuples)
UNJOINED, ACTIVE, CRASHED, DONE, RETIRED = range(5)
_STATUS = ("unjoined", "active", "crashed", "done", "retired")
# phases
IDLE, GATED = 0, 1

MUTATIONS = ("gate_on_raw", "no_boundary_flush", "replay_reapplies",
             "retire_stays_member", "leader_failover_loses_residual",
             "double_apply_across_leaders")


@dataclass(frozen=True)
class Config:
    """One bounded configuration of the protocol model."""

    name: str
    n_workers: int = 2
    staleness: int = 1
    n_clocks: int = 3            # clocks each worker trains (0..n_clocks-1)
    managed: bool = True         # partial pushes enabled off-boundary
    admit_id: Optional[int] = None   # one elastic admission of this id
    retire_worker: Optional[int] = None
    retire_after: int = 0        # retire once its flushed clock >= this
    max_crashes: int = 0         # crash/rejoin episodes (worker 0 only)
    max_lost_acks: int = 0       # pushes whose ack is lost then replayed
    # leader-failover episodes (two-tier fabric, parallel/fabric.py): the
    # worker IS a slice, its leader dies mid-window, a survivor re-elects
    # and resumes the push stream from the replicated ledger with the
    # acked floor re-derived from the service. 0 keeps the family off —
    # pre-fabric configs explore byte-identical state spaces.
    max_failovers: int = 0


@dataclass(frozen=True)
class Violation:
    invariant: str
    detail: str
    trace: Tuple[str, ...]


@dataclass
class Result:
    config: Config
    mutation: Optional[str]
    states: int
    transitions: int
    violations: List[Violation] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def render(self) -> str:
        status = ("ok" if self.ok else
                  f"VIOLATED ({self.violations[0].invariant}: "
                  f"{self.violations[0].detail})")
        mut = f" [mutation={self.mutation}]" if self.mutation else ""
        return (f"model-check {self.config.name}{mut}: "
                f"{self.states} states, {self.transitions} transitions — "
                f"{status}")


def is_boundary(clock: int, staleness: int) -> bool:
    """SSP window boundaries — clocks whose flush MUST be full (mirrors
    AsyncSSPClient._is_boundary; at s=0 every clock is a boundary)."""
    return (clock + 1) % (staleness + 1) == 0


# --------------------------------------------------------------------------- #
# state
# --------------------------------------------------------------------------- #
# worker tuple: (status, clock, phase, residual, replay_clock, lost)
#   clock        — last flushed clock (client-side raw), -1 before any
#   replay_clock — a pushed clock whose ack was lost, awaiting replay (-1)
#   lost         — a leader failover DROPPED this worker's residual (the
#                  seeded loses-residual mutation); the next full flush
#                  claims completeness the anchor can never have, and the
#                  _apply_push monitor flags it. Constant False on every
#                  correct path, so pre-fabric state counts are unchanged.
# service tuple: (raw, durable, seq) each a per-universe-id tuple, plus
#   members / failed frozensets
# budgets: (crashes_left, lost_acks_left, admits_left, failovers_left)

W_STATUS, W_CLOCK, W_PHASE, W_RESID, W_REPLAY, W_LOST = range(6)


@dataclass(frozen=True)
class State:
    workers: Tuple[Tuple[int, int, int, bool, int, bool], ...]
    raw: Tuple[int, ...]
    durable: Tuple[int, ...]
    seq: Tuple[int, ...]
    members: FrozenSet[int]
    failed: FrozenSet[int]
    budgets: Tuple[int, int, int, int]


def _initial(cfg: Config) -> State:
    universe = cfg.n_workers + (1 if cfg.admit_id is not None else 0)
    workers = []
    for w in range(universe):
        joined = w < cfg.n_workers
        workers.append((ACTIVE if joined else UNJOINED, -1, IDLE, False,
                        -1, False))
    return State(
        workers=tuple(workers),
        raw=tuple([-1] * universe),
        durable=tuple([-1] * universe),
        seq=tuple([-1] * universe),
        members=frozenset(range(cfg.n_workers)),
        failed=frozenset(),
        budgets=(cfg.max_crashes, cfg.max_lost_acks,
                 1 if cfg.admit_id is not None else 0,
                 cfg.max_failovers),
    )


def _tset(t: Tuple, i: int, v) -> Tuple:
    return t[:i] + (v,) + t[i + 1:]


def _wset(st: State, w: int, **kw) -> Tuple:
    rec = list(st.workers[w])
    names = ("status", "clock", "phase", "residual", "replay", "lost")
    for k, v in kw.items():
        rec[names.index(k)] = v
    return _tset(st.workers, w, tuple(rec))


def _gate_peers(st: State, w: int) -> List[int]:
    """The ids a gate at worker ``w`` waits on: current members minus
    failed, done, and self (mirrors _min_other_clock)."""
    out = []
    for v in st.members:
        if v == w or v in st.failed:
            continue
        if st.workers[v][W_STATUS] == DONE:
            continue
        out.append(v)
    return out


# --------------------------------------------------------------------------- #
# transition relation
# --------------------------------------------------------------------------- #

def _apply_push(st: State, cfg: Config, w: int, clock: int, full: bool,
                viol: List[Tuple[str, str]],
                mutation: Optional[str],
                fresh_seq: bool = False) -> State:
    """The service side of one push RPC (ParamService._serve 'push'):
    seq-dedup, raw-clock bump, durable bump on full flushes.
    ``fresh_seq`` models a buggy failover successor that restarts its
    seq stream instead of re-deriving the high-water mark — the push
    bypasses dedup (the double-apply-across-leaders mutation)."""
    dup = clock <= st.seq[w]
    if full and not dup and st.workers[w][W_LOST]:
        # completeness monitor: this full flush claims every byte
        # through ``clock`` is in the anchor, but a leader failover
        # dropped the slice's parked residual — the durable clock would
        # advance over bytes that died with the old leader
        viol.append(("failover_completeness",
                     f"worker {w} full flush at clock {clock} after a "
                     f"failover that lost its residual — durable would "
                     f"cover bytes the dead leader never shipped"))
    if dup and mutation != "replay_reapplies" and not fresh_seq:
        return st
    if dup:
        # the seeded no-dedup mutations: apply anyway — the monitor
        # below flags the double application
        viol.append(("exactly_once",
                     f"worker {w} clock {clock} applied twice "
                     f"(seq high-water {st.seq[w]})"))
    raw = _tset(st.raw, w, max(st.raw[w], clock))
    seq = _tset(st.seq, w, max(st.seq[w], clock))
    durable = st.durable
    if full:
        durable = _tset(st.durable, w, max(st.durable[w], clock))
    return replace(st, raw=raw, seq=seq, durable=durable)


def _check_global(st: State, cfg: Config) -> Optional[Tuple[str, str]]:
    """The durable-clock sandwich, over every member, after every edge."""
    bound = cfg.staleness + 1
    for w in st.members:
        if st.durable[w] > st.raw[w]:
            return ("durable_sandwich",
                    f"worker {w}: durable {st.durable[w]} > raw "
                    f"{st.raw[w]}")
        if st.raw[w] - st.durable[w] > bound:
            return ("durable_sandwich",
                    f"worker {w}: raw {st.raw[w]} - durable "
                    f"{st.durable[w]} > staleness+1 ({bound})")
    return None


def _successors(st: State, cfg: Config, mutation: Optional[str]):
    """Yield (label, next_state, [violations]) for every enabled action."""
    s = cfg.staleness
    crashes_left, acks_left, admits_left, failovers_left = st.budgets

    for w, rec in enumerate(st.workers):
        status, clock, phase, residual, replay, lost = rec
        target_clocks = cfg.n_clocks

        if status == ACTIVE and replay >= 0:
            # sender-thread replay of the un-acked flush — checked FIRST
            # so a retiring/finishing worker's drain (which waits for the
            # replay's ack) always has this action available; the
            # service's seq high-water dedups it
            viol: List[Tuple[str, str]] = []
            nst = _apply_push(st, cfg, w, replay, True, viol, mutation)
            nst = replace(nst, workers=_wset(nst, w, replay=-1))
            yield (f"replay({w},{replay})", nst, viol)

        if status == ACTIVE and phase == IDLE:
            k = clock + 1
            retiring = (cfg.retire_worker == w
                        and clock >= cfg.retire_after and clock >= 0)
            if retiring:
                # leave(): flush residual (one forced-full clock), drain
                # (replay must be resolved), then retire the slot
                if replay == -1:
                    if residual:
                        viol = []
                        nst = _apply_push(st, cfg, w, k, True, viol,
                                          mutation)
                        nst = replace(nst, workers=_wset(
                            nst, w, clock=k, residual=False))
                        yield (f"retire_flush({w},{k})", nst, viol)
                    else:
                        members = st.members - {w}
                        if mutation == "retire_stays_member":
                            members = st.members
                        nst = replace(st, members=members,
                                      workers=_wset(st, w, status=RETIRED))
                        yield (f"retire({w})", nst, [])
                continue
            if k >= target_clocks:
                # mark_done(): flush residual, drain, then done
                if replay == -1:
                    if residual:
                        viol = []
                        nst = _apply_push(st, cfg, w, k, True, viol,
                                          mutation)
                        nst = replace(nst, workers=_wset(
                            nst, w, clock=k, residual=False))
                        yield (f"done_flush({w},{k})", nst, viol)
                    else:
                        nst = replace(st, workers=_wset(st, w, status=DONE))
                        yield (f"done({w})", nst, [])
            else:
                # gate(k): the PREDICATE (seedable) decides admission;
                # the MONITOR (fixed) checks the durable contract
                peers = _gate_peers(st, w)
                need = k - s - 1
                vec = st.raw if mutation == "gate_on_raw" else st.durable
                if all(vec[v] >= need for v in peers):
                    viol = []
                    bad = [v for v in peers if st.durable[v] < need]
                    if bad:
                        viol.append((
                            "gate_safety",
                            f"worker {w} admitted at clock {k} but peer"
                            f"(s) {bad} have durable "
                            f"{[st.durable[v] for v in bad]} < {need} — "
                            f"the staleness bound is widened by "
                            f"un-flushed residuals"))
                    nst = replace(st, workers=_wset(st, w, phase=GATED))
                    yield (f"gate({w},{k})", nst, viol)
                # else: blocked — not enabled (deadlock detection covers
                # the case where EVERYONE is blocked)

            # crash/rejoin schedule (worker 0 only, bounded)
            if w == 0 and crashes_left > 0 and clock >= 0:
                nst = replace(
                    st,
                    workers=_wset(st, w, status=CRASHED, residual=False,
                                  replay=-1),
                    failed=st.failed | {w},
                    budgets=(crashes_left - 1, acks_left, admits_left,
                             failovers_left))
                yield (f"crash({w})", nst, [])

            # leader failover (two-tier fabric): the worker is a SLICE;
            # its leader process dies between flushes, a survivor
            # re-elects and resumes from the replicated ledger. The
            # CORRECT successor re-derives the acked floor from the
            # service — entries at or below the service's applied clock
            # are NOT resent (resume_oplog's ``c > applied`` filter), so
            # an outstanding ack-lost replay is dropped, and the
            # residual carries over verbatim. The seeded mutations break
            # exactly one of those two obligations each.
            if failovers_left > 0 and clock >= 0:
                nb = (crashes_left, acks_left, admits_left,
                      failovers_left - 1)
                if mutation == "leader_failover_loses_residual":
                    # the successor resumes the clock/seq stream but the
                    # parked residual died with the old leader; the next
                    # full flush trips the completeness monitor
                    nst = replace(st, workers=_wset(
                        st, w, residual=False, replay=-1,
                        lost=lost or residual), budgets=nb)
                    yield (f"failover({w})", nst, [])
                elif (mutation == "double_apply_across_leaders"
                        and replay >= 0):
                    # the successor restarts its seq stream instead of
                    # re-deriving the high-water mark: the ledgered
                    # entry whose ack was lost re-applies under a fresh
                    # seq — the exactly-once monitor flags it
                    viol = []
                    nst = _apply_push(st, cfg, w, replay, True, viol,
                                      mutation, fresh_seq=True)
                    nst = replace(nst, workers=_wset(nst, w, replay=-1),
                                  budgets=nb)
                    yield (f"failover({w})", nst, viol)
                else:
                    # correct failover: acked floor from the service, so
                    # the already-applied ack-lost entry is dropped (the
                    # service seq dedup would absorb it anyway — this is
                    # the no-resend fast path), residual survives in the
                    # ledger
                    nst = replace(st, workers=_wset(st, w, replay=-1),
                                  budgets=nb)
                    yield (f"failover({w})", nst, [])

        elif status == ACTIVE and phase == GATED:
            k = clock + 1
            boundary = is_boundary(k, s)
            must_full = boundary or not cfg.managed
            if mutation == "no_boundary_flush":
                must_full = not cfg.managed
            # full flush (always an option: budget was comfortable)
            viol = []
            nst = _apply_push(st, cfg, w, k, True, viol, mutation)
            nst = replace(nst, workers=_wset(
                nst, w, clock=k, phase=IDLE, residual=False))
            yield (f"push_full({w},{k})", nst, viol)
            if acks_left > 0 and replay == -1:
                # same flush, ack lost: service applied, client will
                # replay — the exactly-once schedule
                viol = []
                nst = _apply_push(st, cfg, w, k, True, viol, mutation)
                nst = replace(
                    nst,
                    workers=_wset(nst, w, clock=k, phase=IDLE,
                                  residual=False, replay=k),
                    budgets=(crashes_left, acks_left - 1, admits_left,
                             failovers_left))
                yield (f"push_full_acklost({w},{k})", nst, viol)
            if not must_full:
                # partial flush: raw advances, durable does not, the
                # complement parks in the residual
                viol = []
                nst = _apply_push(st, cfg, w, k, False, viol, mutation)
                nst = replace(nst, workers=_wset(
                    nst, w, clock=k, phase=IDLE, residual=True))
                yield (f"push_partial({w},{k})", nst, viol)

        elif status == CRASHED:
            # rejoin(): resume at the service's applied clock; pending
            # and residual are gone (the failure model's bounded loss).
            # No durable re-anchoring is needed: boundary positions are
            # GLOBAL clock positions, so the next boundary (<= s clocks
            # away) force-flushes full and the sandwich holds — a fact
            # this checker verifies rather than assumes.
            nst = replace(
                st,
                workers=_wset(st, w, status=ACTIVE, clock=st.raw[w],
                              phase=IDLE, residual=False, replay=-1,
                              lost=False),
                failed=st.failed - {w})
            yield (f"rejoin({w})", nst, [])

    # elastic admission of the configured extra id
    if admits_left > 0 and cfg.admit_id is not None:
        a = cfg.admit_id
        live = [st.raw[v] for v in st.members
                if v not in st.failed
                and st.workers[v][W_STATUS] not in (DONE,)]
        join = min(live) if live else -1
        join = max(join, st.raw[a], st.seq[a])
        nst = replace(
            st,
            workers=_wset(st, a, status=ACTIVE, clock=join, phase=IDLE,
                          residual=False, replay=-1),
            raw=_tset(st.raw, a, join),
            durable=_tset(st.durable, a, max(st.durable[a], join)),
            seq=_tset(st.seq, a, max(st.seq[a], join)),
            members=st.members | {a},
            budgets=(crashes_left, acks_left, 0, failovers_left))
        yield (f"admit({a},{join})", nst, [])


def _terminal(st: State) -> bool:
    """Every worker that ever joined is done or retired (crashed workers
    must rejoin and finish — a run abandoned mid-crash is not success)."""
    for rec in st.workers:
        if rec[W_STATUS] in (ACTIVE, CRASHED):
            return False
        if rec[W_STATUS] == UNJOINED:
            return False           # the configured admit never happened
    return True


# --------------------------------------------------------------------------- #
# exhaustive exploration
# --------------------------------------------------------------------------- #

def explore(cfg: Config, mutation: Optional[str] = None,
            max_states: int = 2_000_000,
            stop_at_first: bool = True) -> Result:
    """DFS over every interleaving, hashing states so each is visited
    once. Violations carry the action trace that reached them."""
    if mutation is not None and mutation not in MUTATIONS:
        raise ValueError(f"unknown mutation {mutation!r}; "
                         f"choose from {MUTATIONS}")
    init = _initial(cfg)
    visited = {init}
    res = Result(config=cfg, mutation=mutation, states=1, transitions=0)
    stack: List[Tuple[State, Tuple[str, ...]]] = [(init, ())]
    while stack:
        st, path = stack.pop()
        succs = list(_successors(st, cfg, mutation))
        if not succs and not _terminal(st):
            res.violations.append(Violation(
                "deadlock",
                f"non-terminal state with no enabled action "
                f"(workers: "
                f"{[(_STATUS[r[W_STATUS]], r[W_CLOCK]) for r in st.workers]}, "
                f"durable: {list(st.durable)})",
                path))
            if stop_at_first:
                return res
        for label, nst, viols in succs:
            res.transitions += 1
            npath = path + (label,)
            for inv, detail in viols:
                res.violations.append(Violation(inv, detail, npath))
                if stop_at_first:
                    return res
            g = _check_global(nst, cfg)
            if g is not None:
                res.violations.append(Violation(g[0], g[1], npath))
                if stop_at_first:
                    return res
            if nst not in visited:
                if len(visited) >= max_states:
                    raise RuntimeError(
                        f"state-space bound {max_states} exceeded for "
                        f"{cfg.name} — shrink the config")
                visited.add(nst)
                res.states += 1
                stack.append((nst, npath))
    return res


# --------------------------------------------------------------------------- #
# levels + self-test
# --------------------------------------------------------------------------- #

def tiny_config() -> Config:
    # n_clocks=3 matters: the first BINDING gate (need >= 0) appears at
    # clock 2, and a binding gate is what the seeded gate-on-raw
    # mutation needs to be expressible
    return Config(name="2w-s1-plain", n_workers=2, staleness=1, n_clocks=3,
                  managed=True)


def smoke_configs() -> List[Config]:
    """The acceptance set: every 2-worker staleness {0,1,2} config with
    one admit AND one retire event, crash/rejoin and a lost-ack replay
    in the schedule — plus the two-tier fabric configs, where a worker
    IS a slice (the model is granularity-agnostic by construction, so
    slice-level admit/retire is a relabeling) and the leader-failover
    transition family interleaves with lost acks and partial pushes."""
    out = []
    for s in (0, 1, 2):
        out.append(Config(
            name=f"2w-s{s}-admit-retire-crash", n_workers=2, staleness=s,
            n_clocks=3, managed=True, admit_id=2, retire_worker=1,
            retire_after=1, max_crashes=1, max_lost_acks=1))
    # slice granularity: one slice admitted mid-run, one retired — the
    # same elastic machinery the per-process tier uses, exercised under
    # the fabric's labels (a slice id is just a worker id on the wire)
    out.append(Config(
        name="2slice-s1-admit-retire", n_workers=2, staleness=1,
        n_clocks=3, managed=True, admit_id=2, retire_worker=1,
        retire_after=1, max_failovers=1))
    # leader failover mid-window: the failover family crossed with an
    # ack-lost replay (the exactly-once-across-leaders schedule) and
    # managed partial pushes (the residual-carryover schedule)
    out.append(Config(
        name="2slice-s1-leader-failover", n_workers=2, staleness=1,
        n_clocks=3, managed=True, max_lost_acks=1, max_failovers=2))
    return out


def full_configs() -> List[Config]:
    return smoke_configs() + [
        Config(name="3w-s1-admit-retire", n_workers=3, staleness=1,
               n_clocks=3, managed=True, admit_id=3, retire_worker=2,
               retire_after=0, max_crashes=1, max_lost_acks=1),
        Config(name="2w-s2-deep-clocks", n_workers=2, staleness=2,
               n_clocks=5, managed=True, max_crashes=1, max_lost_acks=1),
    ]


def selftest_mutations(cfg: Optional[Config] = None) -> Dict[str, bool]:
    """Every seeded mutation must be CAUGHT (produce a violation) on a
    config rich enough to express it; a mutation the checker agrees with
    means the checker itself regressed. Returns {mutation: caught}."""
    base = cfg or Config(name="selftest", n_workers=2, staleness=1,
                         n_clocks=3, managed=True, max_crashes=1,
                         max_lost_acks=1)
    out: Dict[str, bool] = {}
    for m in MUTATIONS:
        c = base
        if m == "retire_stays_member":
            # needs a retire event and a survivor training past it
            c = replace(base, name="selftest-retire", retire_worker=1,
                        retire_after=0, n_clocks=4, max_crashes=0,
                        max_lost_acks=0)
        elif m in ("leader_failover_loses_residual",
                   "double_apply_across_leaders"):
            # needs the failover family enabled: a partial push parks a
            # residual before the failover (loses_residual), and an
            # ack-lost flush leaves a ledgered entry the buggy successor
            # re-applies under a fresh seq (double_apply)
            c = replace(base, name="selftest-failover", max_crashes=0,
                        max_failovers=1)
        out[m] = not explore(c, mutation=m).ok
    return out


def run_level(level: str) -> Tuple[List[Result], Dict[str, bool]]:
    """One CLI invocation's worth of checking. ``tiny`` = one plain
    config + the gate mutation (subprocess-pinned in tests); ``smoke`` =
    the acceptance set + every mutation self-test (the CI gate);
    ``full`` adds the 3-worker and deep-clock configs."""
    if level == "tiny":
        results = [explore(tiny_config())]
        caught = {"gate_on_raw":
                  not explore(replace(tiny_config(), name="tiny-mut"),
                              mutation="gate_on_raw").ok}
        return results, caught
    if level == "smoke":
        return [explore(c) for c in smoke_configs()], selftest_mutations()
    if level == "full":
        return [explore(c) for c in full_configs()], selftest_mutations()
    raise ValueError(f"unknown model-check level {level!r}; "
                     f"choose tiny, smoke or full")


# --------------------------------------------------------------------------- #
# trace conformance: the model vs the real tier
# --------------------------------------------------------------------------- #

class TraceConformanceError(AssertionError):
    """The real tier took a step the model calls illegal (or vice
    versa) — either the implementation or the model is wrong, and the
    difference is the finding."""


def conform_service_events(events: Sequence[Tuple], staleness: int,
                           n_workers: int) -> Dict[str, int]:
    """Replay a ParamService event log (``record_events=True``) through
    the model's service-state rules. Checks, per event:

    - push: the dup verdict matches the model's seq high-water dedup;
      boundary clocks arrive with ``full=True`` (the force-flush
      contract); the durable sandwich holds after the apply.
    - admit: the join clock equals the service's rendezvous rule
      EXACTLY — ``max(min live raw clock, the id's own historical
      raw/seq high-water)``, where "live" is members minus done (the
      `_admit_locked` computation; a re-admitted retiree resumes past
      its own clocks, never behind them). Scope: failure-free runs
      (evictions are not in the event vocabulary).
    - done: the worker leaves the gate-relevant set (and the admit
      rendezvous denominator).
    - retire: the id was a member and leaves the gate denominator.

    Returns counters (events checked per kind) for the test to pin."""
    raw: Dict[int, int] = {w: -1 for w in range(n_workers)}
    durable: Dict[int, int] = {w: -1 for w in range(n_workers)}
    seq: Dict[int, int] = {w: -1 for w in range(n_workers)}
    members = set(range(n_workers))
    done: set = set()
    counts = {"push": 0, "admit": 0, "retire": 0, "done": 0}
    bound = staleness + 1
    for i, ev in enumerate(events):
        kind = ev[0]
        if kind == "push":
            _, w, clock, full, dup = ev
            if w not in raw:
                raise TraceConformanceError(
                    f"event {i}: push from unknown worker {w}")
            expected_dup = clock <= seq[w]
            if bool(dup) != expected_dup:
                raise TraceConformanceError(
                    f"event {i}: push(w={w}, clock={clock}) dup="
                    f"{dup} but model's seq high-water {seq[w]} says "
                    f"dup={expected_dup} — exactly-once dedup diverged")
            if is_boundary(clock, staleness) and not full and not dup:
                raise TraceConformanceError(
                    f"event {i}: boundary clock {clock} (staleness "
                    f"{staleness}) pushed with full=False — the residual "
                    f"force-flush contract is broken")
            if not expected_dup:
                raw[w] = max(raw[w], clock)
                seq[w] = max(seq[w], clock)
                if full:
                    durable[w] = max(durable[w], clock)
            if durable[w] > raw[w] or raw[w] - durable[w] > bound:
                raise TraceConformanceError(
                    f"event {i}: worker {w} raw {raw[w]} / durable "
                    f"{durable[w]} outside the staleness+1 sandwich")
            counts["push"] += 1
        elif kind == "admit":
            _, w, join = ev
            # mirror _admit_locked exactly: rendezvous at the min LIVE
            # (member, not done) raw clock, and a returning id resumes
            # past everything it ever flushed
            live = [raw[v] for v in members if v not in done]
            expected = min(live) if live else -1
            expected = max(expected, raw.get(w, -1), seq.get(w, -1))
            if join != expected:
                raise TraceConformanceError(
                    f"event {i}: admit(w={w}) at join clock {join} but "
                    f"the rendezvous rule says {expected} (min live "
                    f"{min(live) if live else -1}, own high-water "
                    f"{max(raw.get(w, -1), seq.get(w, -1))})")
            members.add(w)
            done.discard(w)
            raw[w] = max(raw.get(w, -1), join)
            durable[w] = max(durable.get(w, -1), join)
            seq[w] = max(seq.get(w, -1), join)
            counts["admit"] += 1
        elif kind == "done":
            _, w = ev
            done.add(w)
            counts["done"] += 1
        elif kind == "retire":
            _, w = ev
            if w not in members:
                raise TraceConformanceError(
                    f"event {i}: retire of non-member {w}")
            members.discard(w)
            counts["retire"] += 1
        else:
            raise TraceConformanceError(
                f"event {i}: unknown event kind {kind!r}")
    return counts


def conform_gate_events(events: Sequence[Tuple],
                        staleness: int) -> Dict[str, int]:
    """Check a client's recorded gate admissions
    (``AsyncSSPClient(record_events=True)``): every pass must have seen
    ``min(peer durable) >= clock - s - 1`` — the read-gate safety
    property, asserted on what the REAL gate actually observed."""
    n = 0
    for i, ev in enumerate(events):
        if ev[0] != "gate":
            continue
        _, w, clock, min_other = ev
        if min_other < clock - staleness - 1:
            raise TraceConformanceError(
                f"gate event {i}: worker {w} admitted at clock {clock} "
                f"with min peer durable {min_other} < "
                f"{clock - staleness - 1} — staleness bound violated")
        n += 1
    return {"gate": n}
