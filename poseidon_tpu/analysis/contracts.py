"""HLO contract gates: checked-in per-model goldens for compiled invariants.

The perf PRs bought specific, countable properties of the compiled train
step — ~11 bucketed gradient psums on GoogLeNet instead of ~120 per-leaf
all-reduces (PR 4), exactly 2 NHWC layout transposes on AlexNet (the fc6
boundary pair, PR 3), donated param/state/batch buffers (PR 5), an
f64-free program — and until now they lived as assertions scattered
across tests that each compile their own subset. This module promotes
them to *contracts*: one JSON per model under ``evidence/hlo_contracts/``
recording the counters extracted from the lowered (StableHLO) and, where
a CPU compile is affordable, optimized-HLO text of one full data-parallel
optimizer step. The gate recomputes and diffs; ``refresh()`` rewrites the
goldens and prints the diff for review.

With the TPU tunnel down (ROADMAP item 2), these static gates are the
only trustworthy proxy for the compiled program's shape — the
Julia->TPU/XLA argument (arXiv:1810.09868) that whole-program
ahead-of-time analysis is the natural fit for this regime.

Compile-cost policy: tracing+lowering is seconds per model (the tier-1
gate level); full XLA CPU compiles are minutes on GoogLeNet, so the
``optimized`` section (fusion count) is recorded for LeNet only. The
NHWC layout half re-traces a mesh-free step via
``hlo_layout.net_transpose_report`` for AlexNet (the model the claim is
about; LeNet is single-channel and GoogLeNet's NHWC plan is pinned by
tests/test_layout_hlo.py). ROADMAP item 1's mesh work should EXTEND these
contracts with its planned collective schedule per (mesh, model).

Version drift: counters are exact goldens only under the jax version that
generated them (recorded in ``generated_with``). Under a different jax,
the gate falls back to the robust subset — gradient all-reduce count,
layout transposes, f64-freedom, donation non-emptiness, and the
``memory`` section's analytic activation-bytes column (pure shape math;
its LeNet-only ``measured_peak_bytes`` is compiler output and drops out)
— and says so.
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

from . import REPO_ROOT

CONTRACT_DIR = os.path.join(REPO_ROOT, "evidence", "hlo_contracts")
MODELS = ("lenet", "alexnet", "googlenet")

# per-model build recipe: image/channels follow the cheapest configuration
# the existing suites already compile (tests/test_arena.py). The AlexNet
# NHWC half runs at the real 227 px: at toy sizes pool5 degenerates to
# 1x1 and the fc6 boundary pair it exists to pin folds away as bitcasts.
_SPECS = {
    # "mesh": lower the dp2xfsdp2xtp2 sharding-planner step and pin its
    # collective census against the planned schedule (parallel/spmd.py).
    # GoogLeNet skips it for compile budget — its schedule shape (conv
    # arena buckets + gathered-column classifier heads) is covered by
    # AlexNet, and its arena bucket count is already pinned above.
    "lenet": {"image": 28, "channels": 1, "classes": 10,
              "optimized": True, "nhwc": False, "mesh": True},
    "alexnet": {"image": 67, "channels": 3, "classes": 10,
                "optimized": False, "nhwc": True, "nhwc_image": 227,
                "mesh": True},
    "googlenet": {"image": 224, "channels": 3, "classes": 10,
                  "optimized": False, "nhwc": False, "mesh": False},
}

_BATCH = 8          # one row per device on the 8-device virtual mesh

# exact-compare keys that survive jax upgrades (program-level, not
# compiler-whim-level); everything else is exact only under the recorded
# jax version. The collective_schedule keys are structural — the planner
# states them and lowering preserves them (chained buckets cannot merge).
# The full collective "sequence" (op order + replica groups + normalized
# channel ids) is deliberately NOT robust: op ordering inside the lowered
# module is a compiler artifact across versions; under ONE version it is
# deterministic, which is exactly what the cross-participant consistency
# gate (collective_consistency) relies on.
ROBUST_KEYS = ("gradient_all_reduces", "layout_transposes", "f64_tensors",
               "mesh", "arena_buckets", "tp_modes", "planned_counts",
               "lowered_counts", "planned_matches_lowered",
               # the memory section's analytic half is pure shape math
               # (attribution.layer_cost_table act_bytes) — exact under
               # any jax; measured_peak_bytes is compiler output and is
               # deliberately NOT here
               "act_bytes_total", "remat_candidates", "max_reclaim_bytes")

# the ops whose cross-participant divergence is a silent SPMD hang: a
# mesh member waiting in a collective its peers never entered (or
# entered with different groups/channels)
_COLLECTIVE_OP_RE = re.compile(
    r'"stablehlo\.(all_reduce|all_gather|reduce_scatter|all_to_all|'
    r'collective_permute|collective_broadcast)"')
_GROUPS_RE = re.compile(r"replica_groups\s*=\s*dense<([^>]*)>")
_CHANNEL_RE = re.compile(r"channel_handle<handle\s*=\s*(\d+)")
_DIM_RE = re.compile(r"(all_gather_dim|scatter_dimension|"
                     r"split_dimension|concat_dimension)\s*=\s*(\d+)")

_TENSOR_DTYPE_RE = re.compile(r"tensor<[0-9x]*([a-z][a-z0-9]*)>")


def collective_sequence(stablehlo: str) -> List[str]:
    """The ordered collective schedule of a lowered module: one
    normalized entry per collective op, in program order —
    ``op|replica_groups|dims|cN``. Channel ids are renumbered by first
    appearance (c0, c1, ...) so two participants' programs compare equal
    iff their schedules really match, even though jax's channel counter
    is process-global. This is the static form of the cross-participant
    contract: every mesh member must lower the IDENTICAL sequence, or
    some member ends up waiting in a collective its peers never enter —
    the silent-hang failure mode of multi-slice composition."""
    entries: List[str] = []
    chan_map: Dict[str, str] = {}
    for m in _COLLECTIVE_OP_RE.finditer(stablehlo):
        # attributes live between the op token and the body brace of the
        # same instruction; the next op's match bounds the slice
        end = stablehlo.find("({", m.end())
        nxt = _COLLECTIVE_OP_RE.search(stablehlo, m.end())
        stop = min(x for x in (end if end != -1 else len(stablehlo),
                               nxt.start() if nxt else len(stablehlo)))
        attrs = stablehlo[m.end():stop]
        g = _GROUPS_RE.search(attrs)
        groups = "".join((g.group(1) if g else "?").split())
        ch = _CHANNEL_RE.search(attrs)
        if ch:
            cid = chan_map.setdefault(ch.group(1), f"c{len(chan_map)}")
        else:
            cid = "c?"
        dims = ",".join(f"{k}={v}" for k, v in _DIM_RE.findall(attrs))
        entries.append(f"{m.group(1)}|{groups}|{dims}|{cid}")
    return entries


class ContractEnvironmentError(RuntimeError):
    """The measurement substrate does not match the golden's (wrong device
    count): the comparison is refused, not failed — CLI exit 4, never 2."""


def contract_path(model: str) -> str:
    return os.path.join(CONTRACT_DIR, f"{model}.json")


def _dtype_census(stablehlo: str) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for m in _TENSOR_DTYPE_RE.finditer(stablehlo):
        out[m.group(1)] = out.get(m.group(1), 0) + 1
    return dict(sorted(out.items()))


def _fusion_count(optimized_hlo: str) -> int:
    return len(re.findall(r"\bfusion\(", optimized_hlo))


def _build_net(model: str):
    from ..core.net import Net
    from ..models import zoo
    spec = _SPECS[model]
    if model == "lenet":
        np_ = zoo.lenet(with_accuracy=False)
        shapes = zoo.lenet_shapes(_BATCH // 8)
    else:
        np_ = getattr(zoo, model)(num_classes=spec["classes"],
                                  with_accuracy=False)
        shapes = {"data": (_BATCH // 8, spec["channels"], spec["image"],
                           spec["image"]),
                  "label": (_BATCH // 8,)}
    return Net(np_, "TRAIN", source_shapes=shapes), spec


def ensure_virtual_mesh() -> None:
    """Pin the measurement substrate BEFORE jax initializes: the 8-device
    virtual CPU mesh every tier-1 suite runs on (tests/conftest.py). A
    contract measured on a different device count has different collective
    groups and is not comparable — if jax is already up with another
    count, check_model refuses the comparison (ContractEnvironmentError,
    CLI exit 4), never reporting it as a violation."""
    import sys
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def build_contract(model: str) -> Dict:
    """Compile (on the current backend) and measure one model's contract.
    Slow path: seconds of tracing per model; LeNet additionally runs the
    CPU XLA compile for the optimized-HLO section."""
    ensure_virtual_mesh()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..parallel import (CommConfig, build_train_step, init_train_state,
                            make_mesh)
    from ..proto.messages import SolverParameter
    from ..runtime.hlo_comm import count_gradient_all_reduces_stablehlo
    from ..runtime.hlo_layout import (count_layout_transposes,
                                      net_transpose_report)

    net, spec = _build_net(model)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    mesh = make_mesh()
    n_dev = int(np.prod(list(mesh.shape.values())))
    params = net.init(jax.random.PRNGKey(0))
    cc = CommConfig()
    ts = build_train_step(net, sp, mesh, cc, donate=True, donate_batch=True)
    state = init_train_state(params, cc, n_dev)
    rs = np.random.RandomState(0)
    shape = (_BATCH, spec["channels"], spec["image"], spec["image"])
    batch = {"data": jnp.asarray(rs.randn(*shape).astype(np.float32)),
             "label": jnp.asarray(rs.randint(0, spec["classes"],
                                             size=(_BATCH,)))}
    lowered = ts.lowerable.lower(params, state, batch, jax.random.PRNGKey(7))
    txt = lowered.as_text()
    census = _dtype_census(txt)
    arena_buckets = ts.arena.n_buckets if ts.arena is not None else None
    contract: Dict = {
        "model": model,
        "generated_with": {"jax": jax.__version__,
                           "backend": jax.default_backend(),
                           "n_devices": n_dev},
        "config": {"image": spec["image"], "channels": spec["channels"],
                   "batch": _BATCH, "num_classes": spec["classes"],
                   "conv_layout": net.conv_layout,
                   "param_arena": cc.param_arena,
                   "arena_bucket_mb": cc.arena_bucket_mb,
                   "arena_buckets": arena_buckets,
                   "donate": True, "donate_batch": True},
        "stablehlo": {
            # the PR-4 acceptance counter: bucketed psums, never per-leaf
            "gradient_all_reduces": count_gradient_all_reduces_stablehlo(txt),
            # the PR-3 counter under the default (per-backend) layout
            "layout_transposes": count_layout_transposes(txt),
            # PR-5: params + solver state + batch buffers all donated
            "donated_buffers": txt.count("jax.buffer_donor"),
            "f64_tensors": census.get("f64", 0),
            "dtype_census": census,
        },
    }
    if spec["nhwc"]:
        from ..core.net import Net
        img = spec.get("nhwc_image", spec["image"])
        nhwc_net = Net(net.net_param, "TRAIN",
                       {"data": (2, spec["channels"], img, img),
                        "label": (2,)},
                       conv_layout="NHWC")
        rep = net_transpose_report(nhwc_net, sp, per_dev_batch=2,
                                   image=img)
        contract["nhwc"] = {
            "level": rep["level"],
            # the PR-3 headline: exactly the fc-boundary pair on AlexNet
            "layout_transposes": rep["layout_transposes"],
        }
    if spec.get("mesh"):
        # ROADMAP item 1's extension: the SPMD sharding planner's
        # collective schedule, pinned exactly like the arena's buckets.
        # dp2 x fsdp2 x tp2 uses all 8 virtual devices; counted on the
        # LOWERED program (combiner-proof: the chained buckets cannot
        # merge, and XLA never splits a collective).
        from ..runtime.hlo_comm import collective_census_stablehlo
        mtxt, plan, marena, mcfg, mnet, mcc = _lower_mesh_participant(model)
        census = collective_census_stablehlo(mtxt)
        # the planned schedule must be stated with the SAME CommConfig
        # the plan was built from, or planned-vs-lowered diffs for a
        # config reason rather than a lowering one
        sched = plan.collective_schedule(marena, mnet, comm=mcc)
        contract["collective_schedule"] = {
            "mesh": mcfg.describe(),
            "arena_buckets": (marena.n_buckets
                              if marena is not None else 0),
            "tp_modes": {l: d.mode
                         for l, d in sorted(plan.tp_layers.items())},
            "planned_counts": sched["counts"],
            "lowered_counts": census,
            "planned_matches_lowered": census == sched["counts"],
            # the full ordered schedule (op|groups|dims|channel): diffed
            # exactly under the recorded jax version, and the substrate
            # of the cross-participant consistency gate below
            "sequence": collective_sequence(mtxt),
        }
    # the HBM budget planner's contract surface (core/remat.py): the
    # analytic activation-bytes column the knapsack prices against, per
    # model. Pure shape math — robust across jax versions.
    from ..core import remat as remat_mod
    from ..runtime.attribution import layer_cost_table
    table = layer_cost_table(net)
    zero_plan = remat_mod.plan_remat(
        table, 0, 0, candidates=remat_mod.remat_candidates(net),
        source="analytic")
    contract["memory"] = {
        "act_bytes_total": sum(int(r.get("act_bytes", 0))
                               for r in table.values()),
        "remat_candidates": len(remat_mod.remat_candidates(net)),
        # what the zero-budget maximal plan reclaims (bytes) — the
        # planner's full lever arm on this model
        "max_reclaim_bytes": int(zero_plan.saved_bytes),
    }
    if spec["optimized"]:
        compiled = lowered.compile()
        ctxt = compiled.as_text()
        from ..runtime.hlo_comm import count_gradient_all_reduces
        contract["optimized"] = {
            "gradient_all_reduces": count_gradient_all_reduces(ctxt),
            "layout_transposes": count_layout_transposes(ctxt),
            "fusion_count": _fusion_count(ctxt),
        }
        # real memory_analysis() peak — compiler output (exact only
        # under the recorded jax), riding the compile the optimized
        # section already paid; LeNet-only by the compile-cost policy
        contract["memory"]["measured_peak_bytes"] = \
            remat_mod.measured_peak_bytes(compiled)
    return contract


def _lower_mesh_participant(model: str):
    """Build + lower the dp2 x fsdp2 x tp2 sharded step EXACTLY as one
    mesh participant would — fresh Net, fresh plan, fresh trace — and
    return (stablehlo_text, plan, arena, mesh_config, net, comm_config).
    Called once by :func:`build_contract` and N times by
    :func:`collective_consistency` (each call IS one participant)."""
    ensure_virtual_mesh()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..config import MeshConfig
    from ..core.net import Net
    from ..parallel import CommConfig, init_train_state
    from ..parallel.spmd import (ShardingPlan, build_spmd_train_step,
                                 named_mesh)
    from ..proto.messages import SolverParameter

    net, spec = _build_net(model)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    cc = CommConfig()
    mcfg = MeshConfig(data=2, fsdp=2, tp=2)
    smesh = named_mesh(mcfg)
    n_dp = mcfg.data * mcfg.fsdp
    if model == "lenet":
        from ..models import zoo as _zoo
        mshapes = _zoo.lenet_shapes(_BATCH // n_dp)
    else:
        mshapes = {"data": (_BATCH // n_dp, spec["channels"],
                            spec["image"], spec["image"]),
                   "label": (_BATCH // n_dp,)}
    mnet = Net(net.net_param, "TRAIN", source_shapes=mshapes)
    plan = ShardingPlan.build(mnet, mcfg, cc)
    mts = build_spmd_train_step(mnet, sp, smesh, plan, cc, donate=False)
    mparams = mnet.init(jax.random.PRNGKey(0))
    mstate = init_train_state(mparams, cc, n_dp)
    rs = np.random.RandomState(0)
    shape = (_BATCH, spec["channels"], spec["image"], spec["image"])
    batch = {"data": jnp.asarray(rs.randn(*shape).astype(np.float32)),
             "label": jnp.asarray(rs.randint(0, spec["classes"],
                                             size=(_BATCH,)))}
    mlowered = mts.lowerable.lower(mparams, mstate, batch,
                                   jax.random.PRNGKey(7))
    return mlowered.as_text(), plan, mts.arena, mcfg, mnet, cc


def collective_consistency(models: Sequence[str] = ("lenet",),
                           participants: int = 2) -> Tuple[bool, Dict]:
    """The cross-participant collective gate: lower the sharded step
    ``participants`` times INDEPENDENTLY (fresh net, fresh planner state,
    fresh trace — what each process of a multi-process mesh, or each
    slice of ROADMAP item 4's cross-slice tier, would do on its own) and
    require the extracted collective sequences to be IDENTICAL: same ops
    in the same order, same replica groups, same dims, same normalized
    channel assignment. Any divergence is the mismatched-collective
    silent hang, caught at diff time instead of as a wedged pod."""
    report: Dict = {}
    ok = True
    for model in models:
        if not _SPECS.get(model, {}).get("mesh"):
            report[model] = {"ok": True, "skipped":
                             "no mesh spec for this model", "diffs": []}
            continue
        seqs = [collective_sequence(_lower_mesh_participant(model)[0])
                for _ in range(max(2, participants))]
        # a degenerate extraction must REFUSE, never vacuously pass: if
        # an MLIR printing change moves replica_groups out of the attr
        # slice, every entry degrades to 'op|?|...' and two genuinely
        # divergent participants would compare equal — the exact hang
        # this gate exists to catch. RuntimeError -> CLI exit 4 (infra).
        for p, seq in enumerate(seqs):
            bad = [e for e in seq if "|?|" in e]
            if not seq or bad:
                raise RuntimeError(
                    f"{model} participant {p}: collective sequence "
                    f"extraction degenerated ({'empty' if not seq else bad[0]!r}"
                    f") — the stablehlo printing no longer matches "
                    f"collective_sequence's attribute scan; fix the "
                    f"extractor before trusting this gate")
        diffs: List[str] = []
        base = seqs[0]
        for p, seq in enumerate(seqs[1:], start=1):
            if len(seq) != len(base):
                diffs.append(f"participant {p}: {len(seq)} collectives "
                             f"vs participant 0's {len(base)}")
            for i, (a, b) in enumerate(zip(base, seq)):
                if a != b:
                    diffs.append(f"participant {p} diverges at "
                                 f"collective #{i}: {a!r} vs {b!r}")
                    break       # first divergence per participant
        report[model] = {"ok": not diffs, "participants": len(seqs),
                         "sequence_len": len(base), "diffs": diffs}
        ok = ok and not diffs
    return ok, report


def load_contract(model: str) -> Optional[Dict]:
    path = contract_path(model)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def diff_contracts(golden: Dict, fresh: Dict) -> List[str]:
    """Human-readable mismatches, empty when the contract holds. Pure —
    the unit tests feed it synthetic violations without compiling."""
    diffs: List[str] = []
    same_jax = (golden.get("generated_with", {}).get("jax")
                == fresh.get("generated_with", {}).get("jax"))
    g_dev = golden.get("generated_with", {}).get("n_devices")
    f_dev = fresh.get("generated_with", {}).get("n_devices")
    if g_dev != f_dev:
        return [f"n_devices: golden measured on {g_dev}, this process has "
                f"{f_dev} — collective groups are not comparable (run "
                f"under the 8-device virtual mesh, see "
                f"contracts.ensure_virtual_mesh)"]

    def cmp(section: str, key: str, robust: bool) -> None:
        g = golden.get(section, {}).get(key)
        f = fresh.get(section, {}).get(key)
        if g is None:
            return
        if not same_jax and not robust:
            return
        if g != f:
            diffs.append(f"{section}.{key}: golden {g!r} != measured {f!r}")

    for section in ("stablehlo", "nhwc", "collective_schedule",
                    "memory", "optimized"):
        gsec = golden.get(section)
        if gsec is None:
            continue
        if section == "optimized" and fresh.get(section) is None:
            diffs.append("optimized: section missing from measurement")
            continue
        for key in gsec:
            # nothing in the optimized-HLO section is robust: those
            # counters are compiler output (layout assignment, fusion),
            # exact only under the recorded jax version
            cmp(section, key, robust=(key in ROBUST_KEYS
                                      and section != "optimized"))
    # donation is robust as a non-emptiness claim even across jax versions
    # (under the SAME version the exact compare above already covers it)
    if not same_jax:
        g_don = golden.get("stablehlo", {}).get("donated_buffers")
        f_don = fresh.get("stablehlo", {}).get("donated_buffers")
        if g_don and not f_don:
            diffs.append(f"stablehlo.donated_buffers: golden {g_don} but "
                         f"the measured program donates nothing")
    if not same_jax and diffs:
        diffs.append(
            f"note: golden generated under jax "
            f"{golden.get('generated_with', {}).get('jax')!r}, running "
            f"{fresh.get('generated_with', {}).get('jax')!r} — only the "
            f"robust counter subset was compared")
    return diffs


def check_model(model: str,
                fresh: Optional[Dict] = None) -> Tuple[bool, List[str]]:
    golden = load_contract(model)
    if golden is None:
        return False, [f"no checked-in contract for {model!r} "
                       f"(run --refresh-contracts)"]
    fresh = fresh or build_contract(model)
    g_dev = golden.get("generated_with", {}).get("n_devices")
    f_dev = fresh.get("generated_with", {}).get("n_devices")
    if g_dev != f_dev:
        raise ContractEnvironmentError(
            f"{model}: golden measured on {g_dev} devices, this process "
            f"has {f_dev} — collective groups are not comparable (run "
            f"under the 8-device virtual mesh, see "
            f"contracts.ensure_virtual_mesh)")
    diffs = diff_contracts(golden, fresh)
    return not diffs, diffs


def check_all(models: Sequence[str] = MODELS) -> Tuple[bool, Dict]:
    report: Dict = {}
    ok = True
    for m in models:
        m_ok, diffs = check_model(m)
        report[m] = {"ok": m_ok, "diffs": diffs}
        ok = ok and m_ok
    return ok, report


def refresh(models: Sequence[str] = MODELS, out=print) -> None:
    """Rewrite the goldens, printing old->new for review — a contract
    change must be a decision, never an accident."""
    os.makedirs(CONTRACT_DIR, exist_ok=True)
    for m in models:
        fresh = build_contract(m)
        old = load_contract(m)
        if old is not None:
            for d in diff_contracts(old, fresh):
                out(f"  {m}: {d}")
        with open(contract_path(m), "w") as f:
            json.dump(fresh, f, indent=2)
            f.write("\n")
        out(f"refreshed {contract_path(m)}")
