"""Wire-schema lint (PROTO2xx): the distributed protocol, statically checked.

The async-SSP tier and the serving front door speak a hand-rolled RPC
vocabulary: pickled dicts with a ``"kind"`` discriminator, dispatched by
``kind ==`` chains (``ParamService._serve``,
``InferenceServer._dispatch``) and produced by client call sites
(``_rpc``/``_push_rpc``/``_pull_rpc``/``send_frame`` dict literals).
Nothing type-checks that vocabulary: a sender can invent a kind no
dispatcher handles, a handler can read a field some sender omits, a
client can read a reply key the handler never produces — and every one of
those is a runtime hang or a dropped connection in a distributed chaos
test instead of a diff-time finding. This module AST-extracts the whole
message vocabulary from both sides of each service and cross-checks it:

- PROTO201 — kind sent by a client but handled by no dispatcher branch.
- PROTO202 — kind handled by a dispatcher but sent by no known sender
  (dead vocabulary, or a sender that silently fell out of the scan).
- PROTO203 — field a handler requires (plain ``msg["f"]`` read, no
  default) that some sender of that kind omits.
- PROTO204 — reply key a client reads (plain subscript, unguarded) that
  the handler for that kind never produces.
- PROTO205 — unpickle-before-auth: a connection-serving method that
  parses frames (pickles!) before the auth handshake, or a frame-parsing
  endpoint with no handshake at all.
- PROTO206 — a non-idempotent (state-accumulating) kind whose sender
  omits the seq/clock the service's exactly-once dedup keys on.
- PROTO207 — framing: a wire length prefix that reaches the payload
  recv unchecked, or checked only against an absurd (>= 2**31) literal
  cap — the multi-petabyte-allocation-from-a-garbage-header hole.

The extraction is also EMITTED as a machine-readable protocol schema
(``evidence/protocol_schema.json``) that future PRs diff against exactly
like the HLO contract goldens: adding/removing a kind, a field, or a
reply key is a reviewed ``--refresh-schema`` decision, never an accident.
Line numbers are deliberately excluded from the schema (like finding
fingerprints) so it survives unrelated edits.

Scope and honesty: the pass is lexical and per-service. It follows ONE
hop of ``self._method(msg)`` delegation, resolves ``**view`` /
``**self._member_view()`` reply splats through same-class return
literals, and treats a subscript read guarded by an ``"k" in x`` test as
optional. Senders outside the configured files (external ops tooling)
are declared per-service instead of scanned. What it cannot resolve it
marks ``open`` and stays quiet about, rather than guessing.

Findings ride the shared machinery: ``Finding`` fingerprints,
``baseline.json`` grandfathering with written reasons, and in-place
``# static-ok: PROTO2xx`` pragmas. Everything is pure ``ast`` — jax-free
at import, fast enough for a pre-commit hook.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Finding, REPO_ROOT, pragma_suppressed, relpath

__all__ = [
    "ServiceSpec", "SERVICES", "SCHEMA_PATH", "extract_service",
    "extract_schema", "lint_framing", "run_protocol_lint", "diff_schema",
    "load_schema", "save_schema",
]

SCHEMA_PATH = os.path.join(REPO_ROOT, "evidence", "protocol_schema.json")

# call names that put a kind-keyed dict on the wire (client side)
SENDER_CALLS = ("_rpc", "_push_rpc", "_pull_rpc", "_send_msg", "send_frame")
# call names that parse a frame off the wire (server side)
RECV_CALLS = ("recv_frame", "recv_frame_sized", "_recv_msg",
              "_recv_msg_sized")
AUTH_CALLS = ("server_handshake",)


@dataclass(frozen=True)
class ServiceSpec:
    """One socket service: where its dispatcher lives, where its senders
    live, and which kinds are legitimately produced by tooling outside
    the scanned files (ops surface)."""

    name: str
    dispatcher: Tuple[str, str, str]      # (relpath, Class, method)
    recv_method: str                      # the method that parses frames
    sender_files: Tuple[str, ...]
    external_kinds: Tuple[str, ...] = ()


SERVICES: Tuple[ServiceSpec, ...] = (
    ServiceSpec(
        name="param_service",
        dispatcher=("poseidon_tpu/parallel/async_ssp.py",
                    "ParamService", "_serve"),
        recv_method="_serve",
        sender_files=("poseidon_tpu/parallel/async_ssp.py",),
    ),
    ServiceSpec(
        name="inference",
        dispatcher=("poseidon_tpu/serving/server.py",
                    "InferenceServer", "_dispatch"),
        recv_method="_serve_conn",
        sender_files=("poseidon_tpu/serving/client.py",),
    ),
)

# the framing modules PROTO207 audits (length prefix -> bounded recv)
FRAMING_TARGETS = ("poseidon_tpu/proto/wire.py",)

# an "absurd" literal frame cap: at or beyond this, a garbage header
# still buys a multi-gigabyte allocation attempt before failing
ABSURD_CAP = 1 << 31


# --------------------------------------------------------------------------- #
# small AST helpers
# --------------------------------------------------------------------------- #

def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_self_call(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "self")


def _const_str(node) -> Optional[str]:
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


def _find_class(tree: ast.Module, name: str) -> Optional[ast.ClassDef]:
    for n in tree.body:
        if isinstance(n, ast.ClassDef) and n.name == name:
            return n
    return None


def _methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _int_value(node, consts: Dict[str, int]) -> Optional[int]:
    """Evaluate a constant-ish int expression (literal, module constant,
    shifts/arithmetic of those) — enough to judge a frame-cap literal."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.BinOp):
        a = _int_value(node.left, consts)
        b = _int_value(node.right, consts)
        if a is None or b is None:
            return None
        try:
            if isinstance(node.op, ast.LShift):
                return a << b
            if isinstance(node.op, ast.Mult):
                return a * b
            if isinstance(node.op, ast.Add):
                return a + b
            if isinstance(node.op, ast.Pow):
                return a ** b
            if isinstance(node.op, ast.Sub):
                return a - b
        except Exception:  # noqa: BLE001 — absurd exponents etc.
            return None
    return None


def _module_int_consts(tree: ast.Module) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for n in tree.body:
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                isinstance(n.targets[0], ast.Name):
            v = _int_value(n.value, out)
            if v is not None:
                out[n.targets[0].id] = v
    return out


# --------------------------------------------------------------------------- #
# message-shape extraction
# --------------------------------------------------------------------------- #

@dataclass
class MsgShape:
    """What one side knows about a kind's message: required keys (plain
    subscript reads / literal dict keys), optional keys (``.get`` reads,
    conditional stores), and whether the set is closed (every dict splat
    resolved)."""

    required: Set[str] = field(default_factory=set)
    optional: Set[str] = field(default_factory=set)
    open: bool = False

    def all_keys(self) -> Set[str]:
        return self.required | self.optional


def _reads_of(body: Sequence[ast.stmt], var: str) -> MsgShape:
    """Fields read off dict ``var`` inside ``body``: ``var["f"]`` is a
    required read unless the surrounding function also membership-tests
    ``"f" in var``; ``var.get("f", ...)`` is optional."""
    shape = MsgShape()
    guarded: Set[str] = set()
    for stmt in body:
        for n in ast.walk(stmt):
            # "f" in var  (any polarity / position) — guard, not a read
            if isinstance(n, ast.Compare) and len(n.ops) == 1 and \
                    isinstance(n.ops[0], (ast.In, ast.NotIn)) and \
                    isinstance(n.comparators[0], ast.Name) and \
                    n.comparators[0].id == var:
                k = _const_str(n.left)
                if k is not None:
                    guarded.add(k)
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.value, ast.Name) and n.value.id == var \
                    and isinstance(n.ctx, ast.Load):
                k = _const_str(n.slice)
                if k is not None:
                    shape.required.add(k)
            elif isinstance(n, ast.Call) and \
                    isinstance(n.func, ast.Attribute) and \
                    n.func.attr == "get" and \
                    isinstance(n.func.value, ast.Name) and \
                    n.func.value.id == var and n.args:
                k = _const_str(n.args[0])
                if k is not None:
                    shape.optional.add(k)
    shape.optional |= shape.required & guarded
    shape.required -= guarded
    return shape


def _splat_keys(methods: Dict[str, ast.FunctionDef],
                fn: ast.FunctionDef, value: ast.expr) -> Optional[Set[str]]:
    """Resolve a ``**value`` splat (or a bare dict-valued expression) to
    its literal keys: a direct ``self._m()`` call, or a Name every one of
    whose assignments in ``fn`` is such a call, resolved through the
    method's return dict literal. None = unresolvable (schema goes open).
    """
    call = None
    if isinstance(value, ast.Call) and _is_self_call(value):
        call = value
    elif isinstance(value, ast.Name):
        calls = []
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == value.id
                    for t in n.targets):
                calls.append(n.value)
        if calls and all(isinstance(c, ast.Call) and _is_self_call(c)
                         and c.func.attr == calls[0].func.attr  # type: ignore[attr-defined]
                         for c in calls):
            call = calls[0]
    if call is None:
        return None
    target = methods.get(call.func.attr)  # type: ignore[attr-defined]
    if target is None:
        return None
    keys: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Return) and isinstance(n.value, ast.Dict):
            for k in n.value.keys:
                ks = _const_str(k) if k is not None else None
                if k is None or ks is None:
                    return None
                keys.add(ks)
    return keys or None


def _dict_keys(methods: Dict[str, ast.FunctionDef], fn: ast.FunctionDef,
               d: ast.Dict) -> Tuple[Set[str], bool]:
    """(keys, open) for a reply dict literal, resolving ``**`` splats."""
    keys: Set[str] = set()
    open_ = False
    for k, v in zip(d.keys, d.values):
        if k is None:                      # **splat
            got = _splat_keys(methods, fn, v)
            if got is None:
                open_ = True
            else:
                keys |= got
        else:
            ks = _const_str(k)
            if ks is None:
                open_ = True
            else:
                keys.add(ks)
    return keys, open_


def _reply_shapes(methods: Dict[str, ast.FunctionDef],
                  fn: ast.FunctionDef, body: Sequence[ast.stmt],
                  msg_var: str) -> Tuple[MsgShape, List[str]]:
    """Replies produced by one dispatcher branch: dict literals passed to
    send calls, dicts returned (the serving shape, where the caller
    sends the return value), and one-hop ``self._handler(msg)``
    delegation. Returns (reply shape, delegated method names)."""
    shape = MsgShape()
    delegated: List[str] = []

    def absorb_dict(d: ast.Dict) -> None:
        keys, open_ = _dict_keys(methods, fn, d)
        shape.required |= keys
        shape.open = shape.open or open_

    def absorb_name(name: str) -> None:
        # a reply assembled as  reply = {...}; reply["k"] = v; return reply
        lits = [n.value for n in ast.walk(fn)
                if isinstance(n, ast.Assign) and isinstance(n.value, ast.Dict)
                and any(isinstance(t, ast.Name) and t.id == name
                        for t in n.targets)]
        if not lits:
            # e.g. _send_msg(conn, view) where view = self._member_view()
            got = _splat_keys(methods, fn, ast.Name(id=name, ctx=ast.Load()))
            if got is None:
                shape.open = True
            else:
                shape.required |= got
            return
        for d in lits:
            absorb_dict(d)
        for n in ast.walk(fn):
            if isinstance(n, ast.Subscript) and \
                    isinstance(n.value, ast.Name) and n.value.id == name \
                    and isinstance(n.ctx, ast.Store):
                k = _const_str(n.slice)
                if k is not None:
                    shape.optional.add(k)
                else:
                    shape.open = True

    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call) and _call_name(n) in SENDER_CALLS:
                for a in n.args:
                    if isinstance(a, ast.Dict):
                        absorb_dict(a)
                    elif isinstance(a, ast.Name) and a.id not in (
                            "conn", "sock", "sk", "self"):
                        absorb_name(a.id)
            elif isinstance(n, ast.Return) and n.value is not None:
                if isinstance(n.value, ast.Dict):
                    absorb_dict(n.value)
                elif isinstance(n.value, ast.Call) and \
                        _is_self_call(n.value) and any(
                            isinstance(a, ast.Name) and a.id == msg_var
                            for a in n.value.args):
                    delegated.append(n.value.func.attr)  # type: ignore[attr-defined]
                elif isinstance(n.value, ast.Name):
                    absorb_name(n.value.id)
                elif isinstance(n.value, ast.Constant) and \
                        n.value.value is None:
                    pass                   # "bye": close, no reply
                else:
                    shape.open = True
    return shape, delegated


def _branch_mutates(methods: Dict[str, ast.FunctionDef],
                    body: Sequence[ast.stmt]) -> bool:
    """Non-idempotent detection: the branch (or a one-hop self method it
    calls) ACCUMULATES state — a keyed augmented assignment onto ``self``
    state (``self.table[k] += v``), or a call to an additive/apply helper
    (plain-name ``*add*`` functions like ``_tree_add_any``, or ``self``
    methods named ``*apply*`` like ``_apply_adarevision``). Idempotent
    membership changes (``.add``/``.discard`` on sets, admit/retire/done)
    and plain telemetry counters (``self.n += 1``) deliberately do not
    count: replaying those is harmless, so they need no seq."""
    def scan(stmts, depth) -> bool:
        for stmt in stmts:
            for n in ast.walk(stmt):
                if isinstance(n, ast.AugAssign) and \
                        isinstance(n.target, ast.Subscript):
                    root = n.target.value
                    while isinstance(root, (ast.Subscript, ast.Attribute)):
                        root = root.value
                    if isinstance(root, ast.Name) and root.id == "self":
                        return True
                if isinstance(n, ast.Call):
                    name = _call_name(n)
                    if isinstance(n.func, ast.Name) and name and \
                            "add" in name:
                        return True
                    if _is_self_call(n) and name and "apply" in name:
                        return True
                    if depth > 0 and _is_self_call(n) and \
                            n.func.attr in methods:  # type: ignore[attr-defined]
                        if scan(methods[n.func.attr].body,  # type: ignore[attr-defined]
                                depth - 1):
                            return True
        return False
    return scan(body, 1)


# --------------------------------------------------------------------------- #
# dispatcher side
# --------------------------------------------------------------------------- #

def _kind_of_test(test: ast.expr, kind_vars: Set[str],
                  msg_var: str) -> Optional[str]:
    """``kind == "push"`` / ``msg["kind"] == "push"`` -> "push"."""
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1
            and isinstance(test.ops[0], ast.Eq)):
        return None
    left, right = test.left, test.comparators[0]
    k = _const_str(right)
    if k is None:
        k, left = _const_str(left), right
    if k is None:
        return None
    if isinstance(left, ast.Name) and left.id in kind_vars:
        return k
    if isinstance(left, ast.Subscript) and \
            isinstance(left.value, ast.Name) and left.value.id == msg_var \
            and _const_str(left.slice) == "kind":
        return k
    return None


@dataclass
class HandlerInfo:
    kind: str
    line: int
    fields: MsgShape
    reply: MsgShape
    mutating: bool
    symbol: str


def _extract_dispatcher(tree: ast.Module, cls_name: str,
                        method: str) -> Dict[str, HandlerInfo]:
    cls = _find_class(tree, cls_name)
    if cls is None:
        return {}
    methods = _methods(cls)
    fn = methods.get(method)
    if fn is None:
        return {}
    args = [a.arg for a in fn.args.args if a.arg != "self"]
    # the message variable: a ``msg`` parameter (the _dispatch shape), a
    # local assigned from a frame recv (the _serve connection-loop
    # shape), or the last parameter as a fallback
    recv_locals = [n.targets[0].id for n in ast.walk(fn)
                   if isinstance(n, ast.Assign) and len(n.targets) == 1
                   and isinstance(n.targets[0], ast.Name)
                   and isinstance(n.value, ast.Call)
                   and _call_name(n.value) in RECV_CALLS]
    if "msg" in args:
        msg_var = "msg"
    elif recv_locals:
        msg_var = recv_locals[0]
    else:
        msg_var = args[-1] if args else "msg"
    kind_vars = {n.targets[0].id for n in ast.walk(fn)
                 if isinstance(n, ast.Assign) and len(n.targets) == 1
                 and isinstance(n.targets[0], ast.Name)
                 and isinstance(n.value, ast.Subscript)
                 and isinstance(n.value.value, ast.Name)
                 and n.value.value.id == msg_var
                 and _const_str(n.value.slice) == "kind"}
    out: Dict[str, HandlerInfo] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        kind = _kind_of_test(node.test, kind_vars, msg_var)
        if kind is None or kind in out:
            continue
        fields = _reads_of(node.body, msg_var)
        reply, delegated = _reply_shapes(methods, fn, node.body, msg_var)
        mutating = _branch_mutates(methods, node.body)
        for dname in delegated:
            dfn = methods.get(dname)
            if dfn is None:
                reply.open = True
                continue
            dargs = [a.arg for a in dfn.args.args if a.arg != "self"]
            dmsg = dargs[0] if dargs else msg_var
            dshape = _reads_of(dfn.body, dmsg)
            fields.required |= dshape.required
            fields.optional |= dshape.optional
            dreply, _ = _reply_shapes(methods, dfn, dfn.body, dmsg)
            reply.required |= dreply.required
            reply.optional |= dreply.optional
            reply.open = reply.open or dreply.open
            mutating = mutating or _branch_mutates(methods, dfn.body)
        fields.required.discard("kind")
        fields.optional.discard("kind")
        out[kind] = HandlerInfo(kind=kind, line=node.lineno, fields=fields,
                                reply=reply, mutating=mutating,
                                symbol=f"{cls_name}.{method}")
    return out


# --------------------------------------------------------------------------- #
# sender side
# --------------------------------------------------------------------------- #

@dataclass
class SenderSite:
    kind: str
    path: str                      # repo-relative
    line: int
    symbol: str                    # qualname of the enclosing function
    fields: MsgShape               # keys the sender puts in the message
    reply_reads: MsgShape          # keys it reads off the reply


def _function_units(tree: ast.Module):
    """Yield (qualname, fn, class_methods) for every function/method."""
    for n in tree.body:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield n.name, n, {}
        elif isinstance(n, ast.ClassDef):
            meths = _methods(n)
            for name, fn in meths.items():
                yield f"{n.name}.{name}", fn, meths


def _literal_dicts(fn: ast.FunctionDef) -> Dict[str, Tuple[MsgShape, int]]:
    """Name -> (shape, line) for dicts built as literals (+ later
    subscript stores, recorded optional) in this function."""
    out: Dict[str, Tuple[MsgShape, int]] = {}
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Dict) and \
                len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
            out[n.targets[0].id] = (_dict_literal_shape(n.value), n.lineno)
        elif isinstance(n, ast.AnnAssign) and isinstance(n.value, ast.Dict) \
                and isinstance(n.target, ast.Name):
            out[n.target.id] = (_dict_literal_shape(n.value), n.lineno)
    for n in ast.walk(fn):
        if isinstance(n, ast.Subscript) and isinstance(n.value, ast.Name) \
                and n.value.id in out and isinstance(n.ctx, ast.Store):
            k = _const_str(n.slice)
            if k is not None:
                out[n.value.id][0].optional.add(k)
            else:
                out[n.value.id][0].open = True
    return out


def _dict_literal_shape(d: ast.Dict) -> MsgShape:
    shape = MsgShape()
    for k in d.keys:
        ks = _const_str(k) if k is not None else None
        if ks is None:
            shape.open = True
        else:
            shape.required.add(ks)
    return shape


def _reply_reads_for(fn: ast.FunctionDef, call: ast.Call,
                     methods: Dict[str, ast.FunctionDef]) -> MsgShape:
    """Reply keys read after ``var = self._rpc({...})``: subscripts and
    ``.get`` on the assigned name, plus ONE hop into ``self._m(var)``."""
    target: Optional[str] = None
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and n.value is call and \
                len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
            target = n.targets[0].id
    if target is None:
        return MsgShape()
    shape = _reads_of(fn.body, target)
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and _is_self_call(n) and any(
                isinstance(a, ast.Name) and a.id == target
                for a in n.args):
            hop = methods.get(n.func.attr)  # type: ignore[attr-defined]
            if hop is None:
                continue
            hargs = [a.arg for a in hop.args.args if a.arg != "self"]
            if not hargs:
                continue
            pos = next(i for i, a in enumerate(n.args)
                       if isinstance(a, ast.Name) and a.id == target)
            if pos >= len(hargs):
                continue
            hshape = _reads_of(hop.body, hargs[pos])
            shape.required |= hshape.required
            shape.optional |= hshape.optional
    return shape


def _extract_senders(tree: ast.Module, rel: str) -> List[SenderSite]:
    sites: List[SenderSite] = []
    for qual, fn, methods in _function_units(tree):
        local = _literal_dicts(fn)
        for n in ast.walk(fn):
            if not (isinstance(n, ast.Call)
                    and _call_name(n) in SENDER_CALLS):
                continue
            shape: Optional[MsgShape] = None
            line = n.lineno
            for a in n.args:
                if isinstance(a, ast.Dict):
                    cand = _dict_literal_shape(a)
                    if "kind" in cand.required:
                        shape = cand
                elif isinstance(a, ast.Name) and a.id in local:
                    cand = local[a.id][0]
                    if "kind" in cand.required:
                        shape = cand
            if shape is None:
                continue
            # the kind value: re-find it in whichever dict matched
            kind = None
            for a in n.args:
                d = a if isinstance(a, ast.Dict) else None
                if d is None and isinstance(a, ast.Name) and a.id in local:
                    for m in ast.walk(fn):
                        if isinstance(m, ast.Assign) and \
                                isinstance(m.value, ast.Dict) and any(
                                    isinstance(t, ast.Name) and t.id == a.id
                                    for t in m.targets):
                            d = m.value
                        elif isinstance(m, ast.AnnAssign) and \
                                isinstance(m.value, ast.Dict) and \
                                isinstance(m.target, ast.Name) and \
                                m.target.id == a.id:
                            d = m.value
                if d is None:
                    continue
                for k, v in zip(d.keys, d.values):
                    if k is not None and _const_str(k) == "kind":
                        kind = _const_str(v)
                if kind is not None:
                    break
            if kind is None:
                continue               # dynamic kind: out of lexical scope
            fields = MsgShape(required=set(shape.required) - {"kind"},
                              optional=set(shape.optional) - {"kind"},
                              open=shape.open)
            sites.append(SenderSite(
                kind=kind, path=rel, line=line, symbol=qual, fields=fields,
                reply_reads=_reply_reads_for(fn, n, methods)))
    return sites


# --------------------------------------------------------------------------- #
# PROTO205: auth-before-unpickle
# --------------------------------------------------------------------------- #

def _auth_findings(tree: ast.Module, rel: str, cls_name: str,
                   recv_method: str) -> List[Finding]:
    cls = _find_class(tree, cls_name)
    if cls is None:
        return []
    fn = _methods(cls).get(recv_method)
    if fn is None:
        return []
    recv_lines = [n.lineno for n in ast.walk(fn)
                  if isinstance(n, ast.Call) and _call_name(n) in RECV_CALLS]
    if not recv_lines:
        return []
    auth_lines = [n.lineno for n in ast.walk(cls)
                  if isinstance(n, ast.Call) and _call_name(n) in AUTH_CALLS]
    sym = f"{cls_name}.{recv_method}"
    if not auth_lines:
        return [Finding(
            rule="PROTO205", path=rel, line=min(recv_lines), symbol=sym,
            key="no-auth",
            message="frame-parsing endpoint (pickle loads!) with no "
                    "connection handshake anywhere in the class — anyone "
                    "who can reach the port gets code execution")]
    if min(auth_lines) > min(recv_lines):
        return [Finding(
            rule="PROTO205", path=rel, line=min(recv_lines), symbol=sym,
            key="unpickle-before-auth",
            message=f"first frame parse (line {min(recv_lines)}) precedes "
                    f"the auth handshake (line {min(auth_lines)}): "
                    f"unauthenticated bytes reach the pickle loader")]
    return []


# --------------------------------------------------------------------------- #
# PROTO207: framing length-prefix audit
# --------------------------------------------------------------------------- #

def lint_framing(path: str, source: Optional[str] = None,
                 tree: Optional[ast.Module] = None) -> List[Finding]:
    """Audit a framing module: every wire-decoded length that flows into
    the payload recv must first be bounds-checked, and a literal cap must
    be sane (< 2**31). A configurable cap (function call / attribute
    read) passes — configurability is the fix, not the hole."""
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    if tree is None:
        tree = ast.parse(source)
    rel = relpath(path)
    consts = _module_int_consts(tree)
    findings: List[Finding] = []
    for qual, fn, _ in _function_units(tree):
        # length names: (n,) = struct.unpack(...) / n = struct.unpack(...)[0]
        length_names: Set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1:
                val = n.value
                unpacked = (isinstance(val, ast.Call)
                            and _call_name(val) == "unpack")
                if isinstance(val, ast.Subscript):
                    unpacked = (isinstance(val.value, ast.Call)
                                and _call_name(val.value) == "unpack")
                if not unpacked:
                    continue
                t = n.targets[0]
                if isinstance(t, (ast.Tuple, ast.List)):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            length_names.add(e.id)
                elif isinstance(t, ast.Name):
                    length_names.add(t.id)
        if not length_names:
            continue
        recvs = [n for n in ast.walk(fn)
                 if isinstance(n, ast.Call)
                 and _call_name(n) in ("recv_exact", "recv")
                 and any(isinstance(a, ast.Name) and a.id in length_names
                         for a in n.args)]
        if not recvs:
            continue
        # function-local constant assignments overlay the module ones
        # (``cap = 1 << 32`` inside the recv function is just as absurd)
        local_consts = dict(consts)
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 and \
                    isinstance(n.targets[0], ast.Name):
                v = _int_value(n.value, local_consts)
                if v is not None:
                    local_consts[n.targets[0].id] = v
        caps: List[Tuple[int, Optional[int]]] = []   # (line, literal or None)
        for n in ast.walk(fn):
            if isinstance(n, ast.Compare) and len(n.ops) == 1 and \
                    isinstance(n.ops[0], (ast.Gt, ast.GtE, ast.Lt,
                                          ast.LtE)):
                sides = (n.left, n.comparators[0])
                if any(isinstance(s, ast.Name) and s.id in length_names
                       for s in sides):
                    other = sides[1] if (isinstance(sides[0], ast.Name)
                                         and sides[0].id in length_names) \
                        else sides[0]
                    caps.append((n.lineno,
                                 _int_value(other, local_consts)))
        first_recv = min(r.lineno for r in recvs)
        pre = [c for c in caps if c[0] <= first_recv]
        if not pre:
            findings.append(Finding(
                rule="PROTO207", path=rel, line=first_recv, symbol=qual,
                key="unchecked-length",
                message="wire-decoded length prefix reaches the payload "
                        "recv with no bounds check — a garbage header is "
                        "an attempted multi-petabyte allocation"))
            continue
        for line, cap in pre:
            if cap is not None and cap >= ABSURD_CAP:
                findings.append(Finding(
                    rule="PROTO207", path=rel, line=line, symbol=qual,
                    key="absurd-cap",
                    message=f"frame cap {cap} (>= {ABSURD_CAP}) still "
                            f"admits multi-gigabyte allocations from a "
                            f"garbage header; use a configurable sane "
                            f"cap (see wire.max_frame_bytes)"))
    return findings


# --------------------------------------------------------------------------- #
# cross-check + schema
# --------------------------------------------------------------------------- #

def _pragma_filter(findings: List[Finding]) -> List[Finding]:
    """Apply the shared in-place ``# static-ok: RULE`` suppression (same
    grammar as the THR/JIT lints), loading each finding's file once."""
    kept: List[Finding] = []
    cache: Dict[str, List[str]] = {}
    for f in findings:
        path = f.path if os.path.isabs(f.path) \
            else os.path.join(REPO_ROOT, f.path)
        if f.path not in cache:
            try:
                with open(path, encoding="utf-8") as fh:
                    cache[f.path] = fh.read().splitlines()
            except OSError:
                cache[f.path] = []
        if not pragma_suppressed(cache[f.path], f):
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.key))
    return kept


def extract_service(spec: ServiceSpec,
                    root: str = REPO_ROOT) -> Tuple[Dict, List[Finding]]:
    """Extract one service's schema and cross-check findings
    (pragma-filtered)."""
    findings: List[Finding] = []

    def load(rel: str) -> Optional[ast.Module]:
        path = rel if os.path.isabs(rel) else os.path.join(root, rel)
        try:
            with open(path, encoding="utf-8") as f:
                return ast.parse(f.read())
        except (OSError, SyntaxError):
            findings.append(Finding(
                rule="PROTO200", path=relpath(path), line=1,
                symbol="<config>", key="unreadable",
                message=f"configured protocol file missing or "
                        f"unparseable: {rel}"))
            return None

    drel, cls_name, method = spec.dispatcher
    dtree = load(drel)
    handlers = (_extract_dispatcher(dtree, cls_name, method)
                if dtree is not None else {})
    if dtree is not None:
        findings.extend(_auth_findings(dtree, relpath(
            drel if os.path.isabs(drel) else os.path.join(root, drel)),
            cls_name, spec.recv_method))
    senders: List[SenderSite] = []
    for srel in spec.sender_files:
        stree = dtree if srel == drel else load(srel)
        if stree is None:
            continue
        sp = srel if os.path.isabs(srel) else os.path.join(root, srel)
        senders.extend(_extract_senders(stree, relpath(sp)))
    drel_rep = relpath(drel if os.path.isabs(drel)
                       else os.path.join(root, drel))

    by_kind: Dict[str, List[SenderSite]] = {}
    for s in senders:
        by_kind.setdefault(s.kind, []).append(s)

    # PROTO201: sent but unhandled
    for s in senders:
        if handlers and s.kind not in handlers:
            findings.append(Finding(
                rule="PROTO201", path=s.path, line=s.line, symbol=s.symbol,
                key=f"kind:{s.kind}",
                message=f"kind {s.kind!r} is sent here but no "
                        f"{cls_name}.{method} branch handles it — the "
                        f"service will drop this connection as a bad "
                        f"request"))
    # PROTO202: handled but never sent
    for kind, h in handlers.items():
        if kind not in by_kind and kind not in spec.external_kinds:
            findings.append(Finding(
                rule="PROTO202", path=drel_rep, line=h.line, symbol=h.symbol,
                key=f"kind:{kind}",
                message=f"kind {kind!r} has a dispatcher branch but no "
                        f"scanned sender produces it — dead vocabulary, "
                        f"or a sender fell out of the configured scan "
                        f"(declare it in external_kinds if it is ops "
                        f"tooling)"))
    # PROTO203 / PROTO206 per sender site
    for kind, sites in by_kind.items():
        h = handlers.get(kind)
        if h is None:
            continue
        for s in sites:
            if s.fields.open:
                continue
            for f in sorted(h.fields.required):
                if f not in s.fields.all_keys():
                    findings.append(Finding(
                        rule="PROTO203", path=s.path, line=s.line,
                        symbol=s.symbol, key=f"{kind}.{f}",
                        message=f"handler for {kind!r} requires field "
                                f"{f!r} (plain msg[{f!r}] read) but this "
                                f"sender omits it — KeyError server-side, "
                                f"connection dropped"))
            if h.mutating:
                need = ["clock"]
                if "seq" not in h.fields.optional:
                    need.append("seq")
                for f in need:
                    if f not in s.fields.all_keys():
                        findings.append(Finding(
                            rule="PROTO206", path=s.path, line=s.line,
                            symbol=s.symbol, key=f"{kind}.{f}",
                            message=f"{kind!r} accumulates service state "
                                    f"but this sender omits {f!r} — the "
                                    f"exactly-once seq/clock dedup cannot "
                                    f"cover a replay of this message"))
            # PROTO204: reply reads vs produced keys
            if not h.reply.open:
                for f in sorted(s.reply_reads.required):
                    if f not in h.reply.all_keys():
                        findings.append(Finding(
                            rule="PROTO204", path=s.path, line=s.line,
                            symbol=s.symbol, key=f"{kind}.reply.{f}",
                            message=f"client reads reply key {f!r} of "
                                    f"{kind!r} but no handler reply "
                                    f"produces it — KeyError client-side"))

    schema = {
        "dispatcher": f"{drel}:{cls_name}.{method}",
        "kinds": {
            kind: {
                "required_fields": sorted(h.fields.required),
                "optional_fields": sorted(h.fields.optional),
                "reply_keys": sorted(h.reply.all_keys()),
                "reply_open": h.reply.open,
                "mutating": h.mutating,
                "senders": sorted({f"{s.path}:{s.symbol}"
                                   for s in by_kind.get(kind, ())}),
                "sender_fields": sorted(set().union(*(
                    s.fields.all_keys() for s in by_kind.get(kind, ())))
                    if by_kind.get(kind) else set()),
                "client_reads": sorted(set().union(*(
                    s.reply_reads.all_keys()
                    for s in by_kind.get(kind, ())))
                    if by_kind.get(kind) else set()),
            }
            for kind, h in sorted(handlers.items())
        },
        "unhandled_kinds": sorted(k for k in by_kind if k not in handlers),
    }
    return schema, _pragma_filter(findings)


# one-process memo for the DEFAULT extraction: a single CLI run invokes
# it from both run_lints (findings) and the --protocols gate (schema),
# and the sources cannot change mid-process. Custom specs/roots (tests,
# fixtures) bypass the memo entirely.
_default_memo: Optional[Tuple[Dict, List[Finding]]] = None


def extract_schema(services: Sequence[ServiceSpec] = SERVICES,
                   root: str = REPO_ROOT) -> Tuple[Dict, List[Finding]]:
    """The full protocol schema + every PROTO finding (pragma-filtered)."""
    global _default_memo
    is_default = services is SERVICES and root == REPO_ROOT
    if is_default and _default_memo is not None:
        return _default_memo
    schema: Dict = {"comment": "Machine-extracted wire-protocol schema "
                               "(poseidon_tpu.analysis.protocol). Diffed "
                               "in CI; change it with --refresh-schema, "
                               "never by hand.",
                    "services": {}}
    findings: List[Finding] = []
    for spec in services:
        s, f = extract_service(spec, root=root)
        schema["services"][spec.name] = s
        findings.extend(f)
    framing: List[Finding] = []
    for rel in FRAMING_TARGETS:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            framing.extend(lint_framing(path))
    # service findings arrive already pragma-filtered by extract_service;
    # only the framing additions still need the pass (filtering twice
    # would re-read every finding's source file for nothing)
    findings = sorted(findings + _pragma_filter(framing),
                      key=lambda f: (f.path, f.line, f.rule, f.key))
    out = (schema, findings)
    if is_default:
        _default_memo = out
    return out


def run_protocol_lint(root: str = REPO_ROOT) -> List[Finding]:
    return extract_schema(root=root)[1]


# --------------------------------------------------------------------------- #
# schema persistence + diff
# --------------------------------------------------------------------------- #

def load_schema(path: Optional[str] = None) -> Optional[Dict]:
    path = path or SCHEMA_PATH
    if not os.path.exists(path):
        return None
    with open(path) as f:
        return json.load(f)


def save_schema(schema: Dict, path: Optional[str] = None) -> str:
    path = path or SCHEMA_PATH
    d = os.path.dirname(path)
    if d:                      # a bare filename has no directory to make
        os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(schema, f, indent=2, sort_keys=True)
        f.write("\n")
    return path


def diff_schema(golden: Dict, fresh: Dict) -> List[str]:
    """Structural old->new diff, one line per changed path. Pure — tests
    feed it synthetic mutations."""
    diffs: List[str] = []

    def walk(prefix: str, g, f) -> None:
        if isinstance(g, dict) and isinstance(f, dict):
            for k in sorted(set(g) | set(f)):
                if k == "comment":
                    continue
                kp = f"{prefix}.{k}" if prefix else k
                if k not in g:
                    walk(kp, None, f[k])
                elif k not in f:
                    walk(kp, g[k], None)
                else:
                    walk(kp, g[k], f[k])
        elif g != f:
            diffs.append(f"{prefix}: {g!r} -> {f!r}")

    walk("", golden, fresh)
    return diffs
