"""Jit/dispatch-hygiene lint: host syncs, retrace hazards, f64, scopes.

The step pipeline (PR 5) only overlaps if nothing inside the dispatch
window forces a host<->device round-trip, and the attribution table (PR 7)
only stays honest if the named scopes it joins on survive refactors. Both
properties are lexical — so they are lintable:

- **JIT101 host-sync-in-traced**: an implicit host sync (``.item()``,
  ``np.asarray``/``np.array``, ``jax.device_get``,
  ``.block_until_ready()``, ``float()``/``int()`` on a computed value)
  inside a TRACED function — one decorated with / passed to ``jax.jit``,
  ``jax.grad``, ``jax.vmap``, ``jax.lax.scan`` etc., or nested in one.
  Inside a trace these either fail at trace time or, worse, silently
  constant-fold a device value into the compiled program.
- **JIT102 host-sync-in-window**: the same sync calls inside the engine's
  dispatch window — the configured method set below plus everything they
  reach intra-class and module-level helpers they call directly. A sync
  here serializes the pipelined loop (the regression class
  ``input_stall_ms_per_step`` measures after the fact; this catches it
  before).
- **JIT103 retrace-hazard**: ``jax.jit`` applied inside a loop body or to
  a ``lambda`` — each evaluation makes a fresh wrapper with an empty
  cache, so every call retraces; also jit ``static_argnums``/
  ``static_argnames`` functions whose parameter defaults are unhashable
  (list/dict/set) — the call fails or retraces per step.
- **JIT104 f64-promotion**: explicit float64 dtypes (``np.float64``,
  ``jnp.float64``, ``astype("float64")``, ``dtype=float``) — under
  ``jax_enable_x64=False`` these silently degrade to f32 with a warning
  at best; under x64 they double every byte of the buffer they touch.
- **JIT105 missing-named-scope**: the attribution spine's required
  ``jax.named_scope`` coverage (REQUIRED_SCOPES below). Removing one
  silently reclassifies that phase's device time into the
  ``(unattributed)`` residual row of the per-layer table.
- **JIT106 checkpoint-body-scope**: in REMAT_SCOPE_FILES, a local
  function handed to ``jax.checkpoint``/``jax.remat`` must itself
  contain a ``named_scope`` call. The HBM budget planner (core/remat.py)
  wraps chosen layers' forward bodies in ``jax.checkpoint``; the ops XLA
  RECOMPUTES during backward carry only the scopes inside the
  checkpointed body — a scope left outside it covers the forward pass
  and silently drops the recompute cost into ``(unattributed)``.

**Pallas kernel bodies** (functions passed — directly or through
``functools.partial`` — as the first argument of a ``pl.pallas_call``) are
traced too, so JIT101 covers them, with one carve-out: the ``np.*``
patterns are NOT flagged there. Inside a Mosaic kernel every value is a
Ref or a trace-time constant — ``np.asarray`` on static index math cannot
be a device sync because there is no device value to sync — while
``.item()`` / ``.block_until_ready()`` / ``jax.device_get`` remain real
defects (they cannot lower at all) and still fire.

Pure ``ast``; jax-free at import.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from . import Finding, pragma_on_line, relpath

# wrappers whose function argument is traced
TRACING_WRAPPERS = {"jit", "grad", "value_and_grad", "vmap", "pmap",
                    "checkpoint", "remat", "custom_vjp", "custom_jvp",
                    "shard_map", "scan", "while_loop", "fori_loop",
                    "cond", "eval_shape", "make_jaxpr"}

# method-call syncs: x.item(), x.block_until_ready()
SYNC_METHODS = {"item", "block_until_ready"}
# attribute-path syncs rooted at numpy / jax aliases
SYNC_NP_FUNCS = {"asarray", "array"}
SYNC_JAX_FUNCS = {"device_get"}

# The engine's dispatch window: between two hard-sync boundaries these are
# the only frames that run per step, so a host sync in any of them (or in
# what they reach) stalls the pipelined loop. Extend this table when the
# window grows new frames.
WINDOW_METHODS: Dict[str, Set[str]] = {
    "poseidon_tpu/runtime/engine.py": {
        "Engine._dispatch_train_step", "Engine._next_batch",
        "Engine._next_batch_stack", "Engine._absorb",
        "Engine._check_divergence"},
    "poseidon_tpu/runtime/metrics.py": {
        "AsyncScalarFetcher.put", "AsyncScalarFetcher.take_drained"},
    "poseidon_tpu/data/pipeline.py": {"DevicePrefetcher.__next__"},
}

# PR 7's attribution contract: these scope names must keep appearing in
# these modules (prefix match, so f-string suffixes like bucket indices
# are fine). core/net.py is special-cased: the per-layer scope is dynamic
# (jax.named_scope(layer.name)), so the rule requires at least one
# named_scope call with a non-literal argument there.
REQUIRED_SCOPES: Dict[str, Tuple[str, ...]] = {
    "poseidon_tpu/core/arena.py": ("arena_pack", "arena_unpack",
                                   "arena_views", "arena_grads"),
    "poseidon_tpu/solvers/updates.py": ("optimizer_update",),
    "poseidon_tpu/parallel/strategies.py": ("grad_sync_bucket",),
    "poseidon_tpu/core/net.py": (),
}

# JIT106's scope: files where jax.checkpoint wraps attribution-scoped
# layer bodies (the remat planner's wiring). Extend when another module
# grows checkpointed per-layer forwards.
REMAT_SCOPE_FILES: Set[str] = {"poseidon_tpu/core/net.py"}


def _alias_map(tree: ast.Module) -> Dict[str, str]:
    """{local name: canonical root} for numpy / jax / jax.numpy imports."""
    out: Dict[str, str] = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                root = a.name.split(".")[0]
                if root in ("numpy", "jax"):
                    if a.asname:
                        out[a.asname] = (
                            "np" if root == "numpy" else
                            ("jnp" if a.name == "jax.numpy" else
                             ("pallas" if a.name.startswith(
                                 "jax.experimental.pallas") else "jax")))
                    else:
                        # `import jax.numpy` binds only the ROOT name —
                        # mapping 'jax' to jnp would blind the
                        # jax.device_get checks
                        out[root] = "np" if root == "numpy" else "jax"
        elif isinstance(n, ast.ImportFrom) and n.module:
            root = n.module.split(".")[0]
            if root == "jax" and n.module == "jax.numpy":
                for a in n.names:
                    out.setdefault(a.asname or a.name, "jnp_member")
            elif n.module.startswith("jax.experimental"):
                for a in n.names:
                    if a.name == "pallas":     # from jax.experimental ...
                        out[a.asname or a.name] = "pallas"
                    elif a.name == "pallas_call":
                        out[a.asname or a.name] = "pallas_member"
                    elif a.name in TRACING_WRAPPERS:
                        # from jax.experimental.shard_map import shard_map:
                        # still a tracing wrapper — this branch must not
                        # shadow the plain-jax mapping below
                        out[a.asname or a.name] = "jax_member"
            elif root == "jax":
                for a in n.names:
                    if a.name in TRACING_WRAPPERS:
                        out[a.asname or a.name] = "jax_member"
                    elif a.name == "numpy":    # from jax import numpy as jnp
                        out[a.asname or a.name] = "jnp"
            elif root == "numpy":
                for a in n.names:
                    if a.name in SYNC_NP_FUNCS:
                        out[a.asname or a.name] = "np_member"
    return out


def _root_of(node) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _is_const(node) -> bool:
    return isinstance(node, ast.Constant) or (
        isinstance(node, ast.UnaryOp) and _is_const(node.operand))


class _SyncFinder(ast.NodeVisitor):
    """Collect host-sync call sites within one function body.

    ``scalars`` additionally reports ``float()``/``int()`` on computed
    values — meaningful only in HOST code (the dispatch window), where
    they silently block on the device. In traced code they fail loudly at
    trace time, so flagging them there would only re-report what the
    first compile already screams about."""

    def __init__(self, aliases: Dict[str, str], scalars: bool = False,
                 descend: bool = True):
        self.aliases = aliases
        self.scalars = scalars
        self.descend = descend
        self.hits: List[Tuple[int, str]] = []

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in SYNC_METHODS and not node.args:
                self.hits.append((node.lineno, f".{f.attr}()"))
            else:
                root = _root_of(f)
                canon = self.aliases.get(root or "", "")
                if canon == "np" and f.attr in SYNC_NP_FUNCS:
                    self.hits.append((node.lineno, f"np.{f.attr}"))
                elif canon == "jax" and f.attr in SYNC_JAX_FUNCS:
                    self.hits.append((node.lineno, f"jax.{f.attr}"))
        elif isinstance(f, ast.Name):
            if self.scalars and f.id in ("float", "int") and \
                    len(node.args) == 1 and not _is_const(node.args[0]):
                self.hits.append((node.lineno, f"{f.id}()"))
            elif self.aliases.get(f.id) == "np_member":
                self.hits.append((node.lineno, f.id))
        self.generic_visit(node)

    # JIT101 scans each nested def under its own qualname (the nesting
    # closure puts it in the traced set), so it must NOT also descend
    # here — the same sync would land twice under two fingerprints. The
    # JIT102 reachability walk never indexes nested defs, so it keeps
    # descending.
    def visit_FunctionDef(self, node):
        if self.descend:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef


def _fn_pragma(lines: Sequence[str], node, rule: str) -> bool:
    """``# static-ok: RULE`` on (or just above) a ``def`` line suppresses
    the rule for the whole function — for designated sync points whose
    docstring already explains itself (``scalar_rows`` IS where the
    pipeline waits)."""
    return any(pragma_on_line(lines, ln, rule)
               for ln in (node.lineno, node.lineno - 1))


def _function_index(tree: ast.Module) -> Dict[str, ast.AST]:
    """{qualname: FunctionDef} with Class.method / fn.<local> nesting."""
    out: Dict[str, ast.AST] = {}

    def walk(node, prefix):
        for ch in ast.iter_child_nodes(node):
            if isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{ch.name}"
                out[q] = ch
                walk(ch, q + ".")
            elif isinstance(ch, ast.ClassDef):
                walk(ch, f"{prefix}{ch.name}.")
            else:
                walk(ch, prefix)

    walk(tree, "")
    return out


def _traced_functions(tree: ast.Module, aliases: Dict[str, str],
                      index: Dict[str, ast.AST]) -> Set[str]:
    """Qualnames of functions that run under a jax trace: decorated with a
    tracing wrapper, passed to one by (local) name, or nested in one."""
    traced: Set[str] = set()
    by_node = {id(n): q for q, n in index.items()}

    def wrapper_name(func) -> Optional[str]:
        # jax.jit / jit / partial(jax.jit, ...) / functools.partial(jit)
        if isinstance(func, ast.Attribute):
            if func.attr in TRACING_WRAPPERS:
                root = _root_of(func)
                if aliases.get(root or "") in ("jax", "jnp") or \
                        root in ("lax", "jax"):
                    return func.attr
            return None
        if isinstance(func, ast.Name):
            if aliases.get(func.id) == "jax_member" or \
                    func.id in ("jit", "shard_map"):
                return func.id
        return None

    # decorators
    for q, node in index.items():
        for dec in getattr(node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Call):  # partial(jax.jit, ...)
                target = target.func
            if wrapper_name(target) is not None:
                traced.add(q)
            elif isinstance(dec, ast.Call) and any(
                    wrapper_name(a) for a in dec.args
                    if isinstance(a, (ast.Attribute, ast.Name))):
                traced.add(q)       # partial(jax.jit, ...) as a Call dec

    # call sites: jax.jit(f) where f is a Name resolving to a sibling def
    class V(ast.NodeVisitor):
        def __init__(self):
            self.scope: List[str] = []

        def visit_FunctionDef(self, node):
            self.scope.append(by_node.get(id(node), node.name))
            self.generic_visit(node)
            self.scope.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            if wrapper_name(node.func) is not None:
                # fn position varies by wrapper: jit/scan at args[0],
                # while_loop cond/body at [0]/[1], cond branches at
                # [1]/[2], fori_loop body at [2]
                for arg in node.args[:3]:
                    if isinstance(arg, ast.Name):
                        # resolve innermost-scope-first; scope entries
                        # are already full qualnames, so each candidate
                        # is one enclosing qualname + the bare name
                        for enclosing in reversed(self.scope):
                            q = f"{enclosing}.{arg.id}"
                            if q in index:
                                traced.add(q)
                                break
                        else:
                            if arg.id in index:
                                traced.add(arg.id)
                    elif (isinstance(arg, ast.Attribute)
                          and isinstance(arg.value, ast.Name)
                          and arg.value.id == "self"):
                        # jax.jit(self._fwd): `self` binds to the class
                        # the enclosing method hangs off, so peel
                        # trailing qualname segments until a sibling
                        # matches (Class.method.local -> Class._fwd)
                        for enclosing in reversed(self.scope):
                            parts = enclosing.split(".")
                            hit = next(
                                (q for k in range(len(parts) - 1, 0, -1)
                                 if (q := ".".join(parts[:k] + [arg.attr]))
                                 in index), None)
                            if hit is not None:
                                traced.add(hit)
                                break
            self.generic_visit(node)

    V().visit(tree)
    # nesting closure: everything defined inside a traced function traces
    for q in list(index):
        for t in list(traced):
            if q.startswith(t + "."):
                traced.add(q)
    return traced


def _pallas_kernel_bodies(tree: ast.Module, aliases: Dict[str, str],
                          index: Dict[str, ast.AST]) -> Set[str]:
    """Qualnames of functions handed to ``pl.pallas_call`` as the kernel —
    directly, or wrapped in ``functools.partial(kernel, ...)`` (the
    repo's static-parameter idiom)."""
    bodies: Set[str] = set()

    def is_pallas_call(func) -> bool:
        if isinstance(func, ast.Attribute) and func.attr == "pallas_call":
            return aliases.get(_root_of(func) or "") == "pallas"
        return isinstance(func, ast.Name) and \
            aliases.get(func.id) == "pallas_member"

    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call) and is_pallas_call(n.func)
                and n.args):
            continue
        k = n.args[0]
        if isinstance(k, ast.Call):            # functools.partial(kernel, …)
            f = k.func
            is_partial = (isinstance(f, ast.Name) and f.id == "partial") \
                or (isinstance(f, ast.Attribute) and f.attr == "partial")
            if is_partial and k.args:
                k = k.args[0]
        if isinstance(k, ast.Name) and k.id in index:
            bodies.add(k.id)
    return bodies


def _named_scope_strings(tree: ast.Module) -> Tuple[Set[str], bool]:
    """(literal/prefix scope names, saw a dynamic-arg named_scope call)."""
    names: Set[str] = set()
    dynamic = False
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call) and n.args and (
                (isinstance(n.func, ast.Attribute)
                 and n.func.attr == "named_scope")
                or (isinstance(n.func, ast.Name)
                    and n.func.id == "named_scope"))):
            continue
        a = n.args[0]
        if isinstance(a, ast.Constant) and isinstance(a.value, str):
            names.add(a.value)
        elif isinstance(a, ast.JoinedStr):
            if a.values and isinstance(a.values[0], ast.Constant):
                names.add(str(a.values[0].value))
            else:
                dynamic = True
        else:
            dynamic = True
    return names, dynamic


def lint_file(path: str, source: Optional[str] = None,
              tree: Optional[ast.Module] = None) -> List[Finding]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    if tree is None:                 # run_lints hands in a shared parse
        try:
            tree = ast.parse(source)
        except SyntaxError:
            return []        # threads.py already reports THR000
    rel = relpath(path)
    lines = source.splitlines()
    aliases = _alias_map(tree)
    index = _function_index(tree)
    findings: List[Finding] = []

    # ---- JIT101: host sync inside traced functions -------------------- #
    pallas_bodies = _pallas_kernel_bodies(tree, aliases, index)
    for q in sorted(_traced_functions(tree, aliases, index)
                    | pallas_bodies):
        node = index[q]
        if _fn_pragma(lines, node, "JIT101"):
            continue
        body = ast.Module(body=list(node.body), type_ignores=[])
        sf = _SyncFinder(aliases, descend=False)
        sf.visit(body)
        in_kernel = q in pallas_bodies
        for line, what in sf.hits:
            if in_kernel and (what.startswith("np.")
                              or aliases.get(what) == "np_member"):
                # Mosaic kernel body: np.* on static index math is
                # trace-time constant folding, not a host sync — there is
                # no device value inside the kernel to sync on. The
                # method/jax syncs below stay flagged (they cannot lower).
                continue
            where = ("Pallas kernel body" if in_kernel
                     else "traced function")
            findings.append(Finding(
                rule="JIT101", path=rel, line=line, symbol=q, key=what,
                message=f"{what} inside {where} {q!r}: a host "
                        f"sync here either fails at trace time or "
                        f"constant-folds a device value into the "
                        f"compiled program"))

    # ---- JIT102: host sync inside the dispatch window ------------------ #
    window = WINDOW_METHODS.get(rel)
    if window:
        # a stale entry must SURFACE, not silently blind the rule (the
        # JIT105 pattern): a renamed window method with no finding here
        # would let host syncs ship unflagged forever after
        for q in sorted(window):
            if q not in index:
                findings.append(Finding(
                    rule="JIT102", path=rel, line=1, symbol="<module>",
                    key=f"missing:{q}",
                    message=f"configured dispatch-window method {q!r} no "
                            f"longer resolves — update WINDOW_METHODS or "
                            f"the host-sync gate goes blind for it"))
        reach: Set[str] = set()
        work = [q for q in window if q in index]
        while work:
            q = work.pop()
            if q in reach:
                continue
            reach.add(q)
            cls_prefix = q.rsplit(".", 1)[0] + "." if "." in q else ""
            for n in ast.walk(index[q]):
                if not isinstance(n, ast.Call):
                    continue
                callee = None
                if isinstance(n.func, ast.Attribute) and \
                        isinstance(n.func.value, ast.Name) and \
                        n.func.value.id == "self":
                    callee = cls_prefix + n.func.attr
                elif isinstance(n.func, ast.Name):
                    callee = n.func.id          # module-level helper
                if callee and callee in index and callee not in reach:
                    work.append(callee)
        for q in sorted(reach):
            node = index[q]
            if _fn_pragma(lines, node, "JIT102"):
                continue
            sf = _SyncFinder(aliases, scalars=True)
            sf.visit(ast.Module(body=list(node.body), type_ignores=[]))
            for line, what in sf.hits:
                findings.append(Finding(
                    rule="JIT102", path=rel, line=line, symbol=q, key=what,
                    message=f"{what} reachable inside the dispatch window "
                            f"(via {q!r}): a host sync here serializes "
                            f"the pipelined train loop"))

    # ---- JIT103: retrace hazards --------------------------------------- #
    class LoopJit(ast.NodeVisitor):
        def __init__(self):
            self.loops = 0

        def _jit_call(self, node) -> bool:
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "jit" and \
                    aliases.get(_root_of(f) or "") == "jax":
                return True
            return isinstance(f, ast.Name) and aliases.get(f.id) == \
                "jax_member" and f.id == "jit"

        def visit_For(self, node):
            self.loops += 1
            self.generic_visit(node)
            self.loops -= 1

        visit_While = visit_For

        def visit_Call(self, node):
            # jax.jit(f)(x) — fresh wrapper built AND invoked in place:
            # inside a loop every iteration retraces (a stored wrapper,
            # or .lower()/.compile() AOT use, is deliberate and cached)
            if isinstance(node.func, ast.Call) and \
                    self._jit_call(node.func) and self.loops:
                findings.append(Finding(
                    rule="JIT103", path=rel, line=node.lineno,
                    symbol="<loop>", key="jit-in-loop",
                    message="jax.jit(f)(...) built and invoked inside a "
                            "loop body: each iteration makes a fresh "
                            "wrapper with an empty cache and retraces"))
            if self._jit_call(node):
                if self.loops and node.args and \
                        isinstance(node.args[0], ast.Lambda):
                    findings.append(Finding(
                        rule="JIT103", path=rel, line=node.lineno,
                        symbol="<lambda>", key="jit-lambda",
                        message="jax.jit over a lambda inside a loop: "
                                "the wrapper (and its trace cache) is "
                                "rebuilt every iteration"))
                for kw in node.keywords:
                    if kw.arg in ("static_argnums", "static_argnames"):
                        fn = node.args[0] if node.args else None
                        if isinstance(fn, ast.Name) and fn.id in index:
                            fdef = index[fn.id]
                            for d in getattr(fdef.args, "defaults", []):
                                if isinstance(d, (ast.List, ast.Dict,
                                                  ast.Set)):
                                    findings.append(Finding(
                                        rule="JIT103", path=rel,
                                        line=node.lineno, symbol=fn.id,
                                        key="unhashable-static",
                                        message="static arg with an "
                                                "unhashable (list/dict/"
                                                "set) default: every "
                                                "call re-traces or "
                                                "fails to hash"))
            self.generic_visit(node)

    LoopJit().visit(tree)

    # ---- JIT104: f64 promotion ----------------------------------------- #
    for n in ast.walk(tree):
        if isinstance(n, ast.Attribute) and n.attr == "float64" and \
                aliases.get(_root_of(n) or "") in ("np", "jnp"):
            findings.append(Finding(
                rule="JIT104", path=rel, line=n.lineno, symbol="<module>",
                key="float64",
                message="explicit float64 dtype: silently degrades to "
                        "f32 without x64 mode, doubles the buffer with "
                        "it"))
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "astype" and n.args and \
                isinstance(n.args[0], ast.Constant) and \
                n.args[0].value in ("float64", "f64", "double"):
            findings.append(Finding(
                rule="JIT104", path=rel, line=n.lineno, symbol="<module>",
                key="astype-f64",
                message="astype('float64'): accidental double-precision "
                        "promotion"))
        elif isinstance(n, ast.keyword) and n.arg == "dtype" and \
                isinstance(n.value, ast.Name) and n.value.id == "float":
            findings.append(Finding(
                rule="JIT104", path=rel, line=n.value.lineno,
                symbol="<module>", key="dtype-float",
                message="dtype=float is float64 on the host: an "
                        "accidental f64 wire into the traced program"))

    # ---- JIT105: required named_scope coverage ------------------------- #
    req = REQUIRED_SCOPES.get(rel)
    if req is not None:
        present, dynamic = _named_scope_strings(tree)
        if rel.endswith("core/net.py"):
            if not dynamic:
                findings.append(Finding(
                    rule="JIT105", path=rel, line=1, symbol="<module>",
                    key="layer-scope",
                    message="the per-layer jax.named_scope(layer.name) "
                            "wrapper is gone: per-layer device-time "
                            "attribution joins on it"))
        for name in req:
            if not any(p == name or p.startswith(name) for p in present):
                findings.append(Finding(
                    rule="JIT105", path=rel, line=1, symbol="<module>",
                    key=name,
                    message=f"required named_scope {name!r} missing: its "
                            f"device time falls into the attribution "
                            f"table's (unattributed) residual"))

    # ---- JIT106: checkpointed layer bodies keep their named_scope ------ #
    if rel in REMAT_SCOPE_FILES:
        def _has_named_scope(fdef) -> bool:
            return any(
                isinstance(c, ast.Call) and (
                    (isinstance(c.func, ast.Attribute)
                     and c.func.attr == "named_scope")
                    or (isinstance(c.func, ast.Name)
                        and c.func.id == "named_scope"))
                for c in ast.walk(fdef))

        for n in ast.walk(tree):
            if not (isinstance(n, ast.Call) and n.args):
                continue
            f = n.func
            is_ckpt = ((isinstance(f, ast.Attribute)
                        and f.attr in ("checkpoint", "remat")
                        and aliases.get(_root_of(f) or "") == "jax")
                       or (isinstance(f, ast.Name)
                           and f.id in ("checkpoint", "remat")
                           and aliases.get(f.id) == "jax_member"))
            if not is_ckpt or not isinstance(n.args[0], ast.Name):
                continue
            name = n.args[0].id
            # innermost-first resolution against the qualname index; a
            # name that resolves to no local def (e.g. a parameter) is
            # out of this rule's lexical reach
            cands = sorted((q for q in index
                            if q == name or q.endswith("." + name)),
                           key=len, reverse=True)
            if not cands:
                continue
            fdef = index[cands[0]]
            if not _has_named_scope(fdef):
                findings.append(Finding(
                    rule="JIT106", path=rel, line=n.lineno,
                    symbol=cands[0], key=name,
                    message=f"checkpointed body {name!r} has no "
                            f"named_scope inside it: the ops recomputed "
                            f"during backward carry only the scopes "
                            f"INSIDE the jax.checkpoint body, so the "
                            f"layer's recompute time falls into the "
                            f"attribution table's (unattributed) "
                            f"residual"))

    return findings


def required_scope_files() -> Sequence[str]:
    return tuple(REQUIRED_SCOPES)
