"""Concurrency lint: races, lock-order cycles, and thread-unsafety idioms.

A pure-AST pass (no imports of the linted code) over every class that
either spawns a thread (``threading.Thread(target=...)``) or owns a lock
(an attribute assigned ``threading.Lock/RLock/Condition`` in ``__init__``).
For each such class it reconstructs:

- **thread entrypoints** — methods (or method-local functions) passed as a
  Thread target, plus everything reachable from them through ``self.m()``
  calls (the thread-side call graph);
- **caller-side methods** — the public surface (non-underscore methods and
  the iterator/context dunders) plus everything it reaches. A method can
  be on both sides (a poll method called from the watch thread AND a
  server op), which is exactly when its accesses race with themselves;
- **lock discipline** — which of the class's locks are held, lexically, at
  every ``self.<attr>`` access. Private helpers whose every intra-class
  call site holds a lock inherit that lock ("caller holds the lock"
  helpers), computed as an intersection-over-call-sites fixpoint.

Rules:

- **THR001 unsynchronized-shared-state**: an attribute mutated on the
  thread side and accessed on the caller side (or mutated from both) with
  no single lock common to all its accesses, where at least one mutation
  holds no lock at all. Assign / subscript-store / container-mutator form.
- **THR002 lock-order-cycle**: the class's lock-acquisition-order graph
  (nested ``with`` regions + locks acquired by callees while the caller
  holds another) contains a cycle — or a plain ``Lock`` is re-acquired
  while already held (self-deadlock).
- **THR003 check-then-act**: an ``if`` whose test reads a shared attribute
  and whose body mutates the same attribute, with no lock held — the
  classic lost-update window on shared dicts/sets.
- **THR004 unlocked-counter-increment**: the ``+=`` special case of
  THR001, split out because read-modify-write on telemetry counters is
  the race this repo has actually shipped (batcher flush counters,
  reloader failure counters, client reconnect counter).
- **THR005 jax-call-in-thread**: jax touched from a thread entrypoint's
  call graph outside the sanctioned modules (the device prefetcher and
  the scalar-drain fetcher are the ONLY blessed off-main-thread jax
  callers; jax dispatch from anywhere else fights them for the device).
- **THR006 mixed-lock-discipline**: the same attribute is mutated both
  under a lock and with no lock somewhere else in the class — whichever
  side is right, one of them is wrong. Fires even when the thread/caller
  split can't be established (lock-owning classes whose threads live
  elsewhere).

Soundness posture: per-class, lexical, intentionally modest. Cross-object
races (engine vs. fetcher) and aliased locks are out of scope; attributes
whose only writes happen in ``__init__`` are treated as
published-before-start. False positives are suppressed in place with
``# static-ok: RULE`` or grandfathered in ``baseline.json``.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from . import Finding, relpath

# attribute types (by constructor name in __init__) that make an attr a lock
LOCK_TYPES = {"Lock", "RLock", "Condition"}
# attr types that are internally synchronized — their methods are not races
SAFE_TYPES = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue",
              "Event", "Semaphore", "BoundedSemaphore", "Barrier", "local"}
# container methods that mutate the receiver
MUTATOR_METHODS = {"append", "appendleft", "add", "pop", "popleft",
                   "remove", "discard", "clear", "update", "extend",
                   "insert", "setdefault", "popitem"}
# modules blessed to call jax off the main thread (THR005)
SANCTIONED_JAX_THREAD_MODULES = {
    "poseidon_tpu/data/pipeline.py",    # DevicePrefetcher: device_put stage
    "poseidon_tpu/runtime/metrics.py",  # AsyncScalarFetcher: scalar drain
}

CALLER_DUNDERS = {"__next__", "__iter__", "__call__", "__enter__",
                  "__exit__", "__len__", "__contains__", "__getitem__",
                  "__setitem__"}

READ, WRITE, AUGWRITE, MUTCALL = "read", "write", "augwrite", "mutcall"


@dataclass
class Access:
    attr: str
    kind: str                  # read | write | augwrite | mutcall
    line: int
    locks: frozenset           # lock attr names lexically held
    method: str                # qualname within the class


@dataclass
class MethodRec:
    name: str                              # qualname (m or m.<local>f)
    node: ast.AST
    is_public: bool
    accesses: List[Access] = field(default_factory=list)
    # (callee qualname, locks held at the call site, line)
    calls: List[Tuple[str, frozenset, int]] = field(default_factory=list)
    # (lock acquired, locks lexically held just before, line)
    acquires: List[Tuple[str, frozenset, int]] = field(default_factory=list)
    # lock attrs this method acquires anywhere (for call-edge lock flow)
    own_locks: Set[str] = field(default_factory=set)
    thread_targets: Set[str] = field(default_factory=set)
    uses_jax: List[int] = field(default_factory=list)   # lines of jax calls
    # If-statements: (line, locks, attrs read in test, attrs mutated in body)
    check_then_act: List[Tuple[int, frozenset, Set[str], Set[str]]] = \
        field(default_factory=list)


class _MethodScanner(ast.NodeVisitor):
    """Walk one method (and its nested functions, as separate records)."""

    def __init__(self, cls: "_ClassInfo", qualname: str, node, jax_aliases):
        self.cls = cls
        # nested functions (qualname contains ".") are never public roots:
        # they are reachable only through edges from their enclosing
        # method (direct call, callback argument, or Thread target)
        self.rec = MethodRec(
            name=qualname, node=node,
            is_public=("." not in qualname
                       and (not qualname.startswith("_")
                            or qualname in CALLER_DUNDERS)))
        cls.methods[qualname] = self.rec
        self.jax_aliases = jax_aliases
        self._locks: Tuple[str, ...] = ()
        self._local_funcs: Set[str] = set()
        # scan for local defs first so Thread(target=localfn) resolves
        for ch in ast.walk(node):
            if isinstance(ch, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and ch is not node:
                self._local_funcs.add(ch.name)
        for stmt in node.body:
            self.visit(stmt)

    # ---- helpers ----------------------------------------------------- #
    def _held(self) -> frozenset:
        return frozenset(self._locks)

    def _self_attr(self, node) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return None

    def _record(self, attr: str, kind: str, line: int) -> None:
        if attr in self.cls.lock_attrs or attr in self.cls.safe_attrs:
            return
        if attr in self.cls.method_names:
            return                      # bound-method reference, not data
        self.rec.accesses.append(Access(attr, kind, line, self._held(),
                                        self.rec.name))

    # ---- nested functions: separate pseudo-methods -------------------- #
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        qual = f"{self.rec.name}.{node.name}"
        _MethodScanner(self.cls, qual, node, self.jax_aliases)
        # defining is not calling; an explicit Call adds the edge below

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.generic_visit(node)

    # ---- lock regions -------------------------------------------------- #
    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            attr = self._self_attr(item.context_expr)
            if attr is not None and attr in self.cls.lock_attrs:
                # extend _locks PER ITEM: in `with self._a, self._b:`
                # the second acquire happens with the first held, so it
                # must record the _a -> _b order edge exactly like the
                # nested-with spelling
                self.rec.acquires.append((attr, self._held(),
                                          item.context_expr.lineno))
                self.rec.own_locks.add(attr)
                self._locks = self._locks + (attr,)
                acquired.append(attr)
            else:
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        # pop THIS statement's items BY NAME (last occurrence each, like
        # .release()): an unbalanced .acquire() in the body must survive
        # the with-exit instead of being popped in place of the with's own
        # lock, or every later access is credited with the wrong lock
        for attr in reversed(acquired):
            self._pop_lock(attr)

    def _pop_lock(self, attr: str) -> None:
        """Drop the LAST held occurrence of ``attr`` — shared by with-exit
        and ``.release()`` so the two spellings can't desynchronize."""
        if attr in self._locks:
            i = len(self._locks) - 1 - self._locks[::-1].index(attr)
            self._locks = self._locks[:i] + self._locks[i + 1:]

    # ---- accesses ------------------------------------------------------ #
    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._store_target(t)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        # `self.count: int = v` stores exactly like the plain spelling
        # (a bare `self.count: int` with no value stores nothing)
        if node.value is not None:
            self._store_target(node.target)
            self.visit(node.value)

    def _store_target(self, t) -> None:
        attr = self._self_attr(t)
        if attr is not None:
            self._record(attr, WRITE, t.lineno)
            return
        if isinstance(t, ast.Subscript):
            attr = self._self_attr(t.value)
            if attr is not None:
                self._record(attr, WRITE, t.lineno)
                self.visit(t.slice)
                return
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._store_target(el)
            return
        self.visit(t)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self._self_attr(node.target)
        if attr is not None:
            self._record(attr, AUGWRITE, node.lineno)
        elif isinstance(node.target, ast.Subscript):
            sub = self._self_attr(node.target.value)
            if sub is not None:
                self._record(sub, AUGWRITE, node.lineno)
            self.visit(node.target.slice)
        else:
            self.visit(node.target)
        self.visit(node.value)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        attr = self._self_attr(node)
        if attr is not None and isinstance(node.ctx, ast.Load):
            self._record(attr, READ, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # self.m(...) — intra-class call edge
        attr = self._self_attr(func)
        if attr is not None and attr in self.cls.method_names:
            self.rec.calls.append((attr, self._held(), node.lineno))
        # self.x.mutator(...) — container mutation
        if isinstance(func, ast.Attribute):
            recv = self._self_attr(func.value)
            if recv is not None and func.attr in MUTATOR_METHODS:
                self._record(recv, MUTCALL, node.lineno)
            # self._lock.acquire() counts for the order graph AND credits
            # the lock lexically until its .release() — the
            # acquire/try/finally/release idiom is as locked as `with`
            # (visitation follows source order, so the extent is right
            # for the standard spelling; a conditional acquire
            # over-credits its else-branch, which this lint accepts)
            if recv is not None and recv in self.cls.lock_attrs:
                if func.attr == "acquire":
                    self.rec.acquires.append((recv, self._held(),
                                              node.lineno))
                    self.rec.own_locks.add(recv)
                    self._locks = self._locks + (recv,)
                elif func.attr == "release":
                    self._pop_lock(recv)
        # localfn(...) — edge to a nested function of this method chain
        if isinstance(func, ast.Name) and func.id in self._local_funcs:
            self.rec.calls.append((f"{self.rec.name}.{func.id}",
                                   self._held(), node.lineno))
        # callbacks: a local function or bound method passed as an
        # argument is assumed to be invoked by the callee (retry helpers,
        # executors) — the edge keeps its accesses on the caller's side
        # of the thread split instead of unreachable. The edge carries NO
        # held locks: the callback runs whenever the callee decides, not
        # under the locks held at the registration site, so it must not
        # feed "caller holds the lock" inheritance.
        for arg in list(node.args) + [kw.value for kw in node.keywords
                                      if kw.arg != "target"]:
            if isinstance(arg, ast.Name) and arg.id in self._local_funcs:
                self.rec.calls.append((f"{self.rec.name}.{arg.id}",
                                       frozenset(), node.lineno))
            else:
                m_attr = self._self_attr(arg)
                if m_attr is not None and m_attr in self.cls.method_names:
                    self.rec.calls.append((m_attr, frozenset(),
                                           node.lineno))
        # jax.<...>(...) from a thread would fight the dispatch thread
        root = func
        while isinstance(root, ast.Attribute):
            root = root.value
        if isinstance(root, ast.Name) and root.id in self.jax_aliases:
            self.rec.uses_jax.append(node.lineno)
        # Thread(target=...)
        callee = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if callee == "Thread":
            for kw in node.keywords:
                if kw.arg != "target":
                    continue
                t_attr = self._self_attr(kw.value)
                if t_attr is not None:
                    self.rec.thread_targets.add(t_attr)
                elif isinstance(kw.value, ast.Name) and \
                        kw.value.id in self._local_funcs:
                    self.rec.thread_targets.add(
                        f"{self.rec.name}.{kw.value.id}")
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        test_reads = {a for n in ast.walk(node.test)
                      for a in [self._self_attr(n)] if a}
        body_muts: Set[str] = set()
        for stmt in node.body:
            for n in ast.walk(stmt):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        a = self._self_attr(t) or (
                            self._self_attr(t.value)
                            if isinstance(t, ast.Subscript) else None)
                        if a:
                            body_muts.add(a)
                elif isinstance(n, ast.AugAssign):
                    a = self._self_attr(n.target)
                    if a:
                        body_muts.add(a)
                elif isinstance(n, ast.Call) and \
                        isinstance(n.func, ast.Attribute):
                    recv = self._self_attr(n.func.value)
                    if recv and n.func.attr in MUTATOR_METHODS:
                        body_muts.add(recv)
        overlap = {a for a in (test_reads & body_muts)
                   if a not in self.cls.lock_attrs
                   and a not in self.cls.safe_attrs}
        if overlap:
            self.rec.check_then_act.append(
                (node.lineno, self._held(), test_reads, overlap))
        self.generic_visit(node)


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, path: str, jax_aliases):
        self.node = node
        self.name = node.name
        self.path = path
        self.lock_attrs: Set[str] = set()
        self.reentrant_locks: Set[str] = set()   # RLock / Condition attrs
        self.safe_attrs: Set[str] = set()
        self.public_attrs: Set[str] = set()     # assigned in __init__, public
        self.method_names: Set[str] = {
            n.name for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self.methods: Dict[str, MethodRec] = {}
        self._classify_init()
        for n in node.body:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _MethodScanner(self, n.name, n, jax_aliases)

    def _classify_init(self) -> None:
        init = next((n for n in self.node.body
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "__init__"), None)
        if init is None:
            return
        for n in ast.walk(init):
            # plain and annotated assignment both declare attributes
            # (self._lock: threading.Lock = threading.Lock())
            if isinstance(n, ast.Assign):
                targets, value = n.targets, n.value
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                targets, value = [n.target], n.value
            else:
                continue
            for t in targets:
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                if not t.attr.startswith("_"):
                    self.public_attrs.add(t.attr)
                v = value
                if isinstance(v, ast.Call):
                    f = v.func
                    ctor = f.attr if isinstance(f, ast.Attribute) else (
                        f.id if isinstance(f, ast.Name) else "")
                    if ctor in LOCK_TYPES:
                        self.lock_attrs.add(t.attr)
                        # default Condition() wraps an RLock; only a
                        # plain Lock self-deadlocks on re-acquisition
                        if ctor in ("RLock", "Condition"):
                            self.reentrant_locks.add(t.attr)
                    elif ctor in SAFE_TYPES:
                        self.safe_attrs.add(t.attr)

    # ---- call-graph closures ------------------------------------------ #
    def entries(self) -> Set[str]:
        out: Set[str] = set()
        for m in self.methods.values():
            out |= {t for t in m.thread_targets if t in self.methods}
        return out

    def closure(self, roots: Set[str]) -> Set[str]:
        seen = set(r for r in roots if r in self.methods)
        work = list(seen)
        while work:
            m = work.pop()
            for callee, _, _ in self.methods[m].calls:
                if callee in self.methods and callee not in seen:
                    seen.add(callee)
                    work.append(callee)
        return seen

    def context_locks(self) -> Dict[str, frozenset]:
        """Locks a method can assume held because EVERY intra-class call
        site holds them ("caller holds the lock" helpers). Public methods
        and thread entrypoints assume nothing (external callers)."""
        entries = self.entries()
        sites: Dict[str, List[Tuple[str, frozenset]]] = {}
        for m in self.methods.values():
            for callee, locks, _ in m.calls:
                sites.setdefault(callee, []).append((m.name, locks))
        ctx: Dict[str, frozenset] = {m: frozenset()
                                     for m in self.methods}
        TOP = None  # lattice top: no constraint yet
        pend = {m: TOP for m in self.methods}
        for m, rec in self.methods.items():
            if rec.is_public or m in entries or m not in sites:
                pend[m] = frozenset()
        for _ in range(len(self.methods) + 2):
            changed = False
            for m, rec in self.methods.items():
                if pend[m] == frozenset() and (rec.is_public or
                                               m in entries or
                                               m not in sites):
                    continue
                acc = TOP
                for caller, locks in sites.get(m, []):
                    inherit = pend.get(caller)
                    eff = locks | (inherit if inherit not in (None,)
                                   else frozenset())
                    acc = eff if acc is None else (acc & eff)
                acc = acc if acc is not None else frozenset()
                if pend[m] != acc:
                    pend[m] = acc
                    changed = True
            if not changed:
                break
        for m in ctx:
            ctx[m] = pend[m] if pend[m] is not None else frozenset()
        return ctx


# --------------------------------------------------------------------------- #
# rule evaluation
# --------------------------------------------------------------------------- #

def _effective(acc: Access, ctx: Dict[str, frozenset]) -> frozenset:
    return acc.locks | ctx.get(acc.method, frozenset())


def _lint_class(cls: _ClassInfo, rel: str) -> List[Finding]:
    findings: List[Finding] = []
    entries = cls.entries()
    has_threads = bool(entries)
    if not has_threads and not cls.lock_attrs:
        return findings
    ctx = cls.context_locks()
    thread_side = cls.closure(entries)
    caller_roots = {m for m, rec in cls.methods.items()
                    if rec.is_public and m != "__init__"}
    caller_side = cls.closure(caller_roots)

    # __init__ is publish-before-start, but a nested def inside it that
    # is a thread TARGET (the spawn-in-constructor idiom) runs after
    # start and races like any other entrypoint — only non-thread-side
    # __init__ locals stay excluded as init-time helpers. Every rule
    # shares this exemption (a THR003 on __init__ itself would flag code
    # that provably runs before any thread exists).
    def _init_time(m: str) -> bool:
        return m == "__init__" or (m.startswith("__init__.")
                                   and m not in thread_side)

    per_attr: Dict[str, List[Access]] = {}
    for m, rec in cls.methods.items():
        if _init_time(m):
            continue
        for a in rec.accesses:
            per_attr.setdefault(a.attr, []).append(a)

    # check-then-act attrs get the more specific THR003 diagnosis; the
    # generic shared-mutation rules skip them
    cta_attrs: Set[str] = set()
    for m, rec in cls.methods.items():
        if _init_time(m):
            continue
        for _line, locks, _reads, mut_attrs in rec.check_then_act:
            if not (locks | ctx.get(m, frozenset())):
                cta_attrs |= mut_attrs

    def _is_shared(attr: str, accs: List[Access]) -> bool:
        """Cross-thread visibility: accessed from both sides, OR public
        (readable cross-object, the way server.py reads the batcher's
        counters) and written thread-side, OR mutated from both sides.
        THR003 gates on the SAME predicate — a check-then-act deferred
        out of the generic rules must not fall below its bar."""
        t_acc = [a for a in accs if a.method in thread_side]
        muts = [a for a in accs if a.kind != READ]
        return (bool(t_acc)
                and any(a.method in caller_side for a in accs)) or \
            (attr in cls.public_attrs and
             any(a.kind != READ for a in t_acc)) or \
            (any(a.method in thread_side for a in muts) and
             any(a.method in caller_side for a in muts))

    for attr, accs in sorted(per_attr.items()):
        muts = [a for a in accs if a.kind != READ]
        if not muts:
            continue
        shared = _is_shared(attr, accs)
        # THR006 first: mixed discipline needs no thread-side evidence
        locked_muts = [a for a in muts if _effective(a, ctx)]
        unlocked_muts = [a for a in muts if not _effective(a, ctx)]
        if cls.lock_attrs and locked_muts and unlocked_muts:
            a = unlocked_muts[0]
            findings.append(Finding(
                rule="THR006", path=rel, line=a.line,
                symbol=f"{cls.name}.{a.method}", key=attr,
                message=f"self.{attr} is mutated under "
                        f"{sorted(_effective(locked_muts[0], ctx))} at "
                        f"line {locked_muts[0].line} but without any lock "
                        f"here — one discipline is wrong"))
            continue
        if not (has_threads and shared):
            continue
        common = None
        for a in accs:
            eff = _effective(a, ctx)
            common = eff if common is None else (common & eff)
        if common:
            continue                        # one lock protects every access
        if not unlocked_muts:
            # every mutation holds SOME lock — but two writers under
            # DISJOINT locks still don't exclude each other
            mut_lock_sets = {frozenset(_effective(a, ctx)) for a in muts}
            if not frozenset.intersection(*mut_lock_sets):
                a = muts[0]
                desc = " vs ".join(
                    "+".join(sorted(s))
                    for s in sorted(mut_lock_sets, key=sorted))
                findings.append(Finding(
                    rule="THR006", path=rel, line=a.line,
                    symbol=f"{cls.name}.{a.method}", key=attr,
                    message=f"self.{attr} is mutated under DIFFERENT "
                            f"locks ({desc}) — writers under disjoint "
                            f"locks do not exclude each other"))
            continue                        # only torn reads — below the bar
        if attr in cta_attrs and has_threads:
            continue                        # THR003 reports this one
        a = unlocked_muts[0]
        rule = "THR004" if a.kind == AUGWRITE else "THR001"
        what = ("non-atomic increment of" if a.kind == AUGWRITE
                else "unsynchronized mutation of")
        other = "thread" if a.method in thread_side else "caller"
        findings.append(Finding(
            rule=rule, path=rel, line=a.line,
            symbol=f"{cls.name}.{a.method}", key=attr,
            message=f"{what} self.{attr} with no lock held, but the "
                    f"attribute is shared across threads "
                    f"({other}-side write; no common lock over its "
                    f"{len(accs)} accesses)"))

    # THR003 check-then-act
    if has_threads:
        for m, rec in cls.methods.items():
            if _init_time(m):
                continue
            for line, locks, _reads, mut_attrs in rec.check_then_act:
                if locks | ctx.get(m, frozenset()):
                    continue
                for attr in sorted(mut_attrs):
                    if not _is_shared(attr, per_attr.get(attr, [])):
                        continue
                    findings.append(Finding(
                        rule="THR003", path=rel, line=line,
                        symbol=f"{cls.name}.{m}", key=attr,
                        message=f"check-then-act on shared self.{attr} "
                                f"outside any lock (test reads it, body "
                                f"mutates it; another thread can "
                                f"interleave)"))

    # THR002 lock-order cycles + self-deadlock
    edges: Dict[str, Set[str]] = {}
    for m, rec in cls.methods.items():
        base = ctx.get(m, frozenset())
        for lock, held, line in rec.acquires:
            for h in (held | base):
                if h == lock:
                    if lock not in cls.reentrant_locks:
                        findings.append(Finding(
                            rule="THR002", path=rel, line=line,
                            symbol=f"{cls.name}.{m}", key=f"self:{lock}",
                            message=f"self.{lock} acquired while already "
                                    f"held — a plain threading.Lock is "
                                    f"not re-entrant and deadlocks here"))
                else:
                    edges.setdefault(h, set()).add(lock)
        # call edges: callee's own locks acquired under the caller's held
        for callee, held, line in rec.calls:
            crec = cls.methods.get(callee)
            if crec is None:
                continue
            for h in (held | base):
                for lock in crec.own_locks:
                    if h != lock:
                        edges.setdefault(h, set()).add(lock)
    seen_cycles: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str]) -> None:
        for nxt in sorted(edges.get(node, ())):
            if nxt == start:
                cyc = tuple(sorted(path))
                if cyc not in seen_cycles:
                    seen_cycles.add(cyc)
                    findings.append(Finding(
                        rule="THR002", path=rel, line=cls.node.lineno,
                        symbol=cls.name, key="->".join(cyc),
                        message=f"lock-order cycle: "
                                f"{' -> '.join(path + [start])} — two "
                                f"threads taking these in opposite order "
                                f"deadlock"))
            elif nxt not in path:
                dfs(start, nxt, path + [nxt])

    for lock in sorted(edges):
        dfs(lock, lock, [lock])

    # THR005 jax from a thread entrypoint's call graph
    if rel not in SANCTIONED_JAX_THREAD_MODULES:
        for m in sorted(thread_side):
            rec = cls.methods[m]
            if rec.uses_jax:
                findings.append(Finding(
                    rule="THR005", path=rel, line=rec.uses_jax[0],
                    symbol=f"{cls.name}.{m}", key="jax",
                    message="jax call reachable from a thread entrypoint "
                            "outside the sanctioned prefetcher/fetcher "
                            "modules — off-main-thread dispatch races the "
                            "train thread's"))
    return findings


def _jax_aliases(tree: ast.Module) -> Set[str]:
    """Names that refer to the jax package (``jax``, ``jnp``, ...)."""
    out: Set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.name == "jax" or a.name.startswith("jax."):
                    out.add((a.asname or a.name).split(".")[0])
        elif isinstance(n, ast.ImportFrom) and n.module and \
                (n.module == "jax" or n.module.startswith("jax.")):
            for a in n.names:
                out.add(a.asname or a.name)
    return out


def lint_file(path: str, source: Optional[str] = None,
              tree: Optional[ast.Module] = None) -> List[Finding]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    if tree is None:                 # run_lints hands in a shared parse
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            return [Finding(rule="THR000", path=relpath(path),
                            line=e.lineno or 1, symbol="<module>",
                            message=f"syntax error: {e.msg}",
                            key="syntax")]
    rel = relpath(path)
    aliases = _jax_aliases(tree)
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            try:
                findings.extend(_lint_class(_ClassInfo(node, rel, aliases),
                                            rel))
            except RecursionError:
                pass
    return findings
