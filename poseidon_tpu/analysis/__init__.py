"""Static guardrails: concurrency lint, jit-hygiene lint, HLO contract gates.

Seven PRs in, the hot path was defended by *dynamic* checks only: races in
the threaded modules (engine dispatch window, prefetcher, async snapshot
writer, spans/metrics, serving batcher/reloader, async-SSP client/service)
were found by chaos tests when they were found at all, and the HLO
invariants the perf PRs fought for (bucketed psum counts, NHWC transpose
counts, donated batch buffers) lived as ad-hoc assertions that silently
regress in modules the tests don't compile. This package makes those
properties *statically checkable*, in the spirit of the TF-paper argument
(arXiv:1605.08695) that an analyzable program representation lets a system
prove placement/comm properties rather than sample them:

- ``threads.py``  — AST concurrency lint: thread entrypoint discovery,
  per-class lock discipline, unsynchronized shared mutation, lock-order
  cycles, check-then-act, jax-from-thread (rules THR001-THR006).
- ``jit_hygiene.py`` — host syncs inside traced functions and the engine's
  dispatch window, retrace hazards, f64 promotion, named_scope coverage
  (rules JIT101-JIT106).
- ``contracts.py`` — per-model golden HLO contracts
  (``evidence/hlo_contracts/*.json``): gradient all-reduce count, layout
  transposes, donation census, dtype census, fusion count — verified by
  compiling each model on CPU and diffing — plus the cross-participant
  collective-schedule consistency gate (``collective_consistency``).
- ``protocol.py`` — wire-schema lint (PROTO201-PROTO207): the dict-
  ``kind`` RPC vocabulary of the async-SSP and serving socket tiers,
  AST-extracted from dispatchers AND senders, cross-checked, and emitted
  as the checked-in schema golden ``evidence/protocol_schema.json``.
- ``model_check.py`` — exhaustive bounded model checking of the
  SSP/managed-communication protocol (durable-clock gates, partial
  pushes, admit/retire, exactly-once replay), with seeded-mutation
  self-tests and real-run trace conformance.

Findings carry ``file:line`` + rule id and a line-number-free fingerprint;
``baseline.json`` grandfathers pre-existing findings so CI fails only on
NEW violations. An intentional finding is suppressed in place with a
``# static-ok: RULE`` comment on the offending line.

Everything here is jax-free at import (the lints are pure ``ast`` walks;
contracts import jax lazily), so ``python -m poseidon_tpu.analysis`` is
cheap enough for a pre-commit hook.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = [
    "Finding", "load_baseline", "save_baseline", "filter_new",
    "run_lints", "default_targets", "iter_python_files", "REPO_ROOT",
]

# the repo root this package is checked into (…/poseidon_tpu/analysis ->
# two levels up); every finding path is reported relative to it so
# fingerprints are machine-independent
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")


@dataclass(frozen=True)
class Finding:
    """One lint finding. ``key`` disambiguates findings within a symbol
    (the attribute, lock pair, or callee involved); the fingerprint
    deliberately excludes the line number and message so baselines survive
    unrelated edits to the same file."""

    rule: str          # e.g. THR004
    path: str          # repo-relative, forward slashes
    line: int
    symbol: str        # Class.method / function qualname / "<module>"
    message: str
    key: str = ""      # attr name / lock-cycle / callee — fingerprint salt

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.symbol}:{self.key}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}: {self.rule} [{self.symbol}] "
                f"{self.message}")


def relpath(path: str) -> str:
    """Repo-relative forward-slash path (the fingerprint convention)."""
    ap = os.path.abspath(path)
    if ap.startswith(REPO_ROOT + os.sep):
        ap = ap[len(REPO_ROOT) + 1:]
    return ap.replace(os.sep, "/")


# --------------------------------------------------------------------------- #
# pragma suppression
# --------------------------------------------------------------------------- #

def pragma_on_line(source_lines: Sequence[str], ln: int,
                   rule: str) -> bool:
    """One line's ``# static-ok:`` grammar — the single home for it (the
    def-level pragma in jit_hygiene reuses this per-line check)."""
    if not 1 <= ln <= len(source_lines):
        return False
    text = source_lines[ln - 1]
    if "# static-ok:" not in text:
        return False
    rules = text.split("# static-ok:", 1)[1].split("#")[0]
    allowed = {r.strip() for r in rules.split(",")}
    return "*" in allowed or rule in allowed


def pragma_suppressed(source_lines: Sequence[str], finding: Finding,
                      tree: Optional[ast.Module] = None) -> bool:
    """``# static-ok: THR004`` (or ``# static-ok: *``) on the finding line
    — or the line above it — suppresses the finding in place; on (or just
    above) an enclosing ``def`` line it suppresses the rule for the whole
    function. For load-bearing intentional sites (the documented sync
    point in ``scalar_rows``) this beats a baseline entry: the
    justification lives next to the code it excuses and dies with it."""
    if any(pragma_on_line(source_lines, ln, finding.rule)
           for ln in (finding.line, finding.line - 1)):
        return True
    if tree is not None:
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and n.lineno <= finding.line <= (n.end_lineno
                                                     or n.lineno):
                if any(pragma_on_line(source_lines, ln, finding.rule)
                       for ln in (n.lineno, n.lineno - 1)):
                    return True
    return False


# --------------------------------------------------------------------------- #
# baseline
# --------------------------------------------------------------------------- #

def load_baseline(path: Optional[str] = None) -> Dict[str, str]:
    """{fingerprint: reason}. A missing file is an empty baseline."""
    path = path or BASELINE_PATH
    if not os.path.exists(path):
        return {}
    with open(path) as f:
        doc = json.load(f)
    return {e["fingerprint"]: e.get("reason", "")
            for e in doc.get("findings", [])}


def save_baseline(findings: Iterable[Finding],
                  reasons: Optional[Dict[str, str]] = None,
                  path: Optional[str] = None) -> str:
    """Write the grandfather list (sorted, one entry per fingerprint).
    ``reasons`` carries over justifications for fingerprints that stay."""
    path = path or BASELINE_PATH
    reasons = reasons or {}
    entries = {}
    for f in findings:
        entries.setdefault(f.fingerprint, {
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "where": f"{f.path}:{f.line}",
            "reason": reasons.get(f.fingerprint, ""),
        })
    doc = {"comment": "Grandfathered static-analysis findings: CI fails "
                      "only on NEW fingerprints. Shrink this list; never "
                      "grow it without review.",
           "findings": sorted(entries.values(),
                              key=lambda e: e["fingerprint"])}
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")
    return path


def filter_new(findings: Sequence[Finding],
               baseline: Dict[str, str]) -> List[Finding]:
    return [f for f in findings if f.fingerprint not in baseline]


# --------------------------------------------------------------------------- #
# target discovery + driver
# --------------------------------------------------------------------------- #

# Scripts outside the package that import the threaded runtime ride the
# same lint (ISSUE 8 satellite): a host-sync or race added there rots the
# telemetry story just as surely as one inside the package.
EXTRA_SCRIPT_TARGETS = (
    "scripts/layer_time_from_trace.py",
    "scripts/telemetry_smoke.py",
)


def default_targets() -> List[str]:
    pkg = os.path.dirname(os.path.abspath(__file__))          # .../analysis
    targets = [os.path.dirname(pkg)]                          # the package
    targets.extend(os.path.join(REPO_ROOT, rel)
                   for rel in EXTRA_SCRIPT_TARGETS)
    return targets


def iter_python_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs
                           if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    return out


def run_lints(paths: Optional[Sequence[str]] = None,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run both AST lints over ``paths`` (files or directories; default =
    the package + the instrumented scripts). Pragma-suppressed findings
    are dropped here; baseline filtering is the caller's move."""
    from . import jit_hygiene, threads
    targets = list(paths) if paths is not None else default_targets()
    files = iter_python_files(targets)
    findings: List[Finding] = []
    # a configured .py target that vanished must SURFACE (the
    # WINDOW_METHODS pattern): a renamed script silently dropping out of
    # coverage is the stale-config blindness this package exists to stop
    for t in targets:
        if t.endswith(".py") and not os.path.exists(t):
            findings.append(Finding(
                rule="CFG001", path=relpath(t), line=1, symbol="<config>",
                key="missing-target",
                message="configured lint target no longer exists — "
                        "update EXTRA_SCRIPT_TARGETS (or the caller's "
                        "path list) or the file rides unlinted"))
    for path in files:
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError):
            continue
        lines = source.splitlines()
        per_file: List[Finding] = []
        try:
            tree = ast.parse(source)   # ONE parse feeds both linters
        except SyntaxError as e:
            per_file.append(Finding(
                rule="THR000", path=relpath(path), line=e.lineno or 1,
                symbol="<module>", message=f"syntax error: {e.msg}",
                key="syntax"))
            tree = None
        if tree is not None:
            per_file.extend(threads.lint_file(path, source, tree=tree))
            per_file.extend(jit_hygiene.lint_file(path, source, tree=tree))
        findings.extend(f for f in per_file
                        if not pragma_suppressed(lines, f, tree=tree))
    if paths is None:
        # the wire-schema lint is CROSS-file (dispatchers in one module,
        # senders in another), so it runs against its own configured
        # service specs rather than per file — but only on the default
        # sweep: restricting the lint to explicit paths must not drag in
        # findings about files the caller did not ask about. Its
        # findings share the fingerprint/baseline/pragma machinery.
        from . import protocol
        findings.extend(protocol.run_protocol_lint())
    if rules:
        # infrastructure findings (vanished target, unparseable file)
        # survive any --rules restriction — a rule-filtered hook must
        # not re-open the silent-coverage-loss hole CFG001 exists for
        keep = set(rules) | {"CFG001", "THR000"}
        findings = [f for f in findings if f.rule in keep]
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
