"""CLI for the static guardrails.

    python -m poseidon_tpu.analysis                 # lints, baseline-aware
    python -m poseidon_tpu.analysis path/to/file.py # lint specific targets
    python -m poseidon_tpu.analysis --contracts all # HLO contract gates
    python -m poseidon_tpu.analysis --refresh-contracts lenet,alexnet
    python -m poseidon_tpu.analysis --write-baseline

Exit codes: 0 clean; 1 NEW lint findings (not in baseline); 2 HLO
contract violation; 3 usage error (e.g. an unknown model name); 4 the
contract check itself failed to run (infra/compile error — the findings
report is still written). The default invocation is jax-free and fast (pure
AST), so it is safe as a pre-commit hook; ``--contracts`` traces and
(for LeNet) compiles real models — seconds to a minute on CPU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (BASELINE_PATH, filter_new, load_baseline, run_lints,
               save_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.analysis",
        description="concurrency + jit-hygiene lints and HLO contract "
                    "gates")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package + the "
                         "instrumented scripts)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="grandfather list (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--fail-on-new", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="exit 1 on findings not in the baseline (default; "
                         "kept explicit for CI readability — "
                         "--no-fail-on-new for a report-only survey)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to grandfather the current "
                         "findings (carries over existing reasons)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to restrict to")
    ap.add_argument("--report", default=None,
                    help="write a JSON findings report here (CI artifact)")
    ap.add_argument("--contracts", default=None, metavar="MODELS",
                    help="verify HLO contracts: 'all' or a comma list of "
                         "lenet,alexnet,googlenet (imports jax)")
    ap.add_argument("--refresh-contracts", default=None, metavar="MODELS",
                    help="recompute + rewrite contract goldens, printing "
                         "the diff for review")
    # ALL usage errors exit 3 — argparse's default of 2 collides with
    # the documented contract-violation code
    ap.error = lambda msg: ap.exit(3, f"{ap.prog}: error: {msg}\n")
    args = ap.parse_args(argv)

    # a typo'd target must not pass as "0 findings": a guardrail that
    # silently lints nothing is worse than none
    for p in args.paths:
        if not os.path.exists(p):
            ap.error(f"lint target does not exist: {p!r}")

    rules = args.rules.split(",") if args.rules else None
    findings = run_lints(args.paths or None, rules=rules)
    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new = filter_new(findings, baseline)

    if args.write_baseline:
        # a restricted run sees only a subset of findings; rewriting the
        # whole grandfather list from it would delete every other curated
        # entry (and its written reason)
        if args.paths or args.rules:
            ap.exit(3, f"{ap.prog}: error: --write-baseline rewrites the "
                       f"WHOLE grandfather list; run it without path "
                       f"arguments or --rules\n")
        # carry reasons from the on-disk baseline even under
        # --no-baseline (that flag only widens REPORTING; rewriting the
        # grandfather list must never drop the curated justifications)
        path = save_baseline(findings, reasons=load_baseline(args.baseline),
                             path=args.baseline)
        print(f"baseline rewritten: {path} ({len(findings)} findings "
              f"grandfathered)")
        return 0

    for f in new:
        print(f.render())
    n_base = len(findings) - len(new)
    print(f"{len(new)} new finding(s), {n_base} baselined "
          f"({len(findings)} total)")

    report = {"findings": [vars(f) | {"fingerprint": f.fingerprint,
                                      "baselined": f.fingerprint in baseline}
                           for f in findings],
              "new": len(new), "baselined": n_base}
    rc = 1 if (new and args.fail_on_new) else 0

    from . import contracts as C

    def parse_models(spec: str):
        # validate BEFORE any golden is touched: a typo'd model in a
        # --refresh-contracts list must not leave the contract dir
        # half-rewritten
        models = (C.MODELS if spec == "all"
                  else tuple(m.strip() for m in spec.split(",") if m.strip()))
        bad = [m for m in models if m not in C.MODELS]
        if bad:
            # NOT ap.error: argparse exits 2, which the CLI contract
            # reserves for a real contract violation
            ap.exit(3, f"{ap.prog}: error: unknown model(s) {bad}; "
                       f"choose from {list(C.MODELS)} or 'all'\n")
        if not models:
            # a gate over zero models is vacuously "ok" — an unset CI
            # variable must not read as a passed contract check
            ap.exit(3, f"{ap.prog}: error: empty model list; choose from "
                       f"{list(C.MODELS)} or 'all'\n")
        return models

    try:
        if args.refresh_contracts is not None:
            C.refresh(parse_models(args.refresh_contracts))
        elif args.contracts is not None:
            ok, con_report = C.check_all(parse_models(args.contracts))
            report["contracts"] = con_report
            for m, r in con_report.items():
                status = "ok" if r["ok"] else "VIOLATED"
                print(f"contract {m}: {status}")
                for d in r["diffs"]:
                    print(f"  {d}")
            if not ok:
                rc = 2
    except Exception as e:   # infra failure (OOM, jax init), NOT a lint
        # regression (1) or a measured violation (2)
        print(f"contract check failed to run: {type(e).__name__}: {e}",
              file=sys.stderr)
        report["contracts_error"] = f"{type(e).__name__}: {e}"
        rc = 4
    finally:
        # the lint half already completed — CI keeps its artifact even
        # when the contract half dies (or a usage error exits early)
        if args.report:
            with open(args.report, "w") as f:
                json.dump(report, f, indent=2)
            print(f"report written: {args.report}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
