"""CLI for the static guardrails.

    python -m poseidon_tpu.analysis                 # lints, baseline-aware
    python -m poseidon_tpu.analysis path/to/file.py # lint specific targets
    python -m poseidon_tpu.analysis --contracts all # HLO contract gates
    python -m poseidon_tpu.analysis --refresh-contracts lenet,alexnet
    python -m poseidon_tpu.analysis --protocols     # wire-schema lint + gate
    python -m poseidon_tpu.analysis --refresh-schema
    python -m poseidon_tpu.analysis --model-check smoke
    python -m poseidon_tpu.analysis --collectives lenet
    python -m poseidon_tpu.analysis --write-baseline

Exit codes: 0 clean; 1 NEW lint findings (not in baseline); 2 a contract
violation — an HLO contract diff, a protocol-schema regression vs
``evidence/protocol_schema.json``, a model-checker invariant violation
(or a seeded mutation the checker stopped catching), or a
cross-participant collective-schedule divergence; 3 usage error (e.g. an
unknown model name); 4 the gate itself failed to run (infra/compile
error — the findings report is still written). The default invocation
and ``--protocols``/``--model-check`` are jax-free and fast (pure AST /
pure Python), so they are safe as pre-commit hooks; ``--contracts`` and
``--collectives`` trace real models — seconds to a minute on CPU.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from . import (BASELINE_PATH, filter_new, load_baseline, run_lints,
               save_baseline)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m poseidon_tpu.analysis",
        description="concurrency + jit-hygiene lints and HLO contract "
                    "gates")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: the package + the "
                         "instrumented scripts)")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="grandfather list (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, grandfathered or not")
    ap.add_argument("--fail-on-new", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="exit 1 on findings not in the baseline (default; "
                         "kept explicit for CI readability — "
                         "--no-fail-on-new for a report-only survey)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline to grandfather the current "
                         "findings (carries over existing reasons)")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to restrict to")
    ap.add_argument("--report", default=None,
                    help="write a JSON findings report here (CI artifact)")
    ap.add_argument("--contracts", default=None, metavar="MODELS",
                    help="verify HLO contracts: 'all' or a comma list of "
                         "lenet,alexnet,googlenet (imports jax)")
    ap.add_argument("--refresh-contracts", default=None, metavar="MODELS",
                    help="recompute + rewrite contract goldens, printing "
                         "the diff for review")
    ap.add_argument("--protocols", action="store_true",
                    help="wire-schema lint (PROTO2xx, baseline-aware) + "
                         "diff the extracted protocol schema against the "
                         "checked-in golden (exit 2 on schema drift)")
    ap.add_argument("--schema", default=None,
                    help="protocol-schema golden path (default: "
                         "evidence/protocol_schema.json)")
    ap.add_argument("--refresh-schema", action="store_true",
                    help="re-extract + rewrite the protocol schema "
                         "golden, printing old->new for review")
    ap.add_argument("--model-check", default=None, metavar="LEVEL",
                    choices=("tiny", "smoke", "full"),
                    help="exhaustively model-check the SSP/managed-comm "
                         "protocol (tiny|smoke|full); exit 2 on an "
                         "invariant violation or an uncaught seeded "
                         "mutation")
    ap.add_argument("--collectives", default=None, metavar="MODELS",
                    help="cross-participant collective-schedule gate: "
                         "lower the sharded step twice independently and "
                         "require identical collective sequences "
                         "(imports jax); 'all' or a comma list")
    # ALL usage errors exit 3 — argparse's default of 2 collides with
    # the documented contract-violation code
    ap.error = lambda msg: ap.exit(3, f"{ap.prog}: error: {msg}\n")
    args = ap.parse_args(argv)

    # a typo'd target must not pass as "0 findings": a guardrail that
    # silently lints nothing is worse than none
    for p in args.paths:
        if not os.path.exists(p):
            ap.error(f"lint target does not exist: {p!r}")

    rules = args.rules.split(",") if args.rules else None
    findings = run_lints(args.paths or None, rules=rules)
    if args.paths and (args.protocols or args.refresh_schema):
        # run_lints skips the cross-file protocol lint when restricted
        # to explicit paths — but an invocation that ASKED for the
        # protocol gate must not read as a passed check that never ran
        # (the extraction memo makes this free for the default case)
        from . import protocol as PR0
        extra = PR0.run_protocol_lint()
        if rules:
            extra = [f for f in extra
                     if f.rule in set(rules) | {"CFG001", "THR000"}]
        findings = sorted(findings + extra,
                          key=lambda f: (f.path, f.line, f.rule))
    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new = filter_new(findings, baseline)

    if args.write_baseline:
        # a restricted run sees only a subset of findings; rewriting the
        # whole grandfather list from it would delete every other curated
        # entry (and its written reason)
        if args.paths or args.rules:
            ap.exit(3, f"{ap.prog}: error: --write-baseline rewrites the "
                       f"WHOLE grandfather list; run it without path "
                       f"arguments or --rules\n")
        # carry reasons from the on-disk baseline even under
        # --no-baseline (that flag only widens REPORTING; rewriting the
        # grandfather list must never drop the curated justifications)
        path = save_baseline(findings, reasons=load_baseline(args.baseline),
                             path=args.baseline)
        print(f"baseline rewritten: {path} ({len(findings)} findings "
              f"grandfathered)")
        return 0

    for f in new:
        print(f.render())
    n_base = len(findings) - len(new)
    print(f"{len(new)} new finding(s), {n_base} baselined "
          f"({len(findings)} total)")

    report = {"findings": [vars(f) | {"fingerprint": f.fingerprint,
                                      "baselined": f.fingerprint in baseline}
                           for f in findings],
              "new": len(new), "baselined": n_base}
    rc = 1 if (new and args.fail_on_new) else 0

    from . import contracts as C

    def parse_models(spec: str):
        # validate BEFORE any golden is touched: a typo'd model in a
        # --refresh-contracts list must not leave the contract dir
        # half-rewritten
        models = (C.MODELS if spec == "all"
                  else tuple(m.strip() for m in spec.split(",") if m.strip()))
        bad = [m for m in models if m not in C.MODELS]
        if bad:
            # NOT ap.error: argparse exits 2, which the CLI contract
            # reserves for a real contract violation
            ap.exit(3, f"{ap.prog}: error: unknown model(s) {bad}; "
                       f"choose from {list(C.MODELS)} or 'all'\n")
        if not models:
            # a gate over zero models is vacuously "ok" — an unset CI
            # variable must not read as a passed contract check
            ap.exit(3, f"{ap.prog}: error: empty model list; choose from "
                       f"{list(C.MODELS)} or 'all'\n")
        return models

    try:
        if args.refresh_contracts is not None:
            C.refresh(parse_models(args.refresh_contracts))
        elif args.contracts is not None:
            ok, con_report = C.check_all(parse_models(args.contracts))
            report["contracts"] = con_report
            for m, r in con_report.items():
                status = "ok" if r["ok"] else "VIOLATED"
                print(f"contract {m}: {status}")
                for d in r["diffs"]:
                    print(f"  {d}")
            if not ok:
                rc = 2

        if args.refresh_schema:
            from . import protocol as PR
            schema, _ = PR.extract_schema()
            old = PR.load_schema(args.schema)
            if old is not None:
                for d in PR.diff_schema(old, schema):
                    print(f"  schema: {d}")
            path = PR.save_schema(schema, args.schema)
            print(f"protocol schema refreshed: {path}")
        elif args.protocols:
            # the PROTO findings themselves already rode the default lint
            # run above (baseline-aware, exit 1); this gate adds the
            # SCHEMA diff — vocabulary drift vs the checked-in golden is
            # a contract regression (exit 2), reviewed via
            # --refresh-schema exactly like --refresh-contracts
            from . import protocol as PR
            schema, _ = PR.extract_schema()
            golden = PR.load_schema(args.schema)
            if golden is None:
                print("protocol schema: no checked-in golden (run "
                      "--refresh-schema and commit it)")
                report["protocol_schema"] = {"ok": False,
                                             "diffs": ["missing golden"]}
                rc = 2
            else:
                sdiffs = PR.diff_schema(golden, schema)
                report["protocol_schema"] = {"ok": not sdiffs,
                                             "diffs": sdiffs}
                for d in sdiffs:
                    print(f"  schema drift: {d}")
                if sdiffs:
                    print("protocol schema: VIOLATED (extraction no "
                          "longer matches the golden; --refresh-schema "
                          "if the change is intended)")
                    rc = 2
                else:
                    print("protocol schema: ok")

        if args.model_check is not None:
            from . import model_check as MC
            results, caught = MC.run_level(args.model_check)
            report["model_check"] = {
                "level": args.model_check,
                "configs": [{
                    "name": r.config.name, "states": r.states,
                    "transitions": r.transitions, "ok": r.ok,
                    "violations": [{"invariant": v.invariant,
                                    "detail": v.detail,
                                    "trace": list(v.trace)}
                                   for v in r.violations],
                } for r in results],
                "mutations_caught": caught,
            }
            for r in results:
                print(r.render())
                for v in r.violations:
                    print(f"  trace: {' -> '.join(v.trace)}")
            for m, c in caught.items():
                print(f"mutation self-test {m}: "
                      f"{'caught' if c else 'NOT CAUGHT'}")
            if any(not r.ok for r in results) or \
                    not all(caught.values()):
                # a protocol invariant violated, or the checker stopped
                # catching a seeded bug — both are exit-2 regressions
                rc = 2

        if args.collectives is not None:
            ok, crep = C.collective_consistency(
                parse_models(args.collectives))
            report["collectives"] = crep
            for m, r in crep.items():
                status = ("skipped" if r.get("skipped")
                          else "ok" if r["ok"] else "DIVERGED")
                print(f"collective schedule {m}: {status} "
                      f"({r.get('sequence_len', 0)} collectives x "
                      f"{r.get('participants', 0)} participants)")
                for d in r["diffs"]:
                    print(f"  {d}")
            if not ok:
                rc = 2
    except Exception as e:   # infra failure (OOM, jax init), NOT a lint
        # regression (1) or a measured violation (2)
        print(f"contract check failed to run: {type(e).__name__}: {e}",
              file=sys.stderr)
        report["contracts_error"] = f"{type(e).__name__}: {e}"
        rc = 4
    finally:
        # the lint half already completed — CI keeps its artifact even
        # when the contract half dies (or a usage error exits early)
        if args.report:
            with open(args.report, "w") as f:
                json.dump(report, f, indent=2)
            print(f"report written: {args.report}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
