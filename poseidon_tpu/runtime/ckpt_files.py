"""Snapshot-file discovery and hygiene — the filesystem half of
``runtime.checkpoint``, split out so socket-tier processes (async-SSP
workers deciding whether to auto-resume) can use it without paying
checkpoint's jax import."""

from __future__ import annotations

import os
import re
from typing import List, Optional


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True      # exists, owned by someone else
    except OSError:
        return False
    return True


def sweep_stale_tmp(prefix: str, min_age_s: float = 60.0) -> List[str]:
    """Remove orphaned snapshot temp files under ``prefix``.

    ``snapshot()`` writes ``<artifact>.tmp.<pid>`` then ``os.replace``s it
    into place; a process killed between the two leaves a tmp that can
    never be renamed — litter at best, a truncated half-write at worst.
    A tmp file is swept when its writer pid is gone (or is THIS process,
    which is not mid-snapshot while sweeping at startup/restore) AND it is
    at least ``min_age_s`` old. The age guard is what makes the sweep safe
    on a SHARED filesystem: the pid test is host-local, so a live writer
    on another host can look dead here — but its tmp is by construction
    only seconds old (the write->replace window), never past the guard.
    Completed snapshots are never touched (the iter-file naming shares no
    suffix with tmps), and latest_snapshot/restore never select a tmp, so
    un-swept litter is cosmetic, not a correctness hazard. Returns the
    removed paths."""
    import time
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    removed: List[str] = []
    if not os.path.isdir(d):
        return removed
    now = time.time()
    for name in os.listdir(d):
        if not name.startswith(base + "_iter_"):
            continue
        m = re.search(r"\.tmp\.(\d+)$", name)
        if not m:
            continue
        pid = int(m.group(1))
        if pid != os.getpid() and _pid_alive(pid):
            continue
        path = os.path.join(d, name)
        try:
            if now - os.path.getmtime(path) < min_age_s:
                continue
            os.remove(path)
            removed.append(path)
        except OSError:
            pass
    return removed


def latest_snapshot(prefix: str,
                    suffix: str = ".solverstate.npz") -> Optional[str]:
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    best, best_it = None, -1
    if not os.path.isdir(d):
        return None
    for name in os.listdir(d):
        if name.startswith(base + "_iter_") and name.endswith(suffix):
            try:
                it = int(name[len(base + "_iter_"):-len(suffix)])
            except ValueError:
                continue
            if it > best_it:
                best, best_it = os.path.join(d, name), it
    return best
