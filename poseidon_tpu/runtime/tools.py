"""Dataset tools: convert_imageset / compute_image_mean / partition_data,
plus the feature extractor.

Parity targets: ``tools/convert_imageset.cpp``, ``tools/compute_image_mean.cpp``,
``tools/partition_data.cpp`` (LevelDB shard splitter for k clients) and
``src/caffe/feature_extractor.cpp`` (load weights, forward, dump per-blob
features). Databases are LMDB (our reader/writer); the reference's default
LevelDB backend is covered by converting to LMDB.
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

from ..proto.wire import Datum, decode_datum, encode_blob, encode_datum
from .metrics import log


def convert_imageset(listfile: str, out_db: str, root_folder: str = "",
                     resize_height: int = 0, resize_width: int = 0,
                     shuffle: bool = False, gray: bool = False,
                     seed: int = 0) -> int:
    """Image list ('path label' lines) -> LMDB of Datum records."""
    from PIL import Image
    from ..data.lmdb_reader import LMDBWriter

    entries = []
    with open(listfile) as f:
        for line in f:
            line = line.strip()
            if line:
                path, label = line.rsplit(None, 1)
                entries.append((path, int(label)))
    if shuffle:
        np.random.RandomState(seed).shuffle(entries)

    writer = LMDBWriter(out_db)
    for i, (path, label) in enumerate(entries):
        img = Image.open(os.path.join(root_folder, path))
        img = img.convert("L" if gray else "RGB")
        if resize_height and resize_width:
            img = img.resize((resize_width, resize_height))
        arr = np.asarray(img, np.uint8)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        else:
            arr = arr[:, :, ::-1]  # RGB -> BGR, Caffe's convention
        chw = np.ascontiguousarray(arr.transpose(2, 0, 1))
        datum = Datum(channels=chw.shape[0], height=chw.shape[1],
                      width=chw.shape[2], data=chw.tobytes(), label=label)
        writer.put(f"{i:08d}_{os.path.basename(path)}".encode(),
                   encode_datum(datum))
    writer.close()
    log(f"convert_imageset: wrote {len(entries)} records to {out_db}")
    return len(entries)


def compute_image_mean(db_path: str, out_file: str) -> np.ndarray:
    """LMDB of Datums -> mean BlobProto (.binaryproto)."""
    from ..data.lmdb_reader import LMDBReader
    db = LMDBReader(db_path)
    total: Optional[np.ndarray] = None
    count = 0
    for _, value in db:
        arr = decode_datum(value).to_array()
        total = arr if total is None else total + arr
        count += 1
    if count == 0:
        raise ValueError(f"{db_path}: empty database")
    mean = (total / count).astype(np.float32)
    with open(out_file, "wb") as f:
        f.write(encode_blob(mean[None]))  # (1, C, H, W)
    log(f"compute_image_mean: {count} records -> {out_file}")
    return mean


def partition_data(db_path: str, num_shards: int) -> List[str]:
    """Split a database into contiguous shards '<db>_0' ... '<db>_{k-1}'
    (the shared_file_system convention, tools/partition_data.cpp)."""
    from ..data.lmdb_reader import LMDBReader, LMDBWriter
    db = LMDBReader(db_path)
    n = len(db)
    base = n // num_shards
    rem = n % num_shards
    out_paths = []
    idx = 0
    for s in range(num_shards):
        take = base + (1 if s < rem else 0)
        out = f"{db_path.rstrip('/')}_{s}"
        w = LMDBWriter(out)
        for _ in range(take):
            w.put(db.key_at(idx), db.value_at(idx))
            idx += 1
        w.close()
        out_paths.append(out)
    log(f"partition_data: {n} records -> {num_shards} shards")
    return out_paths


def convert_db(src_path: str, out_path: str, out_backend: str = "LMDB") -> int:
    """Copy a database between backends (LevelDB <-> LMDB). LMDB output gets
    the native C++ ingest fast path."""
    from ..data.lmdb_reader import LMDBReader, LMDBWriter
    from ..data.leveldb_reader import LevelDBReader, LevelDBWriter

    reader = None
    try:
        reader = LMDBReader(src_path)
    except Exception:
        reader = LevelDBReader(src_path)
    writer = LMDBWriter(out_path) if out_backend.upper() == "LMDB"         else LevelDBWriter(out_path)
    n = 0
    for key, value in reader:
        writer.put(key, value)
        n += 1
    writer.close()
    log(f"convert_db: {n} records -> {out_path} ({out_backend})")
    return n


def extract_features(net, params, blob_names: List[str], pipeline,
                     num_batches: int, out_prefix: str,
                     sharding=None) -> List[str]:
    """Forward `num_batches` batches, dump named blobs to one LMDB per blob
    (feature_extractor.cpp:16-139; features keyed by running sample index).

    ``sharding`` is the batch sharding to place inputs with — the same
    placement rule the train path uses (``data.pipeline.place_batch``,
    multi-process aware), so tools-path batches land sharded across the
    mesh instead of defaulting onto device 0. Batches whose leading dim
    the sharding cannot split evenly fall back to the pre-sharding
    unsharded put."""
    import jax
    from ..data.lmdb_reader import LMDBWriter
    from ..data.pipeline import place_batch

    writers = {b: LMDBWriter(f"{out_prefix}_{b.replace('/', '_')}")
               for b in blob_names}
    fwd = jax.jit(lambda p, batch: net.apply(p, batch, train=False,
                                             keep_blobs=True).blobs)

    def _place(v):
        # multi-process extraction keeps LOCAL placement: each rank
        # forwards its own record shard and writes its own LMDBs
        # (feature_extractor.cpp's per-client naming) — assembling a
        # global array here would hand every rank non-addressable rows
        # and break the per-client output contract
        if jax.process_count() > 1:
            return jax.device_put(v)
        try:
            return place_batch(v, sharding)
        except ValueError:  # batch not divisible by the data axis
            return jax.device_put(v)

    sample = 0
    for _ in range(num_batches):
        host = next(pipeline)
        batch = {k: _place(v) for k, v in host.items()}
        blobs = fwd(params, batch)
        n = next(iter(host.values())).shape[0]
        for b in blob_names:
            feats = np.asarray(blobs[b], np.float32).reshape(n, -1)
            for i in range(n):
                datum = Datum(channels=feats.shape[1], height=1, width=1,
                              float_data=feats[i])
                writers[b].put(f"{sample + i:010d}".encode(),
                               encode_datum(datum))
        sample += n
    for b, w in writers.items():
        w.close()
    log(f"extract_features: {sample} samples x {len(blob_names)} blobs")
    return [f"{out_prefix}_{b.replace('/', '_')}" for b in blob_names]
