"""Snapshots: solver-state checkpoints + .caffemodel weight exchange.

The reference writes two artifacts (solver.cpp:632-696): the model as a
binary NetParameter (``.caffemodel``, written by rank 0) and per-worker
``.solverstate`` files with the iteration and momentum history. Here:

- ``snapshot()`` writes ``<prefix>_iter_<N>.caffemodel`` (wire-compatible with
  Caffe) and ``<prefix>_iter_<N>.solverstate.npz`` (params + history + iter +
  comm residuals), sharding-agnostic since params are replicated.
- ``restore()`` rebuilds (params, TrainState) from the .npz;
  ``load_caffemodel()`` imports weights alone (CopyTrainedLayersFrom).

**Snapshots are canonical per-leaf** — the flat parameter arena
(core/arena.py) is an in-step representation only: the compiled train step
packs params/grads/history into the flat buffers at entry and unpacks at
exit, so every (params, state) this module sees is the per-leaf tree
regardless of ``--param_arena``. Pre-arena snapshots therefore load into
arena-backed runs unchanged, an arena run's snapshot reloads under
``--param_arena=false`` bit-identically, and nothing here depends on the
arena's offset table or bucket size (tested:
test_runtime.test_arena_snapshot_portability).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.net import Net
from ..parallel.trainer import (SSPState, TrainState, init_comm_error,
                                init_ssp_state, reconcile_comm_error)
from ..proto.wire import decode_caffemodel, encode_caffemodel
from ..solvers.updates import SolverState
from .ckpt_files import latest_snapshot, sweep_stale_tmp  # noqa: F401


# Layer names may contain '/' (GoogLeNet's "inception_3a/1x1"), so tree keys
# are joined with the ASCII unit separator, which cannot appear in prototxt
# identifiers.
_SEP = "\x1f"


def _flatten(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + _SEP))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]):
    tree: Dict = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(v)
    return tree


def _gather(tree):
    """Per-device SSP leaves (and TOPK residuals) are sharded over the data
    axis; under multi-process they span non-addressable devices, so gather
    them to every host first — each rank then writes identical bytes."""
    if jax.process_count() == 1 or not jax.tree_util.tree_leaves(tree):
        return tree
    from jax.experimental import multihost_utils
    return jax.tree_util.tree_map(
        lambda x: multihost_utils.process_allgather(x, tiled=True)
        if isinstance(x, jax.Array) and not x.is_fully_addressable else x,
        tree)


def snapshot_paths(prefix: str, state) -> Tuple[str, str]:
    """(model_path, state_path) the snapshot protocol will produce for this
    state — shared by the sync writer and the async writer's caller-visible
    return value."""
    is_ssp = isinstance(state, SSPState)
    it = int(state.it if is_ssp else state.solver.it)
    return (f"{prefix}_iter_{it}.caffemodel",
            f"{prefix}_iter_{it}.solverstate.npz")


def host_state_copy(params, state):
    """Blocking host copy of (params, state) — THE sync point the async
    snapshot writer serializes from: sharded leaves are gathered first
    (np.asarray on a non-addressable array would fail), every leaf lands
    as numpy, and the state's TrainState/SSPState type is preserved (the
    pytree re-registers the NamedTuple)."""
    to_np = lambda tree: jax.tree_util.tree_map(np.asarray, tree)  # noqa: E731
    return to_np(params), to_np(_gather(state))


def snapshot(prefix: str, net: Net, params, state) -> Tuple[str, str]:
    """Write both artifacts atomically (tmp + rename): with replicated state
    every rank writes identical bytes, so even concurrent snapshots to a
    shared filesystem are safe — the last rename wins with valid content.

    ``state`` is either a TrainState (sync/dense training) or an SSPState
    (staleness > 0); the .solverstate records which, so restore() rebuilds the
    right carry — the analog of the reference's per-thread .solverstate files
    carrying divergent worker histories (solver.cpp:654-667)."""
    is_ssp = isinstance(state, SSPState)
    it = int(state.it if is_ssp else state.solver.it)
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    model_path, state_path = snapshot_paths(prefix, state)
    pid = os.getpid()

    # .caffemodel always holds the globally-agreed view: anchor under SSP.
    model_params = state.anchor_params if is_ssp else params
    tmp = f"{model_path}.tmp.{pid}"
    with open(tmp, "wb") as f:
        f.write(encode_caffemodel(net.name or "net",
                                  net.export_weights(model_params)))
    os.replace(tmp, model_path)

    gather = _gather
    arrays = {"iter": np.asarray(it)}
    if is_ssp:
        arrays["kind"] = np.asarray("ssp")
        arrays.update({f"params/{k}": v
                       for k, v in _flatten(state.anchor_params).items()})
        arrays.update({f"local_params/{k}": v
                       for k, v in _flatten(gather(state.local_params)).items()})
        arrays.update({f"local_history/{k}": v
                       for k, v in
                       _flatten(gather(state.local_history)).items()})
        arrays.update({f"adarev_server/{k}": v
                       for k, v in _flatten(state.adarev_server).items()})
        arrays.update({f"adarev_gsum/{k}": v
                       for k, v in
                       _flatten(gather(state.adarev_gsum)).items()})
    else:
        arrays["kind"] = np.asarray("dense")
        arrays.update({f"params/{k}": v for k, v in _flatten(params).items()})
        arrays.update({f"history/{k}": v
                       for k, v in _flatten(state.solver.history).items()})
    arrays.update({f"comm_error/{k}": v
                   for k, v in _flatten(gather(state.comm_error)).items()})
    tmp = f"{state_path}.tmp.{pid}"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, state_path)
    return model_path, state_path


def restore(state_path: str) -> Tuple[Dict, object]:
    """Rebuild (params, state) from a .solverstate.npz. The state is a
    TrainState or SSPState depending on how the snapshot was taken; callers
    running in the other mode can convert via ``coerce_state``."""
    z = np.load(state_path)
    groups: Dict[str, Dict[str, np.ndarray]] = {}
    it = 0
    kind = "dense"
    for key in z.files:
        if key == "iter":
            it = int(z[key])
        elif key == "kind":
            kind = str(z[key])
        else:
            group, rest = key.split("/", 1)
            groups.setdefault(group, {})[rest] = z[key]
    params = _unflatten(groups.get("params", {}))
    it_arr = jnp.asarray(it, jnp.int32)
    err = _unflatten(groups.get("comm_error", {}))
    if kind == "ssp":
        state = SSPState(
            local_params=_unflatten(groups.get("local_params", {})),
            local_history=_unflatten(groups.get("local_history", {})),
            anchor_params=params, it=it_arr, comm_error=err,
            adarev_server=_unflatten(groups.get("adarev_server", {})),
            adarev_gsum=_unflatten(groups.get("adarev_gsum", {})))
    else:
        state = TrainState(
            solver=SolverState(it=it_arr,
                               history=_unflatten(groups.get("history", {}))),
            comm_error=err)
    return params, state


def coerce_state(params, state, *, staleness: int, n_dev: int, comm=None):
    """Adapt a restored state to the engine's current mode.

    dense -> SSP: broadcast params to fresh per-device copies (histories
    restart, like the reference's thread-0 fallback in Restore).
    SSP -> dense: collapse to the anchor view with fresh history.
    Matching modes pass through (with an n_dev check for SSP), reconciling
    comm_error against the engine's *current* comm config — layers that
    changed strategy get fresh/dropped residuals. On a mode CHANGE the
    residuals restart at zero instead: dense residuals hold per-step gradient
    mass while SSP residuals hold per-period parameter-delta mass — different
    units, so carrying them over would inject a wrongly-scaled correction at
    the first sync (histories restart on mode change for the same reason)."""
    from ..solvers.updates import init_state

    def fix_err(p, st):
        st = st._replace(comm_error=reconcile_comm_error(
            p, st.comm_error, comm, n_dev))
        if not isinstance(st, SSPState):
            return st
        # adarevision accumulators resume only into an identically-shaped
        # adarevision run; any config change restarts them (z/zmax at 1,
        # empty oplog) — mixing units across server logics would inject a
        # wrongly-scaled first sync, same reasoning as comm_error above
        from ..parallel.trainer import init_adarev_state
        server, gsum = init_adarev_state(p, comm, n_dev)
        same = jax.tree_util.tree_structure(server) == \
            jax.tree_util.tree_structure(st.adarev_server) and all(
                a.shape == b.shape for a, b in zip(
                    jax.tree_util.tree_leaves(server),
                    jax.tree_util.tree_leaves(st.adarev_server)))
        if same and server:
            gs_same = jax.tree_util.tree_structure(gsum) == \
                jax.tree_util.tree_structure(st.adarev_gsum) and all(
                    a.shape == b.shape for a, b in zip(
                        jax.tree_util.tree_leaves(gsum),
                        jax.tree_util.tree_leaves(st.adarev_gsum)))
            return st._replace(
                adarev_gsum=st.adarev_gsum if gs_same else gsum)
        return st._replace(adarev_server=server, adarev_gsum=gsum)

    want_ssp = staleness > 0
    is_ssp = isinstance(state, SSPState)
    if want_ssp and not is_ssp:
        fresh = init_ssp_state(params, n_dev, comm)  # zero residuals
        return params, fresh._replace(it=state.solver.it)
    if not want_ssp and is_ssp:
        anchor = state.anchor_params
        return anchor, TrainState(
            solver=init_state(anchor)._replace(it=state.it),
            comm_error=init_comm_error(anchor, comm, n_dev))
    if is_ssp:
        stored_dev = jax.tree_util.tree_leaves(state.local_params)[0].shape[0]
        if stored_dev != n_dev:
            fresh = init_ssp_state(state.anchor_params, n_dev, comm)
            return state.anchor_params, fresh._replace(it=state.it)
    return params, fix_err(params, state)


class AsyncSnapshotWriter:
    """Snapshot serialization off the training critical path.

    ``submit()`` takes the host copy synchronously (the ONLY sync point —
    ``host_state_copy`` blocks on the device and gathers sharded leaves,
    which must happen on the caller thread under multi-process), then
    hands serialization + the atomic tmp-rename protocol to a background
    thread running the unmodified ``snapshot()``. At most one write is in
    flight: a new ``submit`` first joins the previous one, so snapshot
    cadence can never outrun the disk into unbounded queued host copies.

    Failures are loud, never lost: a write error is re-raised by the next
    ``submit()``/``wait()``. A torn shutdown (process death mid-write)
    leaves at worst ``*.tmp.<pid>`` litter — the rename is what creates
    the real suffix, so a partial file can never shadow a completed
    artifact; ``sweep_stale_tmp`` collects the litter on the next
    auto-resume (tests/test_pipeline_overlap.py)."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last: Optional[Tuple[str, str]] = None

    def submit(self, prefix: str, net: Net, params, state) -> Tuple[str, str]:
        """Queue one snapshot; returns the (model, state) paths the write
        will land at. Blocks only for the host copy (and any still-running
        previous write)."""
        self.wait()  # one in flight; re-raises a previous failure
        host_params, host_state = host_state_copy(params, state)
        paths = snapshot_paths(prefix, host_state)

        def _write():
            try:
                self._last = snapshot(prefix, net, host_params, host_state)
            except BaseException as e:  # noqa: BLE001 — surfaced on join
                self._error = e

        self._thread = threading.Thread(target=_write, daemon=True)
        self._thread.start()
        return paths

    def wait(self) -> Optional[Tuple[str, str]]:
        """Join the in-flight write (if any); re-raise its failure; return
        the last completed (model, state) paths.

        Failure surfacing contract (pinned by
        test_pipeline_overlap.test_async_snapshot_failure_aborts_at_next_
        sync_boundary): the training loop calls this at every snapshot
        boundary (submit's join) and at end-of-train, so a background
        write that died aborts the run AT THE NEXT SYNC BOUNDARY with the
        original exception — never a silent pass that leaves auto-resume
        pointing at a snapshot that does not exist."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            # name the failed artifact BEFORE re-raising: the exception
            # type is the writer's own (a disk error stays a disk error),
            # the context says which snapshot is missing because of it
            from .metrics import log
            log(f"async snapshot write FAILED "
                f"({type(err).__name__}: {err}); the snapshot it was "
                f"writing does not exist — aborting at this sync boundary")
            raise err
        return self._last

    def close(self) -> None:
        self.wait()


def load_caffemodel(path: str, net: Net, params):
    with open(path, "rb") as f:
        weights = decode_caffemodel(f.read())
    return net.load_weights(params, weights)


# latest_snapshot / sweep_stale_tmp live in ckpt_files (re-exported above):
# pure-filesystem discovery and tmp hygiene, kept jax-free for the socket
# tier.
