"""Snapshots: solver-state checkpoints + .caffemodel weight exchange.

The reference writes two artifacts (solver.cpp:632-696): the model as a
binary NetParameter (``.caffemodel``, written by rank 0) and per-worker
``.solverstate`` files with the iteration and momentum history. Here:

- ``snapshot()`` writes ``<prefix>_iter_<N>.caffemodel`` (wire-compatible with
  Caffe) and ``<prefix>_iter_<N>.solverstate.npz`` (params + history + iter +
  comm residuals), sharding-agnostic since params are replicated.
- ``restore()`` rebuilds (params, TrainState) from the .npz;
  ``load_caffemodel()`` imports weights alone (CopyTrainedLayersFrom).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.net import Net
from ..parallel.trainer import TrainState
from ..proto.wire import decode_caffemodel, encode_caffemodel
from ..solvers.updates import SolverState


# Layer names may contain '/' (GoogLeNet's "inception_3a/1x1"), so tree keys
# are joined with the ASCII unit separator, which cannot appear in prototxt
# identifiers.
_SEP = "\x1f"


def _flatten(tree, prefix=""):
    out = {}
    for k, v in tree.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + _SEP))
        else:
            out[key] = np.asarray(v)
    return out


def _unflatten(flat: Dict[str, np.ndarray]):
    tree: Dict = {}
    for key, v in flat.items():
        parts = key.split(_SEP)
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(v)
    return tree


def snapshot(prefix: str, net: Net, params, state: TrainState) -> Tuple[str, str]:
    """Write both artifacts atomically (tmp + rename): with replicated state
    every rank writes identical bytes, so even concurrent snapshots to a
    shared filesystem are safe — the last rename wins with valid content."""
    it = int(state.solver.it)
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    model_path = f"{prefix}_iter_{it}.caffemodel"
    state_path = f"{prefix}_iter_{it}.solverstate.npz"
    pid = os.getpid()

    tmp = f"{model_path}.tmp.{pid}"
    with open(tmp, "wb") as f:
        f.write(encode_caffemodel(net.name or "net", net.export_weights(params)))
    os.replace(tmp, model_path)

    arrays = {}
    arrays.update({f"params/{k}": v for k, v in _flatten(params).items()})
    arrays.update({f"history/{k}": v
                   for k, v in _flatten(state.solver.history).items()})
    arrays.update({f"comm_error/{k}": v
                   for k, v in _flatten(state.comm_error).items()})
    arrays["iter"] = np.asarray(it)
    tmp = f"{state_path}.tmp.{pid}"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, state_path)
    return model_path, state_path


def restore(state_path: str) -> Tuple[Dict, TrainState]:
    z = np.load(state_path)
    params_flat, hist_flat, err_flat = {}, {}, {}
    it = 0
    for key in z.files:
        if key == "iter":
            it = int(z[key])
        elif key.startswith("params/"):
            params_flat[key[len("params/"):]] = z[key]
        elif key.startswith("history/"):
            hist_flat[key[len("history/"):]] = z[key]
        elif key.startswith("comm_error/"):
            err_flat[key[len("comm_error/"):]] = z[key]
    params = _unflatten(params_flat)
    state = TrainState(
        solver=SolverState(it=jnp.asarray(it, jnp.int32),
                           history=_unflatten(hist_flat)),
        comm_error=_unflatten(err_flat))
    return params, state


def load_caffemodel(path: str, net: Net, params):
    with open(path, "rb") as f:
        weights = decode_caffemodel(f.read())
    return net.load_weights(params, weights)


def latest_snapshot(prefix: str) -> Optional[str]:
    d = os.path.dirname(prefix) or "."
    base = os.path.basename(prefix)
    best, best_it = None, -1
    if not os.path.isdir(d):
        return None
    for name in os.listdir(d):
        if name.startswith(base + "_iter_") and \
                name.endswith(".solverstate.npz"):
            try:
                it = int(name[len(base + "_iter_"):-len(".solverstate.npz")])
            except ValueError:
                continue
            if it > best_it:
                best, best_it = os.path.join(d, name), it
    return best
