"""Engine hook for the wait-free async-SSP process tier.

Turns `parallel/async_ssp.py` into a product feature:
``train --async_ssp --staleness N`` under the multi-process launcher. Each
process keeps its LOCAL compiled step (its own mesh, its own momentum
history — the reference's client-side solver state) and this tier owns the
only cross-process exchange: every ``sync_every`` optimizer iterations it
flushes the parameter increment to the rank-0 ParamService (non-blocking),
rebuilds the read-my-writes cache, and gates the NEXT clock on the SSP
window — the Bösen execution model (SURVEY §2.2) riding under an unmodified
Engine loop.

No ``jax.distributed`` world exists in this mode: the processes are
independent JAX runtimes (exactly the deployment the reference's PS serves,
where workers share nothing but the server connection); the CLI skips
``init_distributed`` and the Engine shards data by POSEIDON_PROC_ID.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from ..data.workload import Shard, member_shard
from ..parallel.async_ssp import AsyncSSPClient, ParamService
# canonical home is the (jax-free) cluster control plane; re-exported here
# because the engine and the existing tests import it from this module
from .cluster import env_world, is_elastic_joiner  # noqa: F401
from .metrics import log
from .spans import recorder as _spans


def _to_host(tree: Dict) -> Dict:
    return {l: {p: np.asarray(v, np.float32) for p, v in ps.items()}
            for l, ps in tree.items()}


class AsyncSSPTier:
    """Owns the service (rank 0), the client, and the flush cadence."""

    def __init__(self, params: Dict, staleness: int, sync_every: int = 1,
                 service_port: Optional[int] = None,
                 heartbeat_s: Optional[float] = None,
                 liveness_timeout_s: Optional[float] = None,
                 reconnect_deadline_s: Optional[float] = None,
                 gate_timeout_s: float = 120.0,
                 first_gate_timeout_s: Optional[float] = None,
                 comm_budget_mbps: Optional[float] = None,
                 comm_priority_frac: Optional[float] = None,
                 comm_adaptive: Optional[bool] = None,
                 comm_wire_dtype: Optional[str] = None):
        self.rank, self.n_procs, coord = self._identity()
        self.staleness = staleness
        self.sync_every = max(1, sync_every)
        # managed communication (SSPAggr): None knobs resolve against the
        # global ManagedCommConfig; budget <= 0 keeps the dense path
        from .. import config as _config
        mc = _config.managed_comm_config()
        self.comm_budget_mbps = (mc.budget_mbps if comm_budget_mbps is None
                                 else comm_budget_mbps)
        self.comm_priority_frac = (mc.priority_frac
                                   if comm_priority_frac is None
                                   else comm_priority_frac)
        self.comm_adaptive = (mc.adaptive if comm_adaptive is None
                              else comm_adaptive)
        self.comm_wire_dtype = (mc.wire_dtype if comm_wire_dtype is None
                                else comm_wire_dtype)
        # SSP gate backstop, configurable from the launcher (the client's
        # hardcoded 120 s default killed healthy runs). The FIRST clock's
        # gate waits on peers that are still JIT-compiling their train
        # step — multi-minute for the benchmark nets — so it gets a
        # generously scaled timeout unless the caller pins one.
        self.gate_timeout_s = gate_timeout_s
        self.first_gate_timeout_s = (
            first_gate_timeout_s if first_gate_timeout_s is not None
            else max(1800.0, 10.0 * gate_timeout_s))
        self._gated_once = False
        host = "127.0.0.1"
        port = service_port
        if coord:
            chost, cport = coord.rsplit(":", 1)
            host = chost
            if port is None:
                port = int(cport) + 1
        if port is None:
            port = 12356
        self.service = None
        if self.rank == 0:
            # only the service seed needs the host copy of params — every
            # rank's own view (_prev/resume_cache) comes from join()'s
            # anchor pull below. None knobs resolve to the global
            # FaultConfig inside the service/client (config.fault_config())
            self.service = ParamService(
                _to_host(params), n_workers=self.n_procs, host=host,
                port=port, liveness_timeout_s=liveness_timeout_s)
            # an ephemeral bind (service_port=0) resolves here: dial what
            # the service actually got, not the 0 placeholder
            port = self.service.port
        self.client = AsyncSSPClient(
            self.rank, (host, port), staleness, n_workers=self.n_procs,
            heartbeat_s=heartbeat_s,
            reconnect_deadline_s=reconnect_deadline_s,
            budget_mbps=(self.comm_budget_mbps
                         if self.comm_budget_mbps > 0 else None),
            priority_frac=self.comm_priority_frac,
            adaptive=self.comm_adaptive,
            wire_dtype=self.comm_wire_dtype)
        # ONE join path for every process biography (join() == the admit
        # RPC, idempotent for existing members):
        # - fresh launch-roster worker: admit is a no-op pull, clock -1;
        # - restart of a known worker: the service already holds an
        #   applied clock for it, so the push-seq stream resumes PAST the
        #   exactly-once high-water mark (a client naively restarting at
        #   seq 0 would have every post-restart flush swallowed by dedup);
        # - elastic joiner (rank >= launch roster): the service ADMITS it
        #   at the rendezvous anchor clock and every member's gate/data
        #   shard re-keys to the grown member list.
        # join() also hands back the anchor, which seeds the cache for all
        # three (everyone starts from the same rank-0 view, the
        # reference's init broadcast); Engine.train adopts it via
        # ``resume_cache``.
        cache, clocks = self.client.join()
        applied = clocks.get(self.rank, -1)
        if is_elastic_joiner(self.rank, self.n_procs):
            # printed from THIS process regardless of rank (log() is
            # rank-0-only by default): the joiner's operator-visible
            # evidence that the rendezvous landed is this line
            log(f"async-SSP tier: rank {self.rank} ADMITTED into the live "
                f"job at join clock {applied} (members "
                f"{sorted(self.client.members)})")
        elif applied >= 0:
            log(f"async-SSP tier: rank {self.rank} rejoined at clock "
                f"{applied}; push stream resumes at {applied + 1}",
                rank=self.rank)
        self._prev = cache
        self.resume_cache = cache
        self._iters_since = 0
        self._members: Tuple[int, ...] = tuple(sorted(self.client.members))
        self._t0 = time.time()
        managed = (f", managed comm {self.comm_budget_mbps:g} Mbit/s "
                   f"(priority_frac {self.comm_priority_frac:g}, "
                   f"adaptive {'on' if self.comm_adaptive else 'off'})"
                   if self.comm_budget_mbps > 0 else "")
        if self.comm_wire_dtype:
            managed += f", wire dtype {self.comm_wire_dtype}"
        log(f"async-SSP tier: {len(self._members)} members, staleness "
            f"{staleness}, flush every {self.sync_every} iter(s), service "
            f"{host}:{port}{managed}", rank=self.rank)

    # ------------------------------------------------------------------ #
    def _identity(self) -> Tuple[int, int, Optional[str]]:
        """(worker id, worker count, coordinator) — the DCN-tier identity
        this process speaks the protocol under. The base tier is the
        per-process mode (one launcher rank = one SSP worker);
        :class:`FabricTier` overrides it so one SLICE = one worker."""
        return env_world()

    def _mirror(self) -> None:
        """Post-push replication hook: a no-op in per-process mode (a
        worker's oplog dies with it — the bounded-loss failure model);
        the fabric tier mirrors the leader's oplog to the slice ledger
        here so failover can resume the push stream exactly-once."""

    def data_shard(self) -> Shard:
        """This worker's record-space shard under the CURRENT member list
        (data/workload.member_shard keyed by membership, not launch
        rank/world)."""
        members = set(self.client.members) | {self.rank}
        return member_shard(members, self.rank)

    def sync_membership(self, engine) -> bool:
        """Reshard the engine's data assignment if membership changed
        since the last look. Returns True on a change. Called at tier
        creation (a joiner's Engine built its pipelines with a placeholder
        shard) and after every flush (admissions/retirements/evictions
        land within one clock of the service learning about them)."""
        mem = tuple(sorted(set(self.client.members) | {self.rank}))
        if mem == self._members:
            return False
        old, self._members = self._members, mem
        log(f"async-SSP tier: membership changed {list(old)} -> "
            f"{list(mem)}; resharding data assignment", rank=self.rank)
        if engine is not None and hasattr(engine, "reshard_data"):
            engine.reshard_data(member_shard(mem, self.rank))
        return True

    def membership_counters(self) -> Dict[str, float]:
        """Membership churn telemetry for the engine's periodic display
        and stats.yaml (runtime/comm_stats.membership_counters)."""
        from .comm_stats import membership_counters
        return membership_counters(service=self.service, client=self.client)

    def comm_counters(self) -> Dict[str, float]:
        """Per-link managed-communication telemetry (bytes, deferred
        fraction, goodput, cadence backoffs) for the engine's periodic
        display and stats.yaml (runtime/comm_stats.managed_comm_counters)."""
        from .comm_stats import managed_comm_counters
        return managed_comm_counters(self.client)

    # ------------------------------------------------------------------ #
    def after_iters(self, engine, n_iters: int) -> None:
        """Called by Engine.train after every completed dispatch (n_iters
        optimizer steps). Flush + refresh + gate at the clock cadence.

        The iteration carry SUBTRACTS ``sync_every`` per flush (loop-flush)
        instead of resetting to zero: a dispatch covering K > sync_every
        iterations advances the clock floor((carry + K) / sync_every)
        times — the first flush carries the whole delta, the rest advance
        the clock on empty deltas — so ``steps_per_dispatch`` larger than
        ``async_sync_every`` no longer silently widens the effective
        staleness window (a clock must always mean sync_every iterations,
        or the SSP bound s is measured in the wrong unit)."""
        self._iters_since += n_iters
        if self._iters_since < self.sync_every:
            return
        with _spans.span("async_flush", "async", {"rank": self.rank}):
            self._flush(engine)

    def _flush(self, engine) -> None:
        cur = _to_host(engine.params)
        delta = {l: {p: cur[l][p] - self._prev[l][p] for p in ps}
                 for l, ps in cur.items()}
        clock = self.client.push(delta)
        self._mirror()
        # exception safety, not data flow: refresh() below replaces _prev,
        # but if it raises (permanently dead tier) a retrying caller must
        # never re-derive — and double-push — the delta just enqueued
        self._prev = cur
        self._iters_since -= self.sync_every
        while self._iters_since >= self.sync_every:
            # the remaining windows' updates are already in the first
            # flush; advance the clock on EMPTY deltas (the service's
            # apply iterates the payload's keys, so {} is a pure clock
            # tick — no parameter-sized zero trees on the wire or in the
            # client's replay oplog)
            clock = self.client.push({})
            self._mirror()
            self._iters_since -= self.sync_every
        cache, _ = self.client.refresh()
        self._prev = cache
        engine.params = jax.device_put(
            {l: {p: v for p, v in ps.items()} for l, ps in cache.items()},
            engine.train_step.replicated)
        timeout = (self.gate_timeout_s if self._gated_once
                   else self.first_gate_timeout_s)
        self._gated_once = True
        self.client.gate(clock + 1, timeout_s=timeout)
        # the refresh/gate above refreshed the member view: fold any
        # admission/retirement/eviction into the data assignment now, at
        # the clock boundary (never mid-dispatch)
        self.sync_membership(engine)

    def finish(self, engine) -> Dict[str, float]:
        # flush the residual delta of any iterations past the last
        # sync_every boundary — trailing updates must reach the anchor
        if self._iters_since:
            self._iters_since = self.sync_every  # force the flush
            self.after_iters(engine, 0)
        self.client.mark_done()
        out = {"async_blocked_s": round(self.client.blocked_s, 3),
               "async_gate_blocks": float(self.client.gate_blocks),
               "async_final_clock": float(self.client.clock),
               "async_reconnects": float(self.client.reconnects)}
        # the per-link managed-communication bill rides the tier summary
        # (bytes_sent/deferred_fraction/effective_mbps/cadence_backoffs)
        for k, v in self.comm_counters().items():
            out[f"async_comm_{k}"] = round(float(v), 4)
        if self.service is not None:
            # poll (not barrier) until the stragglers flush their last
            # clock; None = the CURRENT member set, which under elastic
            # membership may have grown past (or shrunk below) the
            # launch-time n_procs
            done, failed = self.client.wait_all_done(None)
            out["async_max_spread"] = float(self.service.max_spread)
            out["async_evictions"] = float(self.service.evictions)
            out["async_rejoins"] = float(self.service.rejoins)
            out["async_admissions"] = float(self.service.admissions)
            if failed:
                # elasticity keeps the job alive; it must never keep the
                # loss quiet — the failed workers' un-flushed updates are
                # simply absent from the anchor
                out["async_failed_workers"] = sorted(failed)
                log(f"WARNING: async-SSP workers {sorted(failed)} FAILED "
                    f"mid-run; anchor holds their applied clocks only",
                    rank=self.rank)
            # the final anchor is the job's result: fold it into rank 0's
            # params so snapshots/eval see every worker's updates
            engine.params = jax.device_put(
                self.service.anchor, engine.train_step.replicated)
            time.sleep(0.2)
            self.service.close()
        self.client.close()
        log("async-SSP tier: " + ", ".join(f"{k}={v}"
                                           for k, v in out.items()),
            rank=self.rank)
        return out


class FabricTier(AsyncSSPTier):
    """Two-tier fabric engine hook (``train --async_ssp --slice``): this
    process is the designated LEADER of an SPMD slice, and the DCN
    identity it speaks the protocol under is the SLICE id — the
    ParamService gates, shards and admits/retires by slice membership
    (parallel/fabric.py). Everything else is the inherited tier: the
    inherited ``data_shard`` keyed by slice-id members IS the outer cut
    of the two-tier partition (the inner cut happens inside the slice's
    own SPMD step, which shards the batch over its dp/fsdp sub-mesh),
    and the flush cadence/gates/telemetry carry over unchanged.

    Only the leader runs this tier: a multi-process slice's non-leader
    ranks run the synchronous intra-slice program under the slice's own
    ``jax.distributed`` world and never dial the DCN service — launching
    one with ``--slice`` is refused loudly (a second client under the
    same slice id would fork the seq stream and break exactly-once).
    The leader mirrors its push oplog into the slice ledger after every
    flush; on leader death a surviving member re-launches with the same
    slice env and resumes via ``AsyncSSPClient.resume_oplog``."""

    def __init__(self, params: Dict, staleness: int, **kwargs):
        from ..config import fabric_config
        from ..parallel.fabric import SliceLedger
        from .cluster import slice_world
        sw = slice_world(n_visible_devices=jax.device_count())
        if sw is None:
            raise ValueError(
                "--slice requires the slice env contract: set "
                "POSEIDON_SLICE_ID and POSEIDON_SLICE_SIZE "
                "(runtime/cluster.slice_world)")
        if not sw.is_leader:
            raise ValueError(
                f"rank-in-slice {sw.rank_in_slice} of slice {sw.slice_id} "
                f"is not the leader: only the leader (rank-in-slice 0) "
                f"speaks the DCN protocol — a second client under slice id "
                f"{sw.slice_id} would fork the push-seq stream and break "
                f"exactly-once. Non-leader ranks run the intra-slice SPMD "
                f"program only.")
        self.slice_assignment = sw
        self.ledger = SliceLedger()
        self._fabric_cfg = fabric_config()
        super().__init__(params, staleness, **kwargs)
        log(f"fabric tier: slice {sw.slice_id} of {self.n_procs} "
            f"({sw.slice_size} process(es)/slice, leader rank-in-slice 0) "
            f"speaking the DCN tier as worker {self.rank}", rank=0)

    def _identity(self) -> Tuple[int, int, Optional[str]]:
        """The slice IS the worker: id = slice_id, count = whole slices
        in the roster. The coordinator address still comes from the
        process env (the service rides the same rendezvous host)."""
        _, _, coord = env_world()
        sw = self.slice_assignment
        return sw.slice_id, sw.n_slices, coord

    def _mirror(self) -> None:
        if self._fabric_cfg.ledger_mirroring:
            self.ledger.mirror(self.client)
