"""Engine hook for the wait-free async-SSP process tier.

Turns `parallel/async_ssp.py` into a product feature:
``train --async_ssp --staleness N`` under the multi-process launcher. Each
process keeps its LOCAL compiled step (its own mesh, its own momentum
history — the reference's client-side solver state) and this tier owns the
only cross-process exchange: every ``sync_every`` optimizer iterations it
flushes the parameter increment to the rank-0 ParamService (non-blocking),
rebuilds the read-my-writes cache, and gates the NEXT clock on the SSP
window — the Bösen execution model (SURVEY §2.2) riding under an unmodified
Engine loop.

No ``jax.distributed`` world exists in this mode: the processes are
independent JAX runtimes (exactly the deployment the reference's PS serves,
where workers share nothing but the server connection); the CLI skips
``init_distributed`` and the Engine shards data by POSEIDON_PROC_ID.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

import jax
import numpy as np

from ..parallel.async_ssp import AsyncSSPClient, ParamService
from .metrics import log


def env_world() -> Tuple[int, int, Optional[str]]:
    """(rank, n_procs, coordinator) from the launcher env contract."""
    return (int(os.environ.get("POSEIDON_PROC_ID", "0")),
            int(os.environ.get("POSEIDON_NUM_PROCS", "1")),
            os.environ.get("POSEIDON_COORDINATOR"))


def _to_host(tree: Dict) -> Dict:
    return {l: {p: np.asarray(v, np.float32) for p, v in ps.items()}
            for l, ps in tree.items()}


class AsyncSSPTier:
    """Owns the service (rank 0), the client, and the flush cadence."""

    def __init__(self, params: Dict, staleness: int, sync_every: int = 1,
                 service_port: Optional[int] = None,
                 heartbeat_s: Optional[float] = None,
                 liveness_timeout_s: Optional[float] = None,
                 reconnect_deadline_s: Optional[float] = None,
                 gate_timeout_s: float = 120.0,
                 first_gate_timeout_s: Optional[float] = None):
        self.rank, self.n_procs, coord = env_world()
        self.staleness = staleness
        self.sync_every = max(1, sync_every)
        # SSP gate backstop, configurable from the launcher (the client's
        # hardcoded 120 s default killed healthy runs). The FIRST clock's
        # gate waits on peers that are still JIT-compiling their train
        # step — multi-minute for the benchmark nets — so it gets a
        # generously scaled timeout unless the caller pins one.
        self.gate_timeout_s = gate_timeout_s
        self.first_gate_timeout_s = (
            first_gate_timeout_s if first_gate_timeout_s is not None
            else max(1800.0, 10.0 * gate_timeout_s))
        self._gated_once = False
        host = "127.0.0.1"
        port = service_port
        if coord:
            chost, cport = coord.rsplit(":", 1)
            host = chost
            if port is None:
                port = int(cport) + 1
        if port is None:
            port = 12356
        self.service = None
        if self.rank == 0:
            # only the service seed needs the host copy of params — every
            # rank's own view (_prev/resume_cache) comes from rejoin()'s
            # anchor pull below. None knobs resolve to the global
            # FaultConfig inside the service/client (config.fault_config())
            self.service = ParamService(
                _to_host(params), n_workers=self.n_procs, host=host,
                port=port, liveness_timeout_s=liveness_timeout_s)
        self.client = AsyncSSPClient(
            self.rank, (host, port), staleness, n_workers=self.n_procs,
            heartbeat_s=heartbeat_s,
            reconnect_deadline_s=reconnect_deadline_s)
        # restart-aware join: if the service already holds an applied clock
        # for this worker (a previous incarnation pushed before dying), the
        # push-seq stream MUST resume past it — a fresh client restarting
        # at seq 0 would have every post-restart flush swallowed by the
        # exactly-once dedup. rejoin() also hands back the anchor, which
        # seeds the cache for restarted AND fresh workers alike (everyone
        # starts from the same rank-0 view, the reference's init
        # broadcast); Engine.train adopts it via ``resume_cache``.
        cache, clocks = self.client.rejoin()
        applied = clocks.get(self.rank, -1)
        if applied >= 0:
            log(f"async-SSP tier: rank {self.rank} rejoined at clock "
                f"{applied}; push stream resumes at {applied + 1}",
                rank=self.rank)
        self._prev = cache
        self.resume_cache = cache
        self._iters_since = 0
        self._t0 = time.time()
        log(f"async-SSP tier: {self.n_procs} workers, staleness "
            f"{staleness}, flush every {self.sync_every} iter(s), service "
            f"{host}:{port}", rank=self.rank)

    # ------------------------------------------------------------------ #
    def after_iters(self, engine, n_iters: int) -> None:
        """Called by Engine.train after every completed dispatch (n_iters
        optimizer steps). Flush + refresh + gate at the clock cadence.

        The iteration carry SUBTRACTS ``sync_every`` per flush (loop-flush)
        instead of resetting to zero: a dispatch covering K > sync_every
        iterations advances the clock floor((carry + K) / sync_every)
        times — the first flush carries the whole delta, the rest advance
        the clock on empty deltas — so ``steps_per_dispatch`` larger than
        ``async_sync_every`` no longer silently widens the effective
        staleness window (a clock must always mean sync_every iterations,
        or the SSP bound s is measured in the wrong unit)."""
        self._iters_since += n_iters
        if self._iters_since < self.sync_every:
            return
        cur = _to_host(engine.params)
        delta = {l: {p: cur[l][p] - self._prev[l][p] for p in ps}
                 for l, ps in cur.items()}
        clock = self.client.push(delta)
        # exception safety, not data flow: refresh() below replaces _prev,
        # but if it raises (permanently dead tier) a retrying caller must
        # never re-derive — and double-push — the delta just enqueued
        self._prev = cur
        self._iters_since -= self.sync_every
        while self._iters_since >= self.sync_every:
            # the remaining windows' updates are already in the first
            # flush; advance the clock on EMPTY deltas (the service's
            # apply iterates the payload's keys, so {} is a pure clock
            # tick — no parameter-sized zero trees on the wire or in the
            # client's replay oplog)
            clock = self.client.push({})
            self._iters_since -= self.sync_every
        cache, _ = self.client.refresh()
        self._prev = cache
        engine.params = jax.device_put(
            {l: {p: v for p, v in ps.items()} for l, ps in cache.items()},
            engine.train_step.replicated)
        timeout = (self.gate_timeout_s if self._gated_once
                   else self.first_gate_timeout_s)
        self._gated_once = True
        self.client.gate(clock + 1, timeout_s=timeout)

    def finish(self, engine) -> Dict[str, float]:
        # flush the residual delta of any iterations past the last
        # sync_every boundary — trailing updates must reach the anchor
        if self._iters_since:
            self._iters_since = self.sync_every  # force the flush
            self.after_iters(engine, 0)
        self.client.mark_done()
        out = {"async_blocked_s": round(self.client.blocked_s, 3),
               "async_gate_blocks": float(self.client.gate_blocks),
               "async_final_clock": float(self.client.clock),
               "async_reconnects": float(self.client.reconnects)}
        if self.service is not None:
            # poll (not barrier) until the stragglers flush their last clock
            done, failed = self.client.wait_all_done(self.n_procs)
            out["async_max_spread"] = float(self.service.max_spread)
            out["async_evictions"] = float(self.service.evictions)
            out["async_rejoins"] = float(self.service.rejoins)
            if failed:
                # elasticity keeps the job alive; it must never keep the
                # loss quiet — the failed workers' un-flushed updates are
                # simply absent from the anchor
                out["async_failed_workers"] = sorted(failed)
                log(f"WARNING: async-SSP workers {sorted(failed)} FAILED "
                    f"mid-run; anchor holds their applied clocks only",
                    rank=self.rank)
            # the final anchor is the job's result: fold it into rank 0's
            # params so snapshots/eval see every worker's updates
            engine.params = jax.device_put(
                self.service.anchor, engine.train_step.replicated)
            time.sleep(0.2)
            self.service.close()
        self.client.close()
        log("async-SSP tier: " + ", ".join(f"{k}={v}"
                                           for k, v in out.items()),
            rank=self.rank)
        return out
