from .engine import Engine, resolve_nets  # noqa: F401
from .metrics import MetricsTable, StatsRegistry, log  # noqa: F401
from .checkpoint import (  # noqa: F401
    latest_snapshot, load_caffemodel, restore, snapshot,
)
