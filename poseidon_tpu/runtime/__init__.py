"""Runtime package: engine, metrics, checkpointing, cluster control plane.

Re-exports resolve lazily (PEP 562): importing a light submodule
(``runtime.retry``, ``runtime.metrics``, ``runtime.faults``) from a
plain-socket worker process must not drag in ``engine`` — and with it jax —
as an eager ``from .engine import Engine`` here would.
"""

_LAZY = {
    "Engine": ("engine", "Engine"),
    "resolve_nets": ("engine", "resolve_nets"),
    "MetricsTable": ("metrics", "MetricsTable"),
    "StatsRegistry": ("metrics", "StatsRegistry"),
    "log": ("metrics", "log"),
    "latest_snapshot": ("ckpt_files", "latest_snapshot"),
    "sweep_stale_tmp": ("ckpt_files", "sweep_stale_tmp"),
    "load_caffemodel": ("checkpoint", "load_caffemodel"),
    "restore": ("checkpoint", "restore"),
    "snapshot": ("checkpoint", "snapshot"),
}

__all__ = list(_LAZY)


def __getattr__(name):
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    from importlib import import_module
    return getattr(import_module(f".{mod_name}", __name__), attr)
