"""Metrics registry: the analog of the reference's net-output PS tables + stats.

The reference aggregates per-display-window training metrics into a PS table
whose rows are {iter, time, loss, outputs...} and dumps an averaged CSV at the
end of training (``PrintNetOutputs``, solver.cpp:699-756), plus a YAML stats
artifact when compiled with -DPETUUM_STATS (stats.hpp). Here metrics come back
from the compiled step already cross-replica-averaged; this module accumulates
them per display window and writes the same artifact shapes (CSV + YAML).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np


class MetricsTable:
    def __init__(self, name: str):
        self.name = name
        self.rows: List[Dict[str, float]] = []
        self._window: Dict[str, List[float]] = defaultdict(list)
        self._t0 = time.time()

    def accumulate(self, metrics: Dict[str, float]) -> None:
        for k, v in metrics.items():
            self._window[k].append(float(v))

    def flush_row(self, iteration: int) -> Dict[str, float]:
        row = {"iter": iteration, "time": round(time.time() - self._t0, 3)}
        for k, vals in self._window.items():
            row[k] = sum(vals) / max(len(vals), 1)
        self._window.clear()
        self.rows.append(row)
        return row

    def to_csv(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        cols: List[str] = []
        for row in self.rows:
            for k in row:
                if k not in cols:
                    cols.append(k)
        with open(path, "w") as f:
            f.write(",".join(cols) + "\n")
            for row in self.rows:
                f.write(",".join(str(row.get(c, "")) for c in cols) + "\n")


class StatsRegistry:
    """Run-level counters/timers dumped as one YAML per run (stats.hpp analog).

    ``set_section`` attaches a nested dict (e.g. the static per-layer comm
    accounting from comm_stats.py — the analog of the reference's bg oplog
    bytes / server push bytes stats). Thread-safe: the engine loop, span
    instrumentation, serving handler threads and the live metrics endpoint
    (:class:`MetricsServer`) all touch one registry concurrently.

    The YAML dump is atomic (tmp + rename) and the engine calls it at
    every display boundary — a crashed or preempted run keeps its
    telemetry up to the last boundary, with only sweepable tmp litter."""

    def __init__(self):
        self.counters: Dict[str, float] = defaultdict(float)
        self.timers: Dict[str, float] = defaultdict(float)
        self.gauges: Dict[str, float] = {}
        self.sections: Dict[str, dict] = {}
        self._lock = threading.Lock()

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += value

    def add_time(self, name: str, seconds: float) -> None:
        with self._lock:
            self.timers[name] += seconds

    def set_gauge(self, name: str, value: float) -> None:
        """Last-value-wins instantaneous reading (iteration, loss, queue
        depth) — the live-endpoint counterpart of a monotonic counter."""
        with self._lock:
            self.gauges[name] = value

    def set_section(self, name: str, data: dict) -> None:
        with self._lock:
            self.sections[name] = data

    def snapshot(self) -> Dict[str, dict]:
        """A consistent copy of everything (one lock hold)."""
        with self._lock:
            return {"counters": dict(self.counters),
                    "timers_sec": {k: round(v, 6)
                                   for k, v in self.timers.items()},
                    "gauges": dict(self.gauges),
                    "sections": {k: dict(v)
                                 for k, v in self.sections.items()}}

    def render_text(self) -> str:
        """Flat ``key=value`` lines — what ``--metrics_port`` serves (one
        curl mid-run answers "where is this job"). Sections flatten with
        dotted keys; non-scalar leaves are skipped (the YAML has them)."""
        snap = self.snapshot()
        lines = []

        def emit(prefix: str, tree: dict) -> None:
            for k in sorted(tree):
                v = tree[k]
                if isinstance(v, dict):
                    emit(f"{prefix}{k}.", v)
                elif isinstance(v, (int, float)) and not isinstance(v, bool):
                    lines.append(f"{prefix}{k}={v}")

        emit("", snap["counters"])
        emit("", snap["gauges"])
        emit("", {f"{k}_sec": v for k, v in snap["timers_sec"].items()})
        emit("", snap["sections"])
        return "\n".join(lines) + "\n"

    @staticmethod
    def _write_tree(f, tree: dict, indent: int) -> None:
        pad = "  " * indent
        for k, v in tree.items():
            if isinstance(v, dict):
                f.write(f"{pad}{k}:\n")
                StatsRegistry._write_tree(f, v, indent + 1)
            else:
                f.write(f"{pad}{k}: {'null' if v is None else v}\n")

    def render_yaml(self) -> str:
        """The full stats.yaml document as a string — ONE renderer shared
        by ``dump_yaml`` and the live ``/yaml`` endpoint, so the two can
        never drift."""
        import io
        snap = self.snapshot()
        f = io.StringIO()
        f.write("counters:\n")
        for k in sorted(snap["counters"]):
            f.write(f"  {k}: {snap['counters'][k]}\n")
        f.write("timers_sec:\n")
        for k in sorted(snap["timers_sec"]):
            f.write(f"  {k}: {snap['timers_sec'][k]}\n")
        if snap["gauges"]:
            f.write("gauges:\n")
            for k in sorted(snap["gauges"]):
                f.write(f"  {k}: {snap['gauges'][k]}\n")
        for name in sorted(snap["sections"]):
            f.write(f"{name}:\n")
            self._write_tree(f, snap["sections"][name], 1)
        return f.getvalue()

    def dump_yaml(self, path: str) -> None:
        """Atomic write (tmp + os.replace): a reader — or the next run's
        auto-resume forensics — never sees a torn stats.yaml, and a
        killed writer leaves only a sweepable ``.tmp.<pid>`` file."""
        doc = self.render_yaml()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(doc)
        os.replace(tmp, path)


class MetricsServer:
    """The ``--metrics_port`` one-liner: a read-only HTTP endpoint serving
    a StatsRegistry as ``text/plain`` key=value lines, curl-able mid-run.

    GET /        -> flat key=value (render_text)
    GET /yaml    -> the stats.yaml document, rendered live

    Runs a daemon-threaded stdlib HTTP server; ``port=0`` binds an
    ephemeral port (read it back from ``.port`` — the tests do). Strictly
    read-only: no mutation op exists, so exposing it on loopback during a
    long run costs nothing but a socket."""

    def __init__(self, registry: "StatsRegistry", port: int = 0,
                 host: str = "127.0.0.1"):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        reg = registry

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):          # noqa: N802 — stdlib contract
                if self.path.rstrip("/") == "/yaml":
                    body = reg.render_yaml().encode()
                else:
                    body = reg.render_text().encode()
                try:
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; charset=utf-8")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except OSError:
                    # client went away / endpoint closing mid-reply: a
                    # read-only metrics poll is never worth a stack trace
                    pass

            def log_message(self, *args):  # quiet: not request-log noise
                pass

        self._srv = ThreadingHTTPServer((host, port), Handler)
        self._srv.daemon_threads = True
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5.0)


def scalar_rows(metrics: Dict) -> List[Dict[str, float]]:  # static-ok: JIT102
    """Materialize one dispatch's device metrics into float rows, one per
    optimizer step. Single-step dispatches hold scalars (one row);
    scan-chunk dispatches hold [K]-stacked arrays (K rows). ``np.asarray``
    on a device value blocks until the step that produced it has run —
    this is where the pipeline actually waits on the device."""
    arrs = {k: np.asarray(v) for k, v in metrics.items()}
    k_steps = max((a.shape[0] for a in arrs.values() if a.ndim >= 1),
                  default=1)
    if k_steps == 1 and all(a.ndim == 0 for a in arrs.values()):
        return [{k: float(a) for k, a in arrs.items()}]
    return [{k: float(a[i]) if a.ndim >= 1 else float(a)
             for k, a in arrs.items()} for i in range(k_steps)]


class AsyncScalarFetcher:
    """Bounded in-flight dispatch window + off-thread scalar drain.

    The training loop dispatches step k+1 BEFORE step k's metrics are
    read: each dispatch's device metrics are ``put()`` here, a drainer
    thread materializes them to host floats (blocking on the device off
    the train thread), and ``put`` itself blocks only when more than
    ``max_in_flight`` dispatches are un-materialized — that backpressure
    IS the dispatch window. ``sync()`` is the hard host<->device sync
    point (display/test/snapshot boundaries and end of training).

    NaN/divergence detection rides the drain: the first non-finite value
    of a watched key records ``(iteration, key, value)`` in
    ``divergence``, observed by the loop at most ``max_in_flight`` steps
    after the step that produced it (the pipelining lag). Rows come back
    in dispatch order, tagged with their first iteration."""

    def __init__(self, max_in_flight: int = 2,
                 watch_keys: Tuple[str, ...] = ("loss",)):
        self.max_in_flight = max(1, int(max_in_flight))
        self.watch_keys = tuple(watch_keys)
        self.divergence: Optional[Tuple[int, str, float]] = None
        self._cond = threading.Condition()
        self._inbox: deque = deque()   # (first_iter, device metrics)
        self._drained: deque = deque()  # (iter, float row)
        self._pending = 0               # dispatches not yet materialized
        self._error: Optional[Exception] = None
        self._closed = False
        self._puts = 0
        self._pending_sum = 0
        self._thread = threading.Thread(target=self._drain_loop, daemon=True)
        self._thread.start()

    @staticmethod
    def _already_ready(metrics: Dict) -> bool:
        """True when every value's device computation has finished
        (np/host scalars count as ready) — nothing left to overlap."""
        for v in metrics.values():
            is_ready = getattr(v, "is_ready", None)
            if is_ready is not None and not is_ready():
                return False
        return True

    # ---- producer side (the train thread) ---------------------------- #
    def put(self, first_iter: int, metrics: Dict) -> None:
        """Enqueue one dispatch's device metrics (first_iter = the global
        iteration of its first optimizer step), then block until the
        window INCLUDING this entry has room for the caller's next
        dispatch: on return at most ``max_in_flight - 1`` dispatches are
        un-materialized, so the step the loop dispatches next brings the
        in-flight count to at most ``max_in_flight``. With
        ``max_in_flight=1`` this drains the entry itself before returning
        — the genuinely serial loop.

        Fast path: when the window is empty and the dispatch has ALREADY
        finished (CPU's effectively-synchronous dispatch, or a device
        that ran ahead of the host), the scalars materialize inline with
        zero thread handoff — the drainer ping-pong is a measured
        ~0.4 ms/step tax on a 2-core host, and there is nothing left to
        overlap for a finished dispatch. Accelerator dispatches that are
        still running take the drainer path and overlap for real."""
        with self._cond:
            if self._error:
                raise self._error
            inline = (self._pending == 0 and not self._inbox
                      and self._already_ready(metrics))
            self._puts += 1
            self._pending_sum += 1 if inline else self._pending + 1
            if not inline:
                self._pending += 1
                self._inbox.append((first_iter, metrics))
                self._cond.notify_all()
                while self._pending > self.max_in_flight - 1 and \
                        not self._error:
                    self._cond.wait()
                if self._error:
                    raise self._error
                return
        # materialize OUTSIDE the lock (values are ready, so this cannot
        # block on the device); the single-producer contract means no
        # other put can interleave, and the drainer's inbox is empty, so
        # row order is preserved
        rows = scalar_rows(metrics)
        with self._cond:
            self._ingest(first_iter, rows)

    def take_drained(self) -> List[Tuple[int, Dict[str, float]]]:
        """Rows materialized so far, in order, without waiting."""
        with self._cond:
            out = list(self._drained)
            self._drained.clear()
        return out

    def sync(self) -> List[Tuple[int, Dict[str, float]]]:
        """Hard sync: wait until every pending dispatch has materialized,
        then return all drained rows (in order). Re-raises a drainer
        failure."""
        with self._cond:
            while self._pending and not self._error:
                self._cond.wait()
            if self._error:
                raise self._error
            out = list(self._drained)
            self._drained.clear()
        return out

    def mean_in_flight(self) -> float:
        """Average window occupancy observed at dispatch time (1.0 = the
        serial loop; -> max_in_flight as the pipeline fills)."""
        with self._cond:
            return self._pending_sum / self._puts if self._puts else 0.0

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)

    def _ingest(self, first_iter: int, rows) -> None:
        """Append materialized rows + run the divergence watch. Caller
        holds the lock."""
        for i, row in enumerate(rows):
            it = first_iter + i
            self._drained.append((it, row))
            if self.divergence is None:
                for k in self.watch_keys:
                    v = row.get(k)
                    if v is not None and not np.isfinite(v):
                        self.divergence = (it, k, v)
                        break

    # ---- drainer thread ---------------------------------------------- #
    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._inbox and not self._closed:
                    self._cond.wait()
                if not self._inbox and self._closed:
                    return
                first_iter, metrics = self._inbox.popleft()
            try:
                rows = scalar_rows(metrics)
            except Exception as e:  # noqa: BLE001 — surface, never wedge
                with self._cond:
                    self._error = e
                    self._pending = 0
                    self._cond.notify_all()
                return
            with self._cond:
                self._ingest(first_iter, rows)
                self._pending -= 1
                self._cond.notify_all()


class LatencyWindow:
    """Sliding-window latency percentiles for the serving tier.

    A bounded deque of the last ``maxlen`` samples (seconds): O(1) record
    on the hot path, sort-on-read only when someone asks for a summary —
    the `/stats` op, not the request path. Thread-safe (server handler
    threads record concurrently)."""

    def __init__(self, maxlen: int = 2048):
        self._samples: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.count = 0            # total ever recorded (window is bounded)

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(float(seconds))
            self.count += 1

    @staticmethod
    def _rank(data: List[float], q: float) -> float:
        """Nearest-rank percentile over sorted ``data`` (one formula, used
        by percentile() and summary() alike)."""
        return data[max(0, min(len(data) - 1,
                               int(round(q / 100.0 * (len(data) - 1)))))]

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile (q in [0, 100]) over the window, in
        seconds; None while empty."""
        with self._lock:
            data = sorted(self._samples)
        return self._rank(data, q) if data else None

    def summary(self) -> Dict[str, float]:
        """{count, p50_ms, p99_ms, mean_ms} over the window (empty -> just
        count=0) — the serving `/stats` payload shape."""
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return {"count": 0}
        return {
            "count": self.count,
            "p50_ms": round(self._rank(data, 50.0) * 1e3, 3),
            "p99_ms": round(self._rank(data, 99.0) * 1e3, 3),
            "mean_ms": round(sum(data) / len(data) * 1e3, 3),
        }

    def samples(self) -> List[float]:
        """A copy of the current window (seconds) — merge fodder."""
        with self._lock:
            return list(self._samples)

    @classmethod
    def merged_summary(cls, windows) -> Dict[str, float]:
        """One summary over the POOLED samples of many windows (the fleet
        aggregation: per-replica percentiles do not average, so the fleet
        row re-ranks the union instead). Counts sum over lifetimes; the
        percentile pool is bounded by each window's maxlen."""
        data: List[float] = []
        total = 0
        for w in windows:
            data.extend(w.samples())
            total += w.count
        if not data:
            return {"count": 0}
        data.sort()
        return {
            "count": total,
            "p50_ms": round(cls._rank(data, 50.0) * 1e3, 3),
            "p99_ms": round(cls._rank(data, 99.0) * 1e3, 3),
            "mean_ms": round(sum(data) / len(data) * 1e3, 3),
        }


def log(msg: str, *, rank: int = 0) -> None:
    """Rank-0-only progress logging, the reference's client0/thread0 idiom."""
    if rank == 0:
        print(msg, flush=True)
