"""Metrics registry: the analog of the reference's net-output PS tables + stats.

The reference aggregates per-display-window training metrics into a PS table
whose rows are {iter, time, loss, outputs...} and dumps an averaged CSV at the
end of training (``PrintNetOutputs``, solver.cpp:699-756), plus a YAML stats
artifact when compiled with -DPETUUM_STATS (stats.hpp). Here metrics come back
from the compiled step already cross-replica-averaged; this module accumulates
them per display window and writes the same artifact shapes (CSV + YAML).
"""

from __future__ import annotations

import os
import threading
import time
from collections import defaultdict, deque
from typing import Dict, List, Optional


class MetricsTable:
    def __init__(self, name: str):
        self.name = name
        self.rows: List[Dict[str, float]] = []
        self._window: Dict[str, List[float]] = defaultdict(list)
        self._t0 = time.time()

    def accumulate(self, metrics: Dict[str, float]) -> None:
        for k, v in metrics.items():
            self._window[k].append(float(v))

    def flush_row(self, iteration: int) -> Dict[str, float]:
        row = {"iter": iteration, "time": round(time.time() - self._t0, 3)}
        for k, vals in self._window.items():
            row[k] = sum(vals) / max(len(vals), 1)
        self._window.clear()
        self.rows.append(row)
        return row

    def to_csv(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        cols: List[str] = []
        for row in self.rows:
            for k in row:
                if k not in cols:
                    cols.append(k)
        with open(path, "w") as f:
            f.write(",".join(cols) + "\n")
            for row in self.rows:
                f.write(",".join(str(row.get(c, "")) for c in cols) + "\n")


class StatsRegistry:
    """Run-level counters/timers dumped as one YAML per run (stats.hpp analog).

    ``set_section`` attaches a nested dict (e.g. the static per-layer comm
    accounting from comm_stats.py — the analog of the reference's bg oplog
    bytes / server push bytes stats)."""

    def __init__(self):
        self.counters: Dict[str, float] = defaultdict(float)
        self.timers: Dict[str, float] = defaultdict(float)
        self.sections: Dict[str, dict] = {}

    def add(self, name: str, value: float = 1.0) -> None:
        self.counters[name] += value

    def add_time(self, name: str, seconds: float) -> None:
        self.timers[name] += seconds

    def set_section(self, name: str, data: dict) -> None:
        self.sections[name] = data

    @staticmethod
    def _write_tree(f, tree: dict, indent: int) -> None:
        pad = "  " * indent
        for k, v in tree.items():
            if isinstance(v, dict):
                f.write(f"{pad}{k}:\n")
                StatsRegistry._write_tree(f, v, indent + 1)
            else:
                f.write(f"{pad}{k}: {'null' if v is None else v}\n")

    def dump_yaml(self, path: str) -> None:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "w") as f:
            f.write("counters:\n")
            for k in sorted(self.counters):
                f.write(f"  {k}: {self.counters[k]}\n")
            f.write("timers_sec:\n")
            for k in sorted(self.timers):
                f.write(f"  {k}: {round(self.timers[k], 6)}\n")
            for name in sorted(self.sections):
                f.write(f"{name}:\n")
                self._write_tree(f, self.sections[name], 1)


class LatencyWindow:
    """Sliding-window latency percentiles for the serving tier.

    A bounded deque of the last ``maxlen`` samples (seconds): O(1) record
    on the hot path, sort-on-read only when someone asks for a summary —
    the `/stats` op, not the request path. Thread-safe (server handler
    threads record concurrently)."""

    def __init__(self, maxlen: int = 2048):
        self._samples: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.count = 0            # total ever recorded (window is bounded)

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(float(seconds))
            self.count += 1

    @staticmethod
    def _rank(data: List[float], q: float) -> float:
        """Nearest-rank percentile over sorted ``data`` (one formula, used
        by percentile() and summary() alike)."""
        return data[max(0, min(len(data) - 1,
                               int(round(q / 100.0 * (len(data) - 1)))))]

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile (q in [0, 100]) over the window, in
        seconds; None while empty."""
        with self._lock:
            data = sorted(self._samples)
        return self._rank(data, q) if data else None

    def summary(self) -> Dict[str, float]:
        """{count, p50_ms, p99_ms, mean_ms} over the window (empty -> just
        count=0) — the serving `/stats` payload shape."""
        with self._lock:
            data = sorted(self._samples)
        if not data:
            return {"count": 0}
        return {
            "count": self.count,
            "p50_ms": round(self._rank(data, 50.0) * 1e3, 3),
            "p99_ms": round(self._rank(data, 99.0) * 1e3, 3),
            "mean_ms": round(sum(data) / len(data) * 1e3, 3),
        }


def log(msg: str, *, rank: int = 0) -> None:
    """Rank-0-only progress logging, the reference's client0/thread0 idiom."""
    if rank == 0:
        print(msg, flush=True)
