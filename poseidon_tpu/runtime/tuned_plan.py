"""TunedPlan: one measured, persisted artifact for every policy knob.

BENCH_r05 proved that hand-picked policies and HLO-level proxies can invert
on real hardware (NHWC "won" the transpose count yet ran 0.53x on the v5e),
and the per-layer conv-strategy tuner (ops/conv_tune.py, PR 11) proved the
fix for ONE knob: measure short trials, persist the winner, memo-hit on the
next process. This module generalizes that mechanism to the whole policy
surface — Caffe con Troll's cost-based optimizer (arXiv:1504.04343) applied
to the repo's own knobs:

  conv_layout          internal activation layout (the "auto" per-backend
                       table becomes ONE MEASURED ROW of this plan)
  conv_strategy        per-layer conv lowering ("auto" = the PR-11 measured
                       per-layer store, riding this plan's cache dir)
  arena_bucket_mb      flat-arena gradient-collective bucket size
  mesh                 --mesh axis factorization of the available devices
  device_prefetch /    the step pipeline's input-prefetch depth and bounded
  max_in_flight        in-flight dispatch window
  steps_per_dispatch   optimizer steps per compiled dispatch (lax.scan)
  serve_buckets        the serving tier's batch bucket ladder
  remat / batch_size / the measured HBM budget pair (core/remat.py): at
  hbm_budget_gb        the job's own measured peak as the budget, does
                       checkpointing activations buy enough extra batch
                       to win on img/s?

One ``TunedPlan`` JSON per (model, backend, n_devices) lives in the
compile-cache tuned store (``runtime/compile_cache.load_tuned/save_tuned``,
namespace "plan") next to the AOT executables — the same restart economics:
a re-run with the same job config loads the winners instead of re-measuring.
Provenance (device kind, jax version, what was measured, when) is validated
at load time: a plan tuned on different hardware or a different jax refuses
to auto-load, loudly, and the built-in defaults apply.

Resolution precedence is strict and recorded per knob:

    explicit CLI flag  >  persisted TunedPlan  >  built-in default

``train``/``serve``/``bench_serve`` auto-load the matching plan at startup
(runtime/cli.py); the active resolution is published process-wide
(:func:`set_active_resolution`) so ``numeric.resolve_conv_layout``'s "auto"
branch reads the measured row, ``ops/conv_tune.py`` finds the per-layer
store, and the engine writes the provenance (sources + overrides) into
stats.yaml.

Trials are honest wall-clock measurements through the same hygiene the
bench harness uses: every arm warms before timing (first-call compile noise
never decides a winner) and candidates are timed in INTERLEAVED order-
alternating windows with a min-of-k estimator (host-load drift cannot bias
one arm — the ``bench.py pipeline_speedup`` idiom). The search always
includes the built-in default as a candidate and finishes with a composite
default-vs-tuned full-step A/B; a plan that measures slower than the
defaults is never shipped (the losing knobs revert, loudly).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..config import PipelineConfig
from .compile_cache import load_tuned, save_tuned, step_key, tuned_path
from .metrics import log

PLAN_NAMESPACE = "plan"
PLAN_VERSION = 1

# The built-in defaults every knob falls back to when neither a flag nor a
# plan covers it. The pipeline knobs read the PipelineConfig dataclass
# defaults so config.py stays the single source; the rest are the historic
# ad-hoc defaults this module collapses.
_PC = PipelineConfig()
BUILTIN_DEFAULTS: Dict[str, Any] = {
    "conv_layout": "auto",        # numeric.resolve_conv_layout's table
    "conv_strategy": "",          # legacy global conv_s2d policy
    "arena_bucket_mb": 4.0,
    "mesh": "",                   # flat data mesh over all devices
    "device_prefetch": _PC.device_prefetch,
    "max_in_flight": _PC.max_in_flight,
    "steps_per_dispatch": 1,
    "serve_buckets": "1,4,16,64",
    # LLM serving (serving/continuous.py): KV page size, the decode-batch
    # rung ladder, and the prompt-length prefill buckets
    "llm_page_size": 64,
    "llm_decode_rungs": "1,2,4,8",
    "llm_prompt_buckets": "16,64,256",
    "llm_replicas_tp": "",        # "RxT" replica×tp factorization; "" = auto
    # managed DCN delta wire dtype ('' = f32 byte-for-byte; bf16/f16/int8
    # compress with exact error feedback riding the comm residual)
    "wire_dtype": "",
    # measured HBM budget planner (core/remat.py): '' = no remat, 'auto'
    # = checkpoint per the budget knapsack; hbm_budget_gb 0 = no budget;
    # batch_size is the measured largest-admissible batch AT that budget
    # (informational — the prototxt owns the actual batch; 0 = unmeasured)
    "remat": "",
    "hbm_budget_gb": 0.0,
    "batch_size": 0,
}
TRAIN_KNOBS = ("conv_layout", "conv_strategy", "arena_bucket_mb", "mesh",
               "device_prefetch", "max_in_flight", "steps_per_dispatch",
               "wire_dtype", "remat", "hbm_budget_gb")


# --------------------------------------------------------------------------- #
# store: where plans live, how they are keyed, when they refuse to load
# --------------------------------------------------------------------------- #

def store_dir(cache_dir: Optional[str] = None) -> str:
    """The tuned-plan store directory: an explicit argument, else the
    configured compile-cache dir (plans live next to the AOT executables),
    else POSEIDON_TUNED_DIR, else a stable per-user default — so the
    ``tune`` -> ``train`` auto-load round trip works with zero flags."""
    if cache_dir:
        return cache_dir
    from ..config import compile_cache_config
    return (compile_cache_config().cache_dir
            or os.environ.get("POSEIDON_TUNED_DIR", "")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "poseidon_tpu"))


def plan_key(model: str, backend: str, n_devices: int) -> str:
    """Content key for one plan. Device kind and jax version are NOT in the
    key — they live in the provenance and are validated at load, so a
    mismatch is a LOUD refusal instead of a silent store miss."""
    return step_key(kind=PLAN_NAMESPACE, model=model.lower(),
                    backend=backend, n_devices=int(n_devices))


def plan_path(model: str, backend: str, n_devices: int,
              cache_dir: Optional[str] = None) -> str:
    return tuned_path(store_dir(cache_dir), PLAN_NAMESPACE,
                      plan_key(model, backend, n_devices))


def save_plan(doc: Dict, cache_dir: Optional[str] = None) -> Optional[str]:
    return save_tuned(store_dir(cache_dir), PLAN_NAMESPACE, doc["key"], doc)


def load_plan(model: str, backend: Optional[str] = None,
              n_devices: Optional[int] = None,
              cache_dir: Optional[str] = None) -> Optional[Dict]:
    """The persisted plan for (model, backend, n_devices), or None. A plan
    whose provenance names a different device kind or jax version REFUSES
    to load (loudly — the BENCH_r05 lesson is precisely that measured
    winners do not transfer across hardware); any store-level failure is a
    clean miss (compile_cache.load_tuned logs torn entries)."""
    import jax
    backend = backend or jax.default_backend()
    n_devices = jax.device_count() if n_devices is None else n_devices
    doc = load_tuned(store_dir(cache_dir), PLAN_NAMESPACE,
                     plan_key(model, backend, n_devices))
    if doc is None:
        return None
    kind = jax.devices()[0].device_kind
    for fld, want in (("device_kind", kind),
                      ("jax_version", jax.__version__)):
        have = doc.get(fld)
        if have != want:
            log(f"[tuned_plan] REFUSING plan for {model!r}: {fld} "
                f"{have!r} != current {want!r} (tuned winners do not "
                f"transfer across hardware/toolchains — re-run "
                f"`python -m poseidon_tpu tune`); using built-in defaults")
            return None
    return doc


# --------------------------------------------------------------------------- #
# resolution: flag > plan > default, sources + overrides recorded
# --------------------------------------------------------------------------- #

@dataclass
class PlanResolution:
    """Per-knob resolved values with their source ("flag" | "plan" |
    "default"), plus the plan document (if any) and the store it came
    from. ``overridden`` names knobs where an explicit flag shadowed a
    persisted plan value — recorded in the provenance stats line so a
    stats.yaml always says which measured winners were NOT in effect."""

    values: Dict[str, Any] = field(default_factory=dict)
    sources: Dict[str, str] = field(default_factory=dict)
    doc: Optional[Dict] = None
    store: str = ""

    @property
    def overridden(self) -> List[str]:
        knobs = (self.doc or {}).get("knobs", {})
        return [k for k, src in sorted(self.sources.items())
                if src == "flag" and k in knobs
                and knobs[k] != self.values[k]]

    def provenance(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            k: f"{self.values[k]} ({self.sources[k]})"
            for k in sorted(self.values)}
        if self.doc is not None:
            out["plan_key"] = self.doc.get("key")
            out["plan_model"] = self.doc.get("model")
            out["plan_measured_at"] = self.doc.get("measured_at")
            out["plan_device_kind"] = self.doc.get("device_kind")
            out["plan_jax_version"] = self.doc.get("jax_version")
        if self.overridden:
            out["overridden_by_flags"] = ",".join(self.overridden)
        return out

    def describe(self) -> str:
        head = ("plan " + str(self.doc.get("key"))[:12]
                if self.doc is not None else "no plan (defaults)")
        body = " ".join(f"{k}={self.values[k]}[{self.sources[k][0]}]"
                        for k in TRAIN_KNOBS if k in self.values)
        tail = (f" OVERRIDDEN: {','.join(self.overridden)}"
                if self.overridden else "")
        return f"{head}: {body}{tail}"


def resolve(doc: Optional[Dict], explicit: Dict[str, Any],
            knobs: Tuple[str, ...] = TRAIN_KNOBS,
            store: str = "") -> PlanResolution:
    """Fold the three layers into one resolution. ``explicit`` holds only
    the knobs the user actually set (CLI sentinel defaults keep unset
    flags out of it)."""
    res = PlanResolution(doc=doc, store=store)
    plan_knobs = (doc or {}).get("knobs", {})
    for k in knobs:
        if k in explicit and explicit[k] is not None:
            res.values[k], res.sources[k] = explicit[k], "flag"
        elif k in plan_knobs:
            res.values[k], res.sources[k] = plan_knobs[k], "plan"
        else:
            res.values[k], res.sources[k] = BUILTIN_DEFAULTS[k], "default"
    return res


# the process-wide active resolution: set by the CLI after auto-load, read
# by numeric.resolve_conv_layout (the measured "auto" row), conv_tune (the
# per-layer store location) and the engine (stats.yaml provenance section)
_active: Optional[PlanResolution] = None


def set_active_resolution(res: Optional[PlanResolution]) -> None:
    global _active
    _active = res


def active_resolution() -> Optional[PlanResolution]:
    return _active


def active_plan_value(knob: str) -> Optional[Any]:
    """The active resolution's value for ``knob`` IF it came from a
    measured plan (never a flag or default — callers consulting this want
    specifically the measured row)."""
    if _active is None or _active.sources.get(knob) != "plan":
        return None
    return _active.values.get(knob)


def active_store_dir() -> str:
    """Where the active plan was loaded from — ops/conv_tune.py falls back
    here so a plan-applied ``conv_strategy=auto`` memo-hits the per-layer
    winners the tune run persisted, even without --compile_cache_dir.
    Empty unless a plan actually LOADED: a defaults-only resolution must
    not route conv_tune's store at the directory we merely looked in (a
    flagless ``train --conv_strategy auto`` would otherwise start
    persisting winners into the user-level cache as a side effect)."""
    if _active is None or _active.doc is None:
        return ""
    return _active.store


def apply_training_resolution(res: PlanResolution) -> Dict[str, Any]:
    """Install the resolved values into the global policy/config state the
    training path reads (numeric policy for conv_layout/conv_strategy,
    PipelineConfig for the step-pipeline knobs) and publish the resolution.
    Returns the engine/CLI-level knobs the caller passes through
    explicitly: {arena_bucket_mb, mesh, steps_per_dispatch,
    device_prefetch, max_in_flight}. Used by cmd_train AND the parity
    test — applying a plan and passing the equivalent explicit flags must
    build bit-identical training runs."""
    from .. import config
    v = res.values
    config.set_policy(conv_layout=v["conv_layout"])
    if v["conv_strategy"]:
        config.set_policy(conv_strategy=v["conv_strategy"])
    config.set_pipeline_config(device_prefetch=int(v["device_prefetch"]),
                               max_in_flight=int(v["max_in_flight"]))
    # the managed DCN tier reads its wire dtype from ManagedCommConfig
    # (async_tier falls back to it when no explicit flag rode async_cfg);
    # NEVER returned to the caller — the compiled-tier CommConfig takes
    # the flag only, a plan value must not leak into compiled collectives
    config.set_managed_comm_config(wire_dtype=str(v.get("wire_dtype", "")))
    mesh = v["mesh"]
    if mesh and res.sources.get("mesh") == "plan":
        # plans are keyed by n_devices so this should never fire, but a
        # hand-edited/copied plan must degrade loudly, never SystemExit
        # deep in engine construction
        import jax
        from ..config import MeshConfig
        try:
            need = MeshConfig.parse(mesh).n_devices
        except ValueError as e:
            log(f"[tuned_plan] plan mesh {mesh!r} unparseable ({e}); "
                f"using the flat data mesh")
            mesh, res.values["mesh"], res.sources["mesh"] = "", "", "default"
        else:
            if need > jax.device_count():
                log(f"[tuned_plan] plan mesh {mesh!r} needs {need} devices, "
                    f"{jax.device_count()} available; using the flat data "
                    f"mesh")
                mesh, res.values["mesh"], res.sources["mesh"] = \
                    "", "", "default"
    set_active_resolution(res)
    return {"arena_bucket_mb": float(v["arena_bucket_mb"]),
            "mesh": mesh,
            "steps_per_dispatch": int(v["steps_per_dispatch"]),
            "device_prefetch": int(v["device_prefetch"]),
            "max_in_flight": int(v["max_in_flight"]),
            "remat": str(v.get("remat", "")),
            "hbm_budget_gb": float(v.get("hbm_budget_gb", 0.0))}


# --------------------------------------------------------------------------- #
# the measured-trial estimator (shared with ops/conv_tune.py)
# --------------------------------------------------------------------------- #

def interleaved_min_ms(fns: Dict[str, Callable[[], Any]],
                       windows: int = 4, iters: int = 3,
                       warmup: int = 2) -> Dict[str, float]:
    """Honest wall-clock per candidate: warm EVERY candidate ``warmup``
    times first (the first call pays trace+compile, the second can still
    pay one-time runtime work — neither may decide a winner), then time
    ``windows`` interleaved windows of ``iters`` calls each, alternating
    the candidate order per window (under cgroup throttling the first
    runner of a period gets the burst budget), and keep each candidate's
    MIN window — the robust estimator under one-sided noise (a window can
    be slowed by background load, never sped up). Returns {name: ms per
    call}."""
    order = list(fns)
    for name in order:
        for _ in range(max(1, warmup)):
            fns[name]()
    best = {name: float("inf") for name in order}
    for w in range(max(1, windows)):
        seq = order if w % 2 == 0 else list(reversed(order))
        for name in seq:
            t0 = time.perf_counter()
            for _ in range(max(1, iters)):
                fns[name]()
            best[name] = min(best[name],
                             (time.perf_counter() - t0) / max(1, iters))
    return {name: v * 1e3 for name, v in best.items()}


# --------------------------------------------------------------------------- #
# the search harness: `tune` (CLI + bench.py) lands here
# --------------------------------------------------------------------------- #

TUNE_MODELS = ("lenet", "alexnet", "googlenet")

# the engine-loop A/B net for the pipeline knobs (device_prefetch /
# max_in_flight act on the host<->device boundary, so they are measured
# through real Engine.train loops, not a bare compiled step)
_PIPE_NET = """
name: "tune_pipe"
layers { name: "src" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: %d channels: 3 height: 20 width: 20 } }
layers { name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 12 kernel_size: 3
    weight_filler { type: "xavier" } bias_filler { type: "constant" } } }
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "pool1" type: POOLING bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layers { name: "ip1" type: INNER_PRODUCT bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 10
    weight_filler { type: "xavier" } bias_filler { type: "constant" } } }
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label"
  top: "loss" }
"""

# the serving-ladder probe net when no deploy prototxt is supplied: ladder
# economics (pad waste vs compile slots) are shape-generic enough for a
# measured row, and the doc records that the probe was synthetic
_SERVE_NET = """
name: "tune_serve_synthetic"
input: "data"
input_dim: 1 input_dim: 3 input_dim: 24 input_dim: 24
layers { name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 16 kernel_size: 3
    weight_filler { type: "xavier" } } }
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "fc" type: INNER_PRODUCT bottom: "conv1" top: "fc"
  inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
layers { name: "prob" type: SOFTMAX bottom: "fc" top: "prob" }
"""


def search_space(smoke: bool, n_devices: int) -> Dict[str, List]:
    """The candidate sets per knob. Smoke keeps every measured knob at a
    2-point space (tier-1-safe); the full space is what a TPU re-tune
    sweeps. The built-in default is ALWAYS a candidate, so a winner can
    never measure worse than the default it replaces."""
    return {
        "conv_layout": ["NCHW", "NHWC"],
        "conv_strategy": ["", "auto"],
        "arena_bucket_mb": [1.0, 4.0] if smoke else [1.0, 4.0, 16.0],
        "steps_per_dispatch": [1] if smoke else [1, 4],
        "pipeline": ([(0, 1), (2, 2)] if smoke
                     else [(0, 1), (2, 2), (2, 4)]),
        "serve_buckets": (["1,4", "1,2,4"] if smoke
                          else ["1,4,16,64", "1,8,32,64", "1,2,8,32,64"]),
        "mesh": _mesh_candidates(n_devices, smoke),
        # LLM serving (serving/continuous.py): KV page size, decode-batch
        # rung ladder, replica x tp factorization — all measured against a
        # deep-overload burst through the continuous scheduler (the
        # offered-load operating point the bench's goodput curve saturates
        # at). The built-in default is always a candidate.
        "llm_page_size": [16, 64] if smoke else [16, 64, 128],
        "llm_decode_rungs": (["1,2,4,8", "1,4"] if smoke
                             else ["1,2,4,8", "1,4,8", "1,2,4,8,16"]),
        "llm_replicas_tp": _llm_factorizations(n_devices, smoke),
        # managed DCN wire dtype, measured over a throttled loopback link
        # (the f32 default is always a candidate — revert-if-losing)
        "wire_dtype": ["", "bf16"] if smoke else ["", "bf16", "f16", "int8"],
        # the (remat, batch_size) coordinate pair: at a fixed budget (the
        # no-remat default-batch measured peak) find the largest
        # admissible batch per remat policy, race on img/s ('' default
        # always a candidate — revert-if-losing)
        "remat_batch": ["", "auto"],
    }


def _llm_factorizations(n_devices: int, smoke: bool) -> List[str]:
    """Replica x tp candidates ("RxT") for the LLM fleet: all devices to
    replicas (throughput), or half to tp2 (larger models per replica,
    fewer rows in flight). Smoke keeps the single trivial arm (recorded,
    never a silent cap)."""
    if n_devices <= 1 or smoke:
        return ["1x1"]
    cands = [f"{n_devices}x1"]
    if n_devices % 2 == 0:
        cands.append(f"{n_devices // 2}x2")
    return cands


def _mesh_candidates(n_devices: int, smoke: bool) -> List[str]:
    if n_devices <= 1 or smoke:
        # one device has one factorization; smoke skips the (expensive)
        # spmd arms — both cases are recorded as the only candidate, never
        # a silent cap (the trial row says so)
        return [""]
    cands = [""]                      # flat data mesh (the default)
    if n_devices % 2 == 0:
        cands += [f"dp{n_devices // 2},fsdp2", f"dp{n_devices // 2},tp2"]
    return cands


def _model_setup(model: str, smoke: bool):
    """(net_param, source_shapes) for one tune target at a measurement-
    sized PER-DEVICE batch (trials measure RELATIVE knob cost; the tiny
    smoke shapes keep tier-1 honest and fast)."""
    from ..models import zoo
    if model == "lenet":
        batch = 8 if smoke else 64
        return zoo.lenet(with_accuracy=False), \
            {"data": (batch, 1, 28, 28), "label": (batch,)}
    if model == "alexnet":
        batch, image = (4, 67) if smoke else (32, 227)
        return zoo.alexnet(num_classes=1000, with_accuracy=False), \
            {"data": (batch, 3, image, image), "label": (batch,)}
    if model == "googlenet":
        batch = 2 if smoke else 16
        return zoo.googlenet(num_classes=1000, with_accuracy=False), \
            {"data": (batch, 3, 224, 224), "label": (batch,)}
    raise ValueError(f"unknown tune model {model!r}; choose from "
                     f"{TUNE_MODELS}")


def _build_step_arm(net_param, shapes, conv_layout: str, arena_mb: float,
                    scan_steps: int, mesh_spec: str,
                    conv_strategy: str = "", remat: str = "",
                    measure_peak: bool = False):
    """One measured arm: a compiled train step under one knob assignment,
    returned as a zero-arg blocked callable (state threads through a
    holder so successive calls are real successive steps). The callable's
    ``per_call_steps`` attribute normalizes scan arms to per-optimizer-
    step time.

    ``remat="auto"`` checkpoints every eligible layer (the zero-budget
    maximal plan — what the (remat, batch) stage races against the
    stored-activation default); any other non-empty ``remat`` is a
    comma-joined explicit layer list (the Engine ``--remat`` flag
    semantics — bench.py memory's budget-planned arm rides this).
    ``measure_peak=True`` additionally
    AOT-compiles the step and records its real ``memory_analysis()``
    peak as ``run.peak_bytes`` (a second compile — only the remat stage
    pays it)."""
    import jax
    import jax.numpy as jnp

    from .. import config
    from ..core import remat as remat_mod
    from ..core.net import Net
    from ..parallel import (CommConfig, build_train_step, init_train_state,
                            make_mesh)
    from ..proto.messages import SolverParameter

    with config.policy_scope(conv_layout=conv_layout):
        net = Net(net_param, phase="TRAIN", source_shapes=dict(shapes),
                  conv_strategy=conv_strategy or None)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=5e-4)
    comm = CommConfig(param_arena=True, arena_bucket_mb=float(arena_mb))
    nhwc = net.conv_layout == "NHWC"
    in_layout = "NHWC" if nhwc else "NCHW"
    rp = None
    if remat == "auto":
        from .attribution import layer_cost_table
        rp = remat_mod.plan_remat(
            layer_cost_table(net), 0, 0,
            candidates=remat_mod.remat_candidates(net), source="plan")
    elif remat:
        rp = remat_mod.RematPlan(
            layers=tuple(t.strip() for t in remat.split(",") if t.strip()),
            source="flag")
    if mesh_spec:
        from ..config import MeshConfig
        from ..parallel.spmd import ShardingPlan, named_mesh
        mesh_cfg = MeshConfig.parse(mesh_spec)
        mesh = named_mesh(mesh_cfg)
        plan = ShardingPlan.build(net, mesh_cfg, comm)
        ts = build_train_step(net, sp, mesh, comm, plan=plan,
                              input_layout=in_layout, remat_plan=rp)
        n_batch_dev = mesh_cfg.data * mesh_cfg.fsdp
    else:
        ts = build_train_step(net, sp, make_mesh(), comm,
                              scan_steps=scan_steps if scan_steps > 1
                              else None,
                              scan_reuse_batch=True, input_layout=in_layout,
                              remat_plan=rp)
        n_batch_dev = jax.device_count()
    params = net.init(jax.random.PRNGKey(0))
    state = init_train_state(params, comm, jax.device_count())
    # the prototxt batch contract: per-device rows in the net, global rows
    # on the wire (bench.py's _build semantics); NHWC arms feed channels-
    # last directly so the hot path carries zero entry transposes
    rows = int(shapes["data"][0]) * n_batch_dev
    chw = tuple(shapes["data"][1:])
    data_shape = (chw[1], chw[2], chw[0]) if nhwc else chw
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    batch = {
        "data": jax.device_put(
            jax.random.uniform(k1, (rows,) + data_shape, jnp.float32),
            ts.batch_sharding),
        "label": jax.device_put(jax.random.randint(k2, (rows,), 0, 10),
                                ts.batch_sharding),
    }
    jax.block_until_ready(batch["data"])
    holder = {"params": params, "state": state}
    rng = jax.random.PRNGKey(1)

    def run():
        p, s, m = ts.step(holder["params"], holder["state"], batch, rng)
        holder["params"], holder["state"] = p, s
        jax.block_until_ready(m["loss"])

    run.per_call_steps = max(1, ts.scan_steps or 1)  # type: ignore
    run.global_rows = rows  # type: ignore
    if measure_peak:
        compiled = ts.lowerable.lower(params, state, batch, rng).compile()

        # the AOT compile does NOT seed the jit call cache, so timing
        # through ts.step would compile the same program a second time
        # (minutes per arm on the CPU proxy's conv models) — run the AOT
        # executable itself instead
        def run_aot():
            # the raw device_step returns (params, state, metrics, dumps)
            # — ts.step's wrapper strips the tail, the AOT call does not
            out = compiled(holder["params"], holder["state"], batch, rng)
            holder["params"], holder["state"] = out[0], out[1]
            jax.block_until_ready(out[2]["loss"])

        run_aot.per_call_steps = run.per_call_steps  # type: ignore
        run_aot.global_rows = rows  # type: ignore
        run_aot.peak_bytes = remat_mod.measured_peak_bytes(  # type: ignore
            compiled)
        return run_aot
    return run


def _measure_step_knob(net_param, shapes, current: Dict[str, Any],
                       knob: str, candidates: List, windows: int,
                       iters: int) -> Dict[str, float]:
    """Measure one step-level knob's candidates with every other knob held
    at its current best; ms are per OPTIMIZER step."""
    arms: Dict[str, Callable] = {}
    for cand in candidates:
        cfg = dict(current)
        cfg[knob] = cand
        arms[str(cand)] = _build_step_arm(
            net_param, shapes,
            conv_layout=cfg["conv_layout"],
            arena_mb=float(cfg["arena_bucket_mb"]),
            scan_steps=int(cfg["steps_per_dispatch"]),
            mesh_spec=cfg.get("mesh", ""),
            conv_strategy=cfg.get("conv_strategy", ""))
    raw = interleaved_min_ms(arms, windows=windows, iters=iters)
    return {name: round(raw[name] / arms[name].per_call_steps, 4)
            for name in raw}


def _measure_remat_batch(net_param, shapes, current: Dict[str, Any],
                         windows: int, iters: int,
                         max_doublings: int = 3) -> Dict[str, Any]:
    """The (remat, batch_size) coordinate pair at a FIXED byte budget.

    The budget is the no-remat default-batch step's measured
    ``memory_analysis()`` peak — i.e. "the HBM this job config already
    needs". Per remat policy ('' stored activations, 'auto' maximal
    checkpoint) the largest ADMISSIBLE batch is found by doubling from
    the default while the measured peak stays within the budget (at most
    ``max_doublings`` doublings — recorded, never a silent cap); the
    arms then race on img/s through ``interleaved_min_ms``. Remat wins
    only when dropping activations buys enough extra batch to beat the
    default's throughput — the revert-if-losing discipline.

    Returns {"remat", "batch_size", "hbm_budget_gb", "trial"}."""
    def make(policy: str, batch: int, measure_peak: bool):
        s = dict(shapes)
        s["data"] = (batch,) + tuple(shapes["data"][1:])
        s["label"] = (batch,)
        return _build_step_arm(
            net_param, s, current["conv_layout"],
            float(current["arena_bucket_mb"]), 1, "",
            current.get("conv_strategy", ""), remat=policy,
            measure_peak=measure_peak)

    base_batch = int(shapes["data"][0])
    probes: Dict[Tuple[str, int], Any] = {}
    probes[("", base_batch)] = make("", base_batch, True)
    budget = int(probes[("", base_batch)].peak_bytes)
    trial: Dict[str, Any] = {
        "budget_bytes": budget, "base_batch": base_batch,
        "max_doublings": max_doublings, "arms": {}}
    if budget <= 0:
        # no memory API on this backend: nothing to plan against — the
        # default wins by fiat, and the doc says why
        trial["note"] = ("memory_analysis() reported no peak; remat/"
                         "batch not measured on this backend")
        return {"remat": "", "batch_size": 0, "hbm_budget_gb": 0.0,
                "trial": trial}
    best: Dict[str, Tuple[int, Any]] = {}
    for policy in ("", "auto"):
        b, arm = base_batch, probes.get((policy, base_batch))
        if arm is None:
            arm = make(policy, base_batch, True)
        if arm.peak_bytes > budget and policy:  # remat arm at base batch
            # can only be <= the default's peak, but keep the guard honest
            trial["arms"][policy or "default"] = {
                "batch": base_batch, "peak_bytes": int(arm.peak_bytes),
                "admissible": False}
            continue
        for _ in range(max_doublings):
            nxt = make(policy, b * 2, True)
            if nxt.peak_bytes > budget:
                break
            b, arm = b * 2, nxt
        best[policy] = (b, arm)
        trial["arms"][policy or "default"] = {
            "batch": b, "peak_bytes": int(arm.peak_bytes),
            "admissible": True}
    fns = {(p or "default"): arm for p, (b, arm) in best.items()}
    raw = interleaved_min_ms(fns, windows=windows, iters=iters)
    imgs = {}
    for p, (b, arm) in best.items():
        name = p or "default"
        ms = raw[name] / arm.per_call_steps
        imgs[name] = arm.global_rows / max(ms, 1e-9) * 1e3  # img/s
        trial["arms"][name].update(step_ms=round(ms, 4),
                                   img_per_s=round(imgs[name], 1))
    winner = max(imgs, key=imgs.get)
    default_ips = imgs.get("default", 0.0)
    if winner != "default" and imgs[winner] <= default_ips:
        winner = "default"
    trial["winner"] = winner
    trial["speedup"] = round(imgs[winner] / max(default_ips, 1e-9), 4)
    policy = "" if winner == "default" else winner
    # the budget knob ships only with a winning remat row: a default win
    # must not make every later train run re-pay the measuring compile
    # for an identity plan (the trial row keeps budget_bytes either way)
    return {"remat": policy,
            "batch_size": int(best[policy][0]) if policy in best
            else base_batch,
            "hbm_budget_gb": (round(budget / 2**30, 6) if policy
                              else 0.0),
            "trial": trial}


def _measure_pipeline_knob(candidates: List[Tuple[int, int]], windows: int,
                           iters: int) -> Dict[str, float]:
    """Engine-loop wall per iteration for (device_prefetch, max_in_flight)
    candidates, through real Engine.train loops over a small MEMORY_DATA
    net (the knobs act on host blocking, which a bare compiled step cannot
    see). Interleaved windows, min per arm."""
    import tempfile

    import numpy as np

    from ..proto.messages import SolverParameter, load_net_from_string
    from .engine import Engine

    import shutil

    rs = np.random.RandomState(0)
    md = {"data": rs.randn(256, 3, 20, 20).astype(np.float32),
          "label": rs.randint(0, 10, 256)}
    net_param = load_net_from_string(_PIPE_NET % 8)
    engines: Dict[str, Any] = {}
    scratch = tempfile.mkdtemp(prefix="tune_pipe_")
    try:
        for pf, mif in candidates:
            sp = SolverParameter(train_net_param=net_param, base_lr=0.01,
                                 lr_policy="fixed", momentum=0.9, display=0,
                                 max_iter=0, random_seed=3)
            out_dir = os.path.join(scratch, f"{pf}_{mif}")
            os.makedirs(out_dir, exist_ok=True)
            eng = Engine(sp, memory_data=md, output_dir=out_dir,
                         device_prefetch=pf, max_in_flight=mif)
            eng._write_artifacts = lambda: None   # disk noise off the clock
            engines[f"{pf},{mif}"] = eng
        done = {name: 0 for name in engines}
        for name, eng in engines.items():        # warm: compile + fill
            eng.train(max_iter=2)
            done[name] = 2
        best = {name: float("inf") for name in engines}
        order = list(engines)
        for w in range(max(1, windows)):
            seq = order if w % 2 == 0 else list(reversed(order))
            for name in seq:
                eng = engines[name]
                t0 = time.perf_counter()
                eng.train(max_iter=done[name] + iters)
                best[name] = min(best[name],
                                 (time.perf_counter() - t0) / iters)
                done[name] += iters
        return {name: round(v * 1e3, 4) for name, v in best.items()}
    finally:
        for eng in engines.values():
            eng.close()
        shutil.rmtree(scratch, ignore_errors=True)


def _measure_serve_knob(candidates: List[str], windows: int, iters: int,
                        deploy: str = "") -> Dict[str, float]:
    """Mean request wall (ms) per bucket ladder: every ladder serves the
    same request-size sweep (1..max rows) through a warmed
    BucketedExecutor. Uses the deploy prototxt when given, else the
    synthetic probe net."""
    import numpy as np

    import jax

    from ..core.net import Net
    from ..proto.messages import load_net, load_net_from_string
    from ..serving.executor import BucketedExecutor, parse_buckets

    net_param = (load_net(deploy) if deploy
                 else load_net_from_string(_SERVE_NET))
    net = Net(net_param, "TEST")
    params = net.init(jax.random.PRNGKey(0))
    name = net.input_names[0]
    row_shape = tuple(net.blob_shapes[name][1:])
    max_rows = max(parse_buckets(spec)[-1] for spec in candidates)
    frames = np.random.RandomState(0).randn(
        max_rows, *row_shape).astype(np.float32)
    arms: Dict[str, Callable] = {}
    n_requests: Dict[str, int] = {}
    for spec in candidates:
        ex = BucketedExecutor(net, params, buckets=parse_buckets(spec))
        sizes = list(range(1, ex.max_batch + 1))
        n_requests[spec] = len(sizes)

        def run(ex=ex, sizes=sizes):
            for n in sizes:
                ex.infer({name: frames[:n]})

        arms[spec] = run
    raw = interleaved_min_ms(arms, windows=windows, iters=iters, warmup=1)
    return {spec: round(raw[spec] / n_requests[spec], 4) for spec in raw}


def _measure_llm_knob(arm_specs: Dict[str, Tuple[int, str, int, int]],
                      windows: int, iters: int) -> Dict[str, float]:
    """ms-per-generated-token at DEEP OVERLOAD for each LLM serving arm.

    ``arm_specs``: name -> (page_size, decode_rungs, replicas, tp). Every
    arm serves the same burst of concurrent generate requests (more
    requests than any rung holds, i.e. the saturated end of the offered-
    load curve — where the knob choice actually matters) through real
    :class:`ContinuousScheduler` instances over a tiny probe transformer;
    interleaved windows + min-of-k as everywhere else. tp > 1 arms build
    a (1,1,tp) named mesh per replica."""
    import threading

    import jax
    import numpy as np

    from ..config import MeshConfig
    from ..models.transformer import TransformerConfig, init_params
    from ..serving.continuous import GenerateExecutor, parse_rungs

    cfg = TransformerConfig(vocab_size=128, d_model=32, n_heads=4,
                            n_layers=2, max_seq=256)
    params = init_params(cfg, jax.random.PRNGKey(0))
    p_len, max_new, n_req = 8, 8, 12
    prompts = np.random.RandomState(0).randint(
        0, cfg.vocab_size, size=(n_req, p_len)).astype(np.int32)
    arms: Dict[str, Callable] = {}
    all_scheds = []
    for aname, (page, rungs, reps, tp) in arm_specs.items():
        scheds = []
        for _ in range(int(reps)):
            mesh_cfg = (MeshConfig(data=1, fsdp=1, tp=int(tp))
                        if int(tp) > 1 else None)
            ex = GenerateExecutor(
                cfg, params, page_size=int(page),
                decode_rungs=parse_rungs(rungs), prompt_buckets=(p_len,),
                max_seq_len=cfg.max_seq, default_max_new=max_new,
                mesh_cfg=mesh_cfg)
            scheds.append(ex.make_batcher(max_queue=n_req))
        all_scheds.extend(scheds)

        def run(scheds=scheds):
            errs: List[BaseException] = []

            def worker(i):
                try:
                    scheds[i % len(scheds)].submit(
                        {"prompt": prompts[i], "max_new": max_new},
                        timeout_s=120.0)
                except BaseException as e:  # noqa: BLE001 — surface below
                    errs.append(e)

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(n_req)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            if errs:
                raise errs[0]

        arms[aname] = run
    try:
        raw = interleaved_min_ms(arms, windows=windows, iters=iters,
                                 warmup=1)
    finally:
        for s in all_scheds:
            s.close(drain=False, timeout_s=5.0)
    per_tok = n_req * max_new
    return {name: round(raw[name] / per_tok, 4) for name in raw}


def _measure_wire_knob(candidates: List[str], windows: int, iters: int,
                       link_mbps: float = 8.0, side: int = 96,
                       clocks: int = 4, staleness: int = 0
                       ) -> Dict[str, float]:
    """Wall time of a fixed push/gate/refresh cadence per wire dtype over
    a THROTTLED loopback link (FaultProxy token bucket) — the operating
    point where wire compression pays its encode cost back. Each arm
    drives its own ParamService through its own throttled proxy with an
    :class:`AsyncSSPClient` configured for that dtype; interleaved
    windows + min-of-k as everywhere else. The sync point is the SERVICE
    side (poll the applied clock until every push landed): push() is
    asynchronous and a 1-worker gate never waits on its own clock, so
    only server-side apply bounds the throttled uplink transfer. The ''
    (f32, byte-for-byte) default is always a candidate, so a winner can
    never measure worse than the exact path it replaces."""
    import numpy as np

    from ..parallel.async_ssp import AsyncSSPClient, ParamService
    from .faults import FaultProxy, FaultRule

    rate_bps = link_mbps * 1e6 / 8.0
    params = {"fc": {"w": np.zeros((side, side), np.float32)}}
    arms: Dict[str, Callable] = {}
    closers = []
    for wd in candidates:
        svc = ParamService(params, n_workers=1)
        proxy = FaultProxy(("127.0.0.1", svc.port))
        # burst far below one frame, so transfer time tracks frame bytes
        proxy.add_rule(FaultRule(action="throttle", rate_bps=rate_bps,
                                 burst_bytes=8192))
        # no bandwidth budget: every push is a FULL flush (still wire-
        # compressed), so the arm measures the dtype's byte savings over
        # the throttled link, not the budget scheduler's deferral policy
        cli = AsyncSSPClient(0, proxy.addr, staleness, n_workers=1,
                             wire_dtype=wd)
        closers.append((cli, proxy, svc))
        rng = np.random.RandomState(11)
        state = {"clock": 0}

        def run(cli=cli, svc=svc, rng=rng, state=state):
            for _ in range(clocks):
                state["clock"] += 1
                cli.push({"fc": {"w": rng.randn(side, side)
                                 .astype(np.float32) * 1e-3}})
                cli.gate(state["clock"])
            deadline = time.monotonic() + 60.0
            while svc.clocks.get(0, -1) < state["clock"] - 1:
                if time.monotonic() > deadline:
                    raise TimeoutError("wire-knob arm: pushes not applied")
                time.sleep(0.001)

        arms[wd or "f32"] = run
    try:
        return interleaved_min_ms(arms, windows=windows, iters=iters,
                                  warmup=1)
    finally:
        for cli, proxy, svc in closers:
            cli.close()
            proxy.close()
            svc.close()


def _conv_strategy_rows(net_param, shapes, conv_layout: str,
                        cache_dir: str) -> Dict[str, Dict]:
    """Run the PR-11 per-layer conv tuner for this model (persisting the
    winners into THIS plan's store so a plan-applied conv_strategy="auto"
    memo-hits) and return the per-layer decision docs."""
    from .. import config
    from ..core.net import Net
    from ..ops import conv_tune

    saved = config.compile_cache_config().cache_dir
    config.set_compile_cache_config(cache_dir=cache_dir)
    try:
        with config.policy_scope(conv_layout=conv_layout):
            net = Net(net_param, phase="TRAIN", source_shapes=dict(shapes),
                      conv_strategy="auto")
        rows: Dict[str, Dict] = {}
        for layer in net.layers:
            if layer.TYPE != "CONVOLUTION":
                continue
            n, c, h, w = net.blob_shapes[layer.lp.bottom[0]]
            doc = conv_tune.resolve(       # memo hit: Net already measured
                layer.name, c, h, w, layer.kernel, layer.stride, layer.pad,
                layer.group, layer.params[0].shape[0], layer.run_layout, n,
                cache_dir=cache_dir)
            rows[layer.name] = {"winner": doc["winner"],
                                "source": doc.get("source"),
                                "timings_ms": doc.get("timings_ms", {})}
        return rows
    finally:
        config.set_compile_cache_config(cache_dir=saved)


def _builtin_layout(backend: str) -> str:
    """The pre-plan hardcoded per-backend row — the default arm every
    conv_layout trial measures against."""
    from ..numeric import resolve_conv_layout
    return resolve_conv_layout("auto", backend=backend, consult_plan=False)


def run_tune(model: str, *, smoke: bool = False, force: bool = False,
             cache_dir: Optional[str] = None, deploy: str = "",
             windows: Optional[int] = None, iters: Optional[int] = None,
             net_param=None, source_shapes=None,
             knobs: Optional[List[str]] = None) -> Dict[str, Any]:
    """The tune search: short measured trials over the policy space, one
    persisted TunedPlan with provenance. Returns ``{"doc", "source",
    "path", "store"}`` where source is "persisted" (memo-hit: a valid plan
    for this exact (model, backend, device kind, n_devices, jax version)
    already exists — re-measurement skipped) or "measured".

    ``net_param``/``source_shapes`` let tests tune a programmatic net under
    ``model`` as the plan name; ``knobs`` restricts the measured subset
    (restrictions are RECORDED in the doc's ``skipped`` map — never a
    silent cap)."""
    import jax

    backend = jax.default_backend()
    kind = jax.devices()[0].device_kind
    n_devices = jax.device_count()
    store = store_dir(cache_dir)
    key = plan_key(model, backend, n_devices)

    if not force:
        doc = load_plan(model, backend, n_devices, cache_dir=store)
        if doc is not None:
            log(f"[tune] {model}/{backend}: plan {key[:12]} already "
                f"persisted (measured {doc.get('measured_at')}); "
                f"memo-hit, skipping re-measurement (--force re-tunes)")
            return {"doc": doc, "source": "persisted", "store": store,
                    "path": tuned_path(store, PLAN_NAMESPACE, key)}

    t_start = time.perf_counter()
    if net_param is None:
        net_param, source_shapes = _model_setup(model, smoke)
    windows = windows if windows is not None else (2 if smoke else 4)
    iters = iters if iters is not None else (2 if smoke else 4)
    space = search_space(smoke, n_devices)
    wanted = list(knobs) if knobs else list(space)
    skipped = {k: "restricted by knobs argument"
               for k in space if k not in wanted}
    trials: Dict[str, Dict] = {}
    current: Dict[str, Any] = {
        "conv_layout": _builtin_layout(backend),
        "conv_strategy": "",
        "arena_bucket_mb": BUILTIN_DEFAULTS["arena_bucket_mb"],
        "steps_per_dispatch": BUILTIN_DEFAULTS["steps_per_dispatch"],
        "mesh": "",
    }
    default_cfg = dict(current)

    def note(knob, cands, timings, winner, source):
        trials[knob] = {"candidates": [str(c) for c in cands],
                        "timings_ms": timings, "winner": str(winner),
                        "source": source}
        ranked = ", ".join(f"{n}={timings[n]}ms"
                           for n in sorted(timings, key=timings.get))
        log(f"[tune] {model}.{knob}: -> {winner} [{source}]"
            + (f" ({ranked})" if ranked else ""))

    # ---- step-level knobs, greedy coordinate order ---------------------- #
    for knob, cands in (("conv_layout", space["conv_layout"]),
                        ("arena_bucket_mb", space["arena_bucket_mb"]),
                        ("steps_per_dispatch",
                         space["steps_per_dispatch"]),
                        ("mesh", space["mesh"])):
        if knob in skipped:
            continue
        if len(cands) == 1:
            current[knob] = cands[0]
            note(knob, cands, {}, cands[0],
                 "only-candidate" + (" (smoke skips the spmd arms)"
                                     if knob == "mesh" and n_devices > 1
                                     else ""))
            continue
        timings = _measure_step_knob(net_param, source_shapes, current,
                                     knob, cands, windows, iters)
        winner_s = min(timings, key=timings.get)
        current[knob] = next(c for c in cands if str(c) == winner_s)
        note(knob, cands, timings, current[knob], "measured")

    # ---- per-layer conv strategy (the PR-11 tuner, one plan row) -------- #
    if "conv_strategy" not in skipped:
        if any(lp.canonical_type() == "CONVOLUTION"
               for lp in net_param.layers):
            rows = _conv_strategy_rows(net_param, source_shapes,
                                       current["conv_layout"], store)
            current["conv_strategy"] = "auto"
            trials["conv_strategy"] = {
                "candidates": ["", "auto"], "winner": "auto",
                "source": "measured-per-layer", "per_layer": rows}
            log(f"[tune] {model}.conv_strategy: -> auto (per-layer: "
                + ", ".join(f"{k}={v['winner']}" for k, v in rows.items())
                + ")")
        else:
            skipped["conv_strategy"] = "model has no conv layers"

    # ---- composite default-vs-tuned full-step A/B ----------------------- #
    if any(current[k] != default_cfg[k] for k in default_cfg):
        from .. import config
        saved_cc = config.compile_cache_config().cache_dir
        if current["conv_strategy"]:
            # the tuned arm's Net(conv_strategy="auto") must memo-hit the
            # winners persisted above, not re-measure inside the A/B
            config.set_compile_cache_config(cache_dir=store)
        try:
            arms = {
                "default": _build_step_arm(
                    net_param, source_shapes, default_cfg["conv_layout"],
                    float(default_cfg["arena_bucket_mb"]),
                    int(default_cfg["steps_per_dispatch"]),
                    default_cfg["mesh"], default_cfg["conv_strategy"]),
                "tuned": _build_step_arm(
                    net_param, source_shapes, current["conv_layout"],
                    float(current["arena_bucket_mb"]),
                    int(current["steps_per_dispatch"]),
                    current["mesh"], current["conv_strategy"]),
            }
        finally:
            config.set_compile_cache_config(cache_dir=saved_cc)
        raw = interleaved_min_ms(arms, windows=max(windows, 3), iters=iters)
        d_ms = raw["default"] / arms["default"].per_call_steps
        t_ms = raw["tuned"] / arms["tuned"].per_call_steps
        ab = {"default_step_ms": round(d_ms, 4),
              "tuned_step_ms": round(t_ms, 4),
              "speedup": round(d_ms / max(t_ms, 1e-9), 4),
              "reverted": False}
        if ab["speedup"] < 1.0:
            # a cost-based optimizer never ships a plan it measured to be
            # slower than the defaults: revert the step knobs, keep the
            # losing measurement on record
            log(f"[tune] {model}: composite tuned arm measured "
                f"{ab['speedup']}x vs defaults — REVERTING step knobs to "
                f"built-in defaults (per-knob wins did not compose)")
            ab.update(raw_speedup=ab["speedup"], reverted=True, speedup=1.0)
            current.update(default_cfg)
    else:
        ab = {"speedup": 1.0,
              "note": "every measured winner equals the built-in default; "
                      "the arms are the same program"}

    # ---- engine-loop pipeline knobs ------------------------------------- #
    pf = BUILTIN_DEFAULTS["device_prefetch"]
    mif = BUILTIN_DEFAULTS["max_in_flight"]
    if "pipeline" not in skipped:
        timings = _measure_pipeline_knob(space["pipeline"], windows, iters)
        winner_s = min(timings, key=timings.get)
        pf, mif = (int(tok) for tok in winner_s.split(","))
        note("pipeline", space["pipeline"], timings, winner_s, "measured")

    # ---- serving bucket ladder ------------------------------------------ #
    serve_buckets = BUILTIN_DEFAULTS["serve_buckets"]
    if "serve_buckets" not in skipped:
        timings = _measure_serve_knob(space["serve_buckets"], windows,
                                      iters, deploy=deploy)
        serve_buckets = min(timings, key=timings.get)
        note("serve_buckets", space["serve_buckets"], timings,
             serve_buckets,
             "measured" + ("" if deploy else " (synthetic probe net)"))

    # ---- managed DCN wire dtype ----------------------------------------- #
    wire_dtype = str(BUILTIN_DEFAULTS["wire_dtype"])
    if "wire_dtype" not in skipped:
        cands = space["wire_dtype"]
        timings = _measure_wire_knob(cands, windows, iters)
        winner_s = min(timings, key=timings.get)
        wire_dtype = next(c for c in cands if (c or "f32") == winner_s)
        note("wire_dtype", [c or "f32" for c in cands], timings,
             wire_dtype or "f32",
             "measured (throttled loopback; f32 default always a "
             "candidate)")

    # ---- measured HBM budget: the (remat, batch_size) pair --------------- #
    # at the job config's own measured peak as the budget, does dropping
    # activations buy enough extra batch to win on img/s? ('' stored-
    # activation default always a candidate — revert-if-losing)
    remat = str(BUILTIN_DEFAULTS["remat"])
    tuned_batch = int(BUILTIN_DEFAULTS["batch_size"])
    hbm_gb = float(BUILTIN_DEFAULTS["hbm_budget_gb"])
    if "remat_batch" not in skipped:
        rb = _measure_remat_batch(net_param, source_shapes, current,
                                  windows, iters)
        remat, tuned_batch = rb["remat"], rb["batch_size"]
        hbm_gb = rb["hbm_budget_gb"]
        arms = rb["trial"].get("arms", {})
        note("remat_batch", list(arms),
             {n: a.get("step_ms", 0.0) for n, a in arms.items()
              if "step_ms" in a},
             f"{remat or 'default'}@batch{tuned_batch or '-'}",
             "measured (img/s at fixed measured-peak budget)")
        trials["remat_batch"].update(rb["trial"])  # the full per-arm rows

    # ---- LLM serving: page size, rung ladder, replica x tp --------------- #
    # greedy coordinate descent at the deep-overload operating point (the
    # saturated end of the offered-load curve bench.py serving_llm sweeps);
    # each later knob is measured under the earlier winners
    llm_page = int(BUILTIN_DEFAULTS["llm_page_size"])
    llm_rungs = str(BUILTIN_DEFAULTS["llm_decode_rungs"])
    llm_rt = str(BUILTIN_DEFAULTS["llm_replicas_tp"])
    if "llm_page_size" not in skipped:
        cands = space["llm_page_size"]
        timings = _measure_llm_knob(
            {str(p): (p, llm_rungs, 1, 1) for p in cands}, windows, iters)
        llm_page = int(min(timings, key=timings.get))
        note("llm_page_size", cands, timings, llm_page, "measured")
    if "llm_decode_rungs" not in skipped:
        cands = space["llm_decode_rungs"]
        timings = _measure_llm_knob(
            {r: (llm_page, r, 1, 1) for r in cands}, windows, iters)
        llm_rungs = min(timings, key=timings.get)
        note("llm_decode_rungs", cands, timings, llm_rungs, "measured")
    if "llm_replicas_tp" not in skipped:
        cands = space["llm_replicas_tp"]
        if len(cands) == 1:
            llm_rt = cands[0]
            note("llm_replicas_tp", cands, {}, llm_rt,
                 "only-candidate" + (" (smoke skips the fleet arms)"
                                     if smoke and n_devices > 1 else ""))
        else:
            specs = {}
            for c in cands:
                reps, tp = (int(t) for t in c.split("x"))
                specs[c] = (llm_page, llm_rungs, reps, tp)
            timings = _measure_llm_knob(specs, windows, iters)
            llm_rt = min(timings, key=timings.get)
            note("llm_replicas_tp", cands, timings, llm_rt, "measured")

    search_cost_s = round(time.perf_counter() - t_start, 2)
    doc = {
        "version": PLAN_VERSION,
        "model": model.lower(),
        "backend": backend,
        "device_kind": kind,
        "jax_version": jax.__version__,
        "n_devices": n_devices,
        "key": key,
        "smoke": smoke,
        "knobs": {
            "conv_layout": current["conv_layout"],
            "conv_strategy": current["conv_strategy"],
            "arena_bucket_mb": float(current["arena_bucket_mb"]),
            "steps_per_dispatch": int(current["steps_per_dispatch"]),
            "mesh": current["mesh"],
            "device_prefetch": int(pf),
            "max_in_flight": int(mif),
            "serve_buckets": serve_buckets,
            "llm_page_size": llm_page,
            "llm_decode_rungs": llm_rungs,
            # prompt buckets ride the defaults (prompt-length DISTRIBUTION
            # is workload data the probe net cannot stand in for)
            "llm_prompt_buckets": str(BUILTIN_DEFAULTS["llm_prompt_buckets"]),
            "llm_replicas_tp": llm_rt,
            "wire_dtype": wire_dtype,
            "remat": remat,
            "batch_size": tuned_batch,
            "hbm_budget_gb": hbm_gb,
        },
        "trials": trials,
        "ab": ab,
        "search_space": {k: [str(c) for c in v] for k, v in space.items()},
        "skipped": skipped,
        "search_cost_s": search_cost_s,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    path = save_plan(doc, cache_dir=store)
    log(f"[tune] {model}/{backend}/{kind}: plan {key[:12]} persisted to "
        f"{path} ({search_cost_s}s search"
        + (f", skipped: {skipped}" if skipped else "") + ")")
    return {"doc": doc, "source": "measured", "store": store, "path": path}
