"""Engine: solver-driven training orchestration (CaffeEngine + Solver::Solve).

Mirrors the reference's control flow (caffe_engine.cpp:55-293,
solver.cpp:246-402) on top of the compiled SPMD step:

- resolve train/test nets from a SolverParameter (file or inline, shared-net
  phase filtering like Net::FilterNet)
- data pipelines per data layer, sharded per host, prefetching in background
- the hot loop: one pjit-compiled step per iteration (forward + backward +
  per-layer gradient collectives + update), with display / test / snapshot
  cadence from the solver prototxt
- metrics aggregated across the mesh inside the step (the net-output-PS-table
  analog) and flushed to CSV; stats YAML per run.

Batch-size semantics: the prototxt batch_size is PER-DEVICE (the reference's
per-worker meaning); the global batch is batch_size * num_devices. With the
default "mean" gradient reduction this behaves like single-worker Caffe at the
global batch size.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.net import Net
from ..data.pipeline import (BatchPipeline, DevicePrefetcher,
                             build_phase_pipelines)
from ..data.workload import Shard
from ..parallel import (CommConfig, build_eval_step, build_ssp_train_step,
                        build_train_step, init_ssp_state, init_train_state,
                        make_mesh)
from ..parallel.trainer import TrainStep, comm_error_groups, stack_batches
from ..proto.messages import NetParameter, SolverParameter, load_net
from ..solvers.updates import learning_rate
from .checkpoint import (AsyncSnapshotWriter, latest_snapshot,
                         load_caffemodel, restore, snapshot, sweep_stale_tmp)
from .metrics import (AsyncScalarFetcher, MetricsServer, MetricsTable,
                      StatsRegistry, log)
from .spans import recorder as span_recorder


class TrainingDivergedError(RuntimeError):
    """Raised when a watched training metric (loss) goes non-finite.

    Detection rides the async metrics drain (AsyncScalarFetcher), so the
    loop learns of the divergence at most ``max_in_flight`` dispatches
    after the step that produced it; ``iteration`` rewinds the report to
    the step whose metrics actually diverged."""

    def __init__(self, iteration: int, key: str, value: float):
        self.iteration = iteration
        self.key = key
        self.value = value
        super().__init__(
            f"training diverged: {key} = {value} at iteration {iteration} "
            f"(detected asynchronously, within the in-flight window)")


def resolve_nets(sp: SolverParameter):
    """Train NetParameter + list of test NetParameters, per the reference's
    precedence: train_net_param, train_net, net_param, net (solver.cpp)."""
    train: Optional[NetParameter] = None
    tests: List[NetParameter] = []
    if sp.train_net_param is not None:
        train = sp.train_net_param
    elif sp.train_net:
        train = load_net(sp.train_net)
    elif sp.net_param is not None:
        train = sp.net_param
    elif sp.net:
        train = load_net(sp.net)
    else:
        raise ValueError("solver specifies no train net")

    tests.extend(sp.test_net_param)
    for path in sp.test_net:
        tests.append(load_net(path))
    if not tests and sp.test_iter:
        # shared-net pattern: same NetParameter filtered by TEST phase
        tests.append(train)
    return train, tests


class Engine:
    def __init__(
        self,
        sp: SolverParameter,
        comm: Optional[CommConfig] = None,
        mesh=None,
        mesh_cfg=None,
        memory_data: Optional[Dict[str, np.ndarray]] = None,
        output_dir: str = ".",
        staleness: int = 0,
        sfb_auto: bool = False,
        steps_per_dispatch: int = 1,
        device_transform: bool = False,
        async_ssp: Optional[Dict] = None,
        device_prefetch: Optional[int] = None,
        max_in_flight: Optional[int] = None,
        async_snapshot: Optional[bool] = None,
        trace_out: Optional[str] = None,
        metrics_port: Optional[int] = None,
        hbm_budget_gb: Optional[float] = None,
        remat: Optional[str] = None,
    ):
        self.sp = sp
        # step-pipeline knobs: explicit args win, else the global policy
        # (config.PipelineConfig; CLI flags land there or here directly)
        from ..config import pipeline_config
        _pc = pipeline_config()
        self.device_prefetch = int(_pc.device_prefetch
                                   if device_prefetch is None
                                   else device_prefetch)
        self.max_in_flight = max(1, int(_pc.max_in_flight
                                        if max_in_flight is None
                                        else max_in_flight))
        self.async_snapshot = bool(_pc.async_snapshot
                                   if async_snapshot is None
                                   else async_snapshot)
        # named SPMD mesh (--mesh dp2,fsdp2,tp1 -> config.MeshConfig):
        # the sharding planner (parallel/spmd.py) computes the per-layer
        # plan below, once the train net exists
        self.mesh_cfg = mesh_cfg
        self.plan = None
        if mesh_cfg is not None:
            # honored even when inactive (fsdp=tp=1): '--mesh dp2' means
            # TWO devices, not a silent fall-through to all of them
            if mesh is not None:
                raise ValueError("pass mesh or mesh_cfg, not both")
            from ..parallel.spmd import named_mesh
            mesh = named_mesh(mesh_cfg)
        self.mesh = mesh or make_mesh()
        self.n_dev = int(np.prod(list(self.mesh.shape.values())))
        self.comm = comm or CommConfig()
        if self.plan is None and mesh_cfg is not None and mesh_cfg.active \
                and self.comm.dcn_axis is not None:
            raise ValueError("--mesh and --dcn_slices do not compose")
        self.staleness = staleness
        self.output_dir = output_dir
        self.stats = StatsRegistry()
        # TunedPlan provenance (runtime/tuned_plan.py): when the CLI
        # resolved a plan for this run, stats.yaml carries every knob's
        # value + source (flag/plan/default) and which measured winners an
        # explicit flag overrode — a stats artifact always says what
        # policy was in effect and why
        from .tuned_plan import active_resolution
        self._plan_resolution = active_resolution()
        if self._plan_resolution is not None:
            self.stats.set_section("tuned_plan",
                                   self._plan_resolution.provenance())
        self.rank = jax.process_index()
        self.world = jax.process_count()
        # --- telemetry spine ------------------------------------------- #
        # --trace_out enables the process-wide span recorder (dispatch /
        # hard-sync / snapshot / prefetch-stall spans, plus whatever the
        # async tier records) and dumps a Chrome trace-event JSON at every
        # display boundary and at exit. (--metrics_port is wired up BELOW,
        # after the async tier resolves this process's real rank.)
        self._trace_out: Optional[str] = None
        self._owns_span_recorder = False
        if trace_out:
            self._trace_out = (trace_out if os.path.isabs(trace_out)
                               else os.path.join(output_dir, trace_out))
            self._owns_span_recorder = not span_recorder.enabled
            # fresh ownership = fresh timeline: a previous engine's spans
            # (the recorder is process-global) must not ghost-prefix this
            # run's dump
            if self._owns_span_recorder:
                span_recorder.clear()
            span_recorder.enable()
        self._metrics_server: Optional[MetricsServer] = None
        self.metrics_port: Optional[int] = None
        self._metrics_port_arg = metrics_port
        # wait-free async-SSP process tier (runtime/async_tier.py): the
        # processes are INDEPENDENT jax runtimes (no jax.distributed world),
        # so rank/world come from the launcher env, the local mesh is this
        # process's own devices, and the only cross-process exchange is the
        # tier's parameter service
        self._async_cfg = async_ssp
        self._async_tier = None
        if async_ssp is not None:
            from .async_tier import env_world
            self.rank, self.world, _ = env_world()
        # --metrics_port: read-only HTTP endpoint for the stats registry
        # (text key=value, curl-able mid-run). Created only now that the
        # async tier has resolved the REAL rank: a fixed port is bound by
        # rank 0 alone — every worker of a multi-process job gets the same
        # CLI args, and N processes racing one port is EADDRINUSE, not
        # telemetry. Port 0 (ephemeral) binds on every rank.
        if self._metrics_port_arg is not None and \
                self._metrics_port_arg >= 0:
            if self._metrics_port_arg == 0 or self.rank == 0:
                try:
                    self._metrics_server = MetricsServer(
                        self.stats, port=self._metrics_port_arg)
                except OSError as e:
                    # an optional read-only endpoint must never abort a
                    # training run (a stale daemon holding the port is
                    # the operator's most likely EADDRINUSE)
                    log(f"WARNING: --metrics_port "
                        f"{self._metrics_port_arg} unavailable ({e}); "
                        f"training continues without the endpoint",
                        rank=self.rank)
                else:
                    self.metrics_port = self._metrics_server.port
                    # printed from EVERY rank that bound a server (the
                    # ADMITTED-line idiom): an ephemeral port nobody
                    # logged is an endpoint nobody can curl
                    log(f"metrics endpoint (rank {self.rank}): "
                        f"http://127.0.0.1:{self.metrics_port}/ "
                        f"(text key=value)")
        self.memory_data = memory_data
        # data assignment: launch-time (rank, world) for the fixed-world
        # tiers; the async tier re-keys it by the CURRENT member list via
        # reshard_data (an elastic joiner's rank sits OUTSIDE the launch
        # world, so it builds with the whole-range placeholder and the
        # tier reshards it at join, before the first batch is consumed)
        self._data_shard = (Shard(self.rank, self.world)
                            if self.rank < self.world else Shard(0, 1))
        # uint8 ingest + on-device (x - mean) * scale (the TPU-native split
        # of DataTransformer): train pipelines ship quarter-width bytes and
        # the normalization fuses into the compiled step (sync and SSP).
        self._device_transform = device_transform

        if self.comm.server_logic != "inc" and staleness == 0:
            log(f"WARNING: --server_logic {self.comm.server_logic} requires "
                f"--staleness > 0 (there is no server in the synchronous "
                f"step); training plain sync SGD", rank=self.rank)

        # iter_size (V2-prototxt gradient accumulation; the 2015 reference
        # predates it): K micro-batches' gradients accumulate inside the
        # compiled step before one update — batch_size B at iter_size K is
        # numerically equivalent to batch_size B*K (trainer.py, tested)
        self.iter_size = max(1, int(sp.iter_size))
        if self.iter_size > 1 and staleness > 0:
            log("WARNING: iter_size > 1 ignored under SSP staleness "
                "(increase batch_size instead)", rank=self.rank)
            self.iter_size = 1

        train_param, test_params = resolve_nets(sp)

        # --- data pipelines for the train net ---------------------------- #
        self._train_param = train_param  # retained: reshard_data rebuilds
        self.train_pipelines, train_shapes = self._build_pipelines(
            train_param, "TRAIN")
        self._train_shapes = train_shapes  # per-device; remat probe scales
        self.train_net = Net(train_param, "TRAIN", source_shapes=train_shapes)
        if self.mesh_cfg is not None and self.mesh_cfg.active:
            from ..parallel.spmd import ShardingPlan
            self.plan = ShardingPlan.build(
                self.train_net, self.mesh_cfg, self.comm,
                shard_params=self.mesh_cfg.shard,
                enable_tp=self.mesh_cfg.shard)
            log(f"sharding plan: {self.plan.describe()}", rank=self.rank)
            if self.iter_size > 1:
                log("WARNING: iter_size > 1 does not compose with --mesh "
                    "sharding yet; running iter_size=1", rank=self.rank)
                self.iter_size = 1
            if max(1, int(steps_per_dispatch)) > 1:
                log("WARNING: steps_per_dispatch ignored under --mesh "
                    "sharding", rank=self.rank)
                steps_per_dispatch = 1
        self._input_transform = self._make_input_transform()
        if self._device_transform and self._input_transform is None:
            log("WARNING: --device_transform requested but no train data "
                "layer is eligible (needs the native LMDB batcher, "
                "byte-backed records, and mean_value-style mean — a "
                "mean_file must stay host-side); using the host transform",
                rank=self.rank)

        self.test_nets: List[Net] = []
        self.test_pipelines: List[List[BatchPipeline]] = []
        for i, tp in enumerate(test_params):
            pipes, shapes = self._build_pipelines(tp, "TEST")
            self.test_nets.append(Net(tp, "TEST", source_shapes=shapes))
            self.test_pipelines.append(pipes)

        if sfb_auto:
            # SACP cost-model strategy choice must land before step building:
            # build_*_train_step snapshots the strategy map eagerly. SFB is a
            # per-step backward-time exchange, so under SSP (local steps, no
            # per-step exchange) the auto picks stay DENSE instead.
            if staleness > 0 and self.comm.dcn_axis is None:
                log("sfb_auto: SFB does not compose with flat-mesh SSP "
                    "staleness; keeping DENSE delta sync for all layers "
                    "(on a two-tier mesh SFB rides the intra-slice tier)",
                    rank=self.rank)
            else:
                from ..parallel.strategies import auto_strategies
                self.comm.layer_strategies.update(
                    auto_strategies(self.train_net))

        # HDF5_OUTPUT in the TRAIN net (hdf5_output_layer.cpp): the step
        # additionally returns the dump bottoms; after every iteration the
        # file is rewritten with the latest batch — the reference's
        # overwrite-per-forward semantics. Must be known before step build.
        self._h5_train = [
            (l.lp.hdf5_output_param.file_name, list(l.lp.bottom))
            for l in self.train_net.layers if l.TYPE == "HDF5_OUTPUT"]
        if self._h5_train and staleness > 0:
            log("WARNING: HDF5_OUTPUT in the TRAIN net is not dumped "
                "under SSP staleness", rank=self.rank)
            self._h5_train = []

        # --- step pipeline eligibility ------------------------------------ #
        # Device-side input prefetch feeds the SINGLE-batch path: the
        # stacked paths (scan chunking, iter_size micro-batches) assemble
        # host batches in their own shapes and would desync the shared
        # pipeline order if a prefetcher were draining the same pipes.
        self._use_prefetch = (self.device_prefetch > 0
                              and self.iter_size == 1
                              and max(1, int(steps_per_dispatch)) == 1)
        if device_prefetch is not None and self.device_prefetch > 0 and \
                not self._use_prefetch:
            # warn only on an EXPLICIT request — the policy default (2)
            # silently stands down for stacked-batch runs
            log("WARNING: --device_prefetch disabled (iter_size > 1 or "
                "steps_per_dispatch > 1 use stacked host batches); the "
                "stacked transfer already amortizes the host->device "
                "boundary", rank=self.rank)
        # with a prefetcher handing the step a FRESH device batch every
        # iteration, donating the batch buffers lets XLA recycle the
        # previous step's allocation — steady state allocates no new
        # device batch buffers. CPU never honors donation (unimplemented)
        # yet the unhonored aliasing spec measurably slows the call path
        # (~10% on the 2-core bench box), so donate only where the
        # allocator actually recycles.
        donate_batch = self._use_prefetch and jax.default_backend() != "cpu"
        self._donate_batch = donate_batch

        # --- measured HBM budget planner (core/remat.py) ------------------ #
        # --hbm_budget_gb fits the compiled train step's real
        # memory_analysis() peak under a byte budget by rematerializing
        # the cheapest-recompute activations (greedy knapsack against the
        # attribution table's act_bytes column); --remat either forces an
        # explicit layer list (skipping the measuring compile) or says
        # "auto" (plan against the budget) / "none" (off). The plan is
        # computed ONCE here, then rides build_train_step(remat_plan=).
        self.remat_plan = None
        self.hbm_budget_gb = hbm_budget_gb
        _want_plan = ((remat or "").strip().lower() not in ("", "none")
                      or (hbm_budget_gb is not None and hbm_budget_gb != 0))
        if _want_plan and staleness > 0:
            log("WARNING: --hbm_budget_gb/--remat are ignored under SSP "
                "staleness (the local-step path has no remat wiring yet)",
                rank=self.rank)
        elif _want_plan:
            self.remat_plan = self._plan_remat(remat, hbm_budget_gb,
                                               donate_batch)
        if self.remat_plan is not None and not self.remat_plan.active:
            self.remat_plan = None  # fits the budget: identity plan
        if self.remat_plan is not None:
            log(self.remat_plan.describe(), rank=self.rank)
            # stats.yaml says WHAT dropped and WHY (budget, measured
            # peak, claimed bytes) — the tuned-plan provenance discipline
            self.stats.set_section("remat", self.remat_plan.to_doc())

        # --- compiled steps ---------------------------------------------- #
        if staleness > 0:
            # SSP (ssp_consistency_controller.cpp): each device runs local
            # steps, reconciling every staleness+1 iters. The engine's view
            # of "the params" is the replicated anchor (what the PS holds).
            ssp_ts = build_ssp_train_step(self.train_net, sp, self.mesh,
                                          staleness, self.comm,
                                          input_transform=self._input_transform,
                                          donate_batch=donate_batch,
                                          plan=self.plan)
            raw_step = ssp_ts.step

            def _ssp_step(params, state, batch, rng):
                state, m = raw_step(state, batch, rng)
                return state.anchor_params, state, m

            self.train_step = TrainStep(
                step=_ssp_step, mesh=ssp_ts.mesh,
                batch_sharding=ssp_ts.batch_sharding,
                replicated=ssp_ts.replicated,
                # NOTE: the SSP lowerable has the 3-arg (state, batch, rng)
                # signature, not the wrapper's 4-arg one
                lowerable=ssp_ts.lowerable,
                arena=ssp_ts.arena)
        else:
            dump = sorted({b for _, bs in self._h5_train for b in bs})
            if dump and self.iter_size > 1:
                log("WARNING: iter_size > 1 ignored with HDF5_OUTPUT in "
                    "the TRAIN net (per-iteration dump semantics)",
                    rank=self.rank)
                self.iter_size = 1
            if dump and self.plan is not None:
                log("WARNING: HDF5_OUTPUT in the TRAIN net is not dumped "
                    "under --mesh sharding", rank=self.rank)
                dump = []
                self._h5_train = []
            self.train_step = build_train_step(
                self.train_net, sp, self.mesh, self.comm, dump_blobs=dump,
                input_transform=self._input_transform,
                iter_size=self.iter_size, donate_batch=donate_batch,
                plan=self.plan, remat_plan=self.remat_plan)

        # --- multi-step dispatch (scan chunks) ---------------------------- #
        # K optimizer steps per compiled dispatch: amortizes the runtime's
        # per-dispatch round-trip (dominant on tunneled/multi-host runtimes).
        # The engine falls back to single steps near display/test/snapshot
        # boundaries so solver cadence semantics are exact.
        self.steps_per_dispatch = max(1, int(steps_per_dispatch))
        self._scan_step = None
        if self.steps_per_dispatch > 1:
            if staleness > 0:
                log("WARNING: steps_per_dispatch ignored under SSP "
                    "staleness (the SSP step already batches local steps)",
                    rank=self.rank)
                self.steps_per_dispatch = 1
            elif self._h5_train:
                log("WARNING: steps_per_dispatch ignored with HDF5_OUTPUT "
                    "in the TRAIN net (per-iteration dump semantics)",
                    rank=self.rank)
                self.steps_per_dispatch = 1
            else:
                self._scan_step = build_train_step(
                    self.train_net, sp, self.mesh, self.comm,
                    scan_steps=self.steps_per_dispatch,
                    input_transform=self._input_transform,
                    iter_size=self.iter_size,
                    remat_plan=self.remat_plan)
        self.eval_steps = [
            build_eval_step(n, self.mesh, dcn_axis=self.comm.dcn_axis,
                            plan=self.plan)
            for n in self.test_nets]

        # --- state -------------------------------------------------------- #
        seed = sp.random_seed if sp.random_seed >= 0 else 1
        self.rng = jax.random.PRNGKey(seed)
        self.params = self.train_net.init(jax.random.fold_in(self.rng, 0))
        self.err_groups = comm_error_groups(self.comm, self.mesh)
        if staleness > 0:
            # SSP groups = slices on a two-tier mesh, devices on a flat one
            # (the same granularity comm_error_groups computes)
            self.state = init_ssp_state(self.params, self.err_groups,
                                        self.comm)
        else:
            self.state = init_train_state(self.params, self.comm,
                                          self.err_groups)
        # single-batch placement spec (test/eval batches and non-accumulated
        # train steps): the train step's input sharding minus the leading
        # [iter_size] micro-batch axis it gains under gradient accumulation
        from jax.sharding import NamedSharding, PartitionSpec
        spec = self.train_step.batch_sharding.spec
        if self.iter_size > 1:
            spec = PartitionSpec(*spec[1:])
        self._sample_sharding = NamedSharding(self.mesh, spec)
        self.metrics = MetricsTable("train")
        self.test_metrics = [MetricsTable(f"test_{i}")
                             for i in range(len(self.test_nets))]
        self.profile_steps = 0  # set >0 to capture an xplane trace
        # background snapshot serialization (--async_snapshot): the host
        # copy is still taken synchronously at the snapshot boundary (THE
        # sync point), but encode + write + atomic rename leave the loop
        self._snap_writer = AsyncSnapshotWriter() if self.async_snapshot \
            else None
        self._device_feed: Optional[DevicePrefetcher] = None

        # fast restart (runtime/compile_cache.py): when a compile-cache
        # dir is configured, the single-step hot path resolves through the
        # AOT step-executable store on first dispatch — a restarted-or-new
        # worker whose (model, shapes, mesh, policy) key matches skips
        # tracing AND compilation entirely; a miss compiles once,
        # serializes for the next incarnation, and still rides the
        # persistent XLA cache. SSP local-step and HDF5-dump steps keep
        # the jit path (different call signatures).
        from ..config import compile_cache_config
        _ccc = compile_cache_config()
        self._aot_exec = None
        self._aot_failed = False
        # the AOT step store calls lowerable.lower(params, state, batch,
        # rng) and replays the executable with those four args; the spmd
        # step carries bound trailing (sharded multiplier) arguments the
        # replay would miss, so warm start stands down under a plan
        self._aot_enabled = (bool(_ccc.cache_dir) and _ccc.aot_steps
                             and staleness == 0 and not self._h5_train
                             and self.iter_size == 1
                             and self.plan is None)

        self._h5_outputs = [
            [(l.lp.hdf5_output_param.file_name, list(l.lp.bottom))
             for l in net.layers if l.TYPE == "HDF5_OUTPUT"]
            for net in self.test_nets]
        self._h5_fetch = [
            (jax.jit(lambda p, b, _n=net: _n.apply(p, b, train=False,
                                                   keep_blobs=True).blobs)
             if any(outs) else None)
            for net, outs in zip(self.test_nets, self._h5_outputs)]

        # debug_info (solver.cpp:326,422; net.cpp ForwardDebugInfo/
        # UpdateDebugInfo): per-layer mean-|.| of activations, params, and
        # gradients, printed at display boundaries. Off the hot path — a
        # separate jitted pass that runs only when enabled.
        self._debug_fn = None
        if sp.debug_info and not sp.display:
            log("WARNING: debug_info needs a display cadence (display: N) "
                "to print; set display in the solver", rank=self.rank)
        elif sp.debug_info:
            def _debug(params, batch, rng):
                if self._input_transform is not None:
                    batch = self._input_transform(batch)
                out = self.train_net.apply(
                    params, batch, train=True, rng=rng, keep_blobs=True)
                grads = jax.grad(
                    lambda p: self.train_net.apply(
                        p, batch, train=True, rng=rng).loss)(params)
                stats = {}
                for name, v in out.blobs.items():
                    stats[f"blob\x00{name}"] = jnp.mean(jnp.abs(
                        v.astype(jnp.float32)))
                for lname, lp in params.items():
                    for pname, w in lp.items():
                        stats[f"param\x00{lname}/{pname}"] = jnp.mean(
                            jnp.abs(w.astype(jnp.float32)))
                        stats[f"grad\x00{lname}/{pname}"] = jnp.mean(
                            jnp.abs(grads[lname][pname].astype(jnp.float32)))
                return stats

            self._debug_fn = jax.jit(_debug)

    # ---------------------------------------------------------------- #
    def _build_pipelines(self, net_param: NetParameter, phase: str,
                         shard: Optional[Shard] = None):
        # Each host produces only its addressable devices' rows; the pipeline
        # shards the record space across hosts (shared_file_system-style).
        return build_phase_pipelines(
            net_param, phase, batch_multiplier=jax.local_device_count(),
            shard=shard if shard is not None else self._data_shard,
            memory_data=self.memory_data,
            device_transform=(self._device_transform and phase == "TRAIN"))

    def _plan_remat(self, remat, hbm_budget_gb, donate_batch):
        """Resolve the remat decision for this job config (called once,
        before step building). Three spellings:

        - ``remat`` = comma-separated layer names: trust the operator,
          price the list against the attribution table, skip the
          measuring compile entirely (source="flag");
        - ``remat`` = "auto" and/or a budget: build the NO-remat step,
          compile it against abstract batch avals, read the real
          ``memory_analysis()`` peak, and run the knapsack
          (source="measured"; the no-remat compile is the price of
          measuring — the tuned store memoizes the decision);
        - ``hbm_budget_gb`` < 0: auto-detect the device's own HBM limit
          (``default_budget_bytes``); refuses quietly on backends with
          no memory stats (the CPU proxy needs an explicit budget).
        """
        from ..core import remat as remat_mod
        from .attribution import layer_cost_table
        table = layer_cost_table(self.train_net)
        names = [s.strip() for s in str(remat or "").split(",")
                 if s.strip() and s.strip().lower() not in ("none",
                                                            "auto")]
        if names:
            known = {l.name for l in self.train_net.layers}
            unknown = sorted(set(names) - known)
            if unknown:
                raise ValueError(
                    f"--remat names unknown layers: {unknown}")
            return remat_mod.RematPlan(
                budget_bytes=0,
                layers=tuple(names),
                saved_bytes=sum(int(table.get(n, {}).get("act_bytes", 0))
                                for n in names),
                recompute_flops=sum(
                    float(table.get(n, {}).get("flops", 0.0)) / 3.0
                    for n in names),
                source="flag")
        if hbm_budget_gb is not None and hbm_budget_gb < 0:
            budget = remat_mod.default_budget_bytes()
            if budget <= 0:
                log("WARNING: --hbm_budget_gb auto needs device memory "
                    "stats (none on this backend); pass an explicit "
                    "budget — skipping remat planning", rank=self.rank)
                return None
        else:
            budget = int(float(hbm_budget_gb or 0) * 2**30)
        # the measuring probe: the SAME step config the engine is about
        # to build, minus remat, lowered against abstract avals (no
        # params materialize here — eval_shape carries the pytrees)
        probe = build_train_step(
            self.train_net, self.sp, self.mesh, self.comm,
            input_transform=self._input_transform,
            iter_size=self.iter_size, donate_batch=donate_batch,
            plan=self.plan)
        params_avals = jax.eval_shape(self.train_net.init,
                                      jax.random.PRNGKey(0))
        groups = comm_error_groups(self.comm, self.mesh)
        state_avals = jax.eval_shape(
            lambda p: init_train_state(p, self.comm, groups), params_avals)
        batch_avals = {}
        for k, s in self._train_shapes.items():
            g = (int(s[0]) * self.n_dev,) + tuple(int(d) for d in s[1:])
            if self.iter_size > 1:
                g = (self.iter_size,) + g
            # rank-1 source blobs are the data layers' label tops
            dt = jnp.int32 if len(s) == 1 else jnp.float32
            batch_avals[k] = jax.ShapeDtypeStruct(g, dt)
        return remat_mod.plan_for_net_step(
            self.train_net, probe.lowerable,
            (params_avals, state_avals, batch_avals,
             jax.random.PRNGKey(7)),
            budget)

    def reshard_data(self, shard: Shard) -> bool:
        """Re-key the TRAIN data assignment (elastic membership: the async
        tier calls this when the member list changes, with the shard from
        ``data/workload.member_shard``). Rebuilds the train pipelines —
        and the device prefetcher consuming them — against the new
        contiguous range; test pipelines keep the launch shard (eval is a
        fixed-world sweep). No-op when the shard is unchanged."""
        if shard == self._data_shard:
            return False
        old = self._data_shard
        if self._device_feed is not None:
            # the feed's worker thread consumes the pipelines being torn
            # down; stop it first, recreate it against the new ones below
            self._device_feed.close()
            self._device_feed = None
        for p in self.train_pipelines:
            p.close()
        self.train_pipelines, _ = self._build_pipelines(
            self._train_param, "TRAIN", shard=shard)
        self._data_shard = shard
        if self._use_prefetch:
            self._device_feed = DevicePrefetcher(
                self.train_pipelines, self._sample_sharding,
                depth=self.device_prefetch)
        log(f"resharded data assignment: shard {old.index}/{old.count} -> "
            f"{shard.index}/{shard.count}", rank=self.rank)
        return True

    def _make_input_transform(self):
        """The device half of the uint8 ingest split: per data-layer
        (x - mean_values) * scale, traced into the compiled train step."""
        specs = {p.tops[0]: p.device_transform_spec
                 for p in self.train_pipelines
                 if getattr(p, "device_transform_spec", None) is not None}
        if not specs:
            return None
        frozen = {top: (None if s["mean_values"] is None
                        else jnp.asarray(s["mean_values"], jnp.float32),
                        float(s["scale"]))
                  for top, s in specs.items()}

        def transform(batch):
            out = dict(batch)
            for top, (mean, scale) in frozen.items():
                if top not in out:
                    continue
                x = out[top].astype(jnp.float32)
                if mean is not None:
                    x = x - mean.reshape(1, -1, 1, 1)
                if scale != 1.0:
                    x = x * scale
                out[top] = x
            return out

        return transform

    def _next_batch(self, pipes: List[BatchPipeline]):
        from ..data.pipeline import place_batch
        batch: Dict[str, jax.Array] = {}
        for pipe in pipes:
            for k, v in next(pipe).items():
                batch[k] = place_batch(v, self._sample_sharding)
        return batch

    def _next_batch_stack(self, pipes: List[BatchPipeline], k: int,
                          sharding=None, lead_shape=None):
        """k host batches stacked to [k, ...] and placed in ONE transfer
        (the feeding side of steps_per_dispatch). ``lead_shape`` reshapes
        the leading axis, e.g. (chunk, iter_size) when scan chunking and
        gradient accumulation compose."""
        rows: List[Dict[str, np.ndarray]] = [{} for _ in range(k)]
        for pipe in pipes:
            for i in range(k):
                rows[i].update(next(pipe))
        if sharding is None:
            sharding = self._scan_step.batch_sharding
        return stack_batches(rows, sharding, lead_shape=lead_shape)

    # ---------------------------------------------------------------- #
    def _dispatch_train_step(self, batch, rng):
        """One single-step dispatch, through the AOT warm-start path when
        configured (resolution is lazy: the store key needs the concrete
        batch shapes, which exist only once the first batch is drawn)."""
        if self._aot_enabled and self._aot_exec is None \
                and not self._aot_failed:
            self._resolve_aot_step(batch, rng)
        if self._aot_exec is not None:
            # the lowerable's raw signature carries the (empty — AOT is
            # disabled under HDF5_OUTPUT) dump slot; keep the step()
            # wrapper's 3-tuple contract
            out = self._aot_exec(self.params, self.state, batch, rng)
            return out[:3] if isinstance(out, tuple) and len(out) > 3 \
                else out
        return self.train_step.step(self.params, self.state, batch, rng)

    # one-time AOT resolution at the FIRST dispatch (key hashing over
    # static shapes/mesh ints), never steady-state:
    def _resolve_aot_step(self, batch, rng) -> None:  # static-ok: JIT102
        """Load — or compile + serialize — the step executable for this
        exact (model, shapes, mesh, backend, policy) key. Best-effort:
        any failure pins the jit path for the rest of the run (which the
        persistent compile cache still accelerates)."""
        from ..config import compile_cache_config, policy
        from .compile_cache import (load_step_executable,
                                    save_step_executable, step_key)
        try:
            cfg = compile_cache_config()
            key = step_key(
                kind="train_step",
                model=self.train_net.name or "net",
                params={l: {p: (list(v.shape), str(v.dtype))
                            for p, v in ps.items()}
                        for l, ps in self.params.items()},
                batch={k: (list(v.shape), str(v.dtype))
                       for k, v in batch.items()},
                mesh={k: int(v) for k, v in self.mesh.shape.items()},
                backend=jax.default_backend(),
                device_kind=jax.devices()[0].device_kind,
                n_devices=self.n_dev,
                jax_version=jax.__version__,
                numeric_policy=str(policy()),
                conv_layout=self.train_net.conv_layout,
                # compile-RELEVANT solver fields only: max_iter/display/
                # snapshot cadence never reach the traced program, and
                # folding them in would defeat the warm start for the
                # standard resume-and-train-longer flow
                solver={k: str(getattr(self.sp, k, None)) for k in (
                    "solver_type", "base_lr", "lr_policy", "gamma",
                    "power", "stepsize", "stepvalue", "momentum",
                    "momentum2", "weight_decay", "regularization_type",
                    "delta", "clip_gradients", "iter_size",
                    "random_seed")},
                comm=str(self.comm),
                donate_batch=self._donate_batch)
            exec_ = load_step_executable(cfg.cache_dir, key)
            if exec_ is None:
                low = self.train_step.lowerable or self.train_step.step
                compiled = low.lower(self.params, self.state, batch,
                                     rng).compile()
                save_step_executable(cfg.cache_dir, key, compiled)
                exec_ = compiled
                log(f"aot warm start: compiled + serialized train step "
                    f"(key {key[:12]}); next start of this config skips "
                    f"trace+compile", rank=self.rank)
            else:
                log(f"aot warm start: loaded serialized train step "
                    f"(key {key[:12]}) — trace and compile skipped",
                    rank=self.rank)
            self._aot_exec = exec_
        except Exception as e:  # noqa: BLE001 — warm start is best-effort
            self._aot_failed = True
            log(f"aot warm start unavailable ({type(e).__name__}: {e}); "
                f"using the jit path", rank=self.rank)

    # ---------------------------------------------------------------- #
    def iteration(self) -> int:
        return int(self.state.it if self.staleness > 0
                   else self.state.solver.it)

    def restore_from(self, path: str):
        if path.endswith(".caffemodel"):
            self.params = load_caffemodel(path, self.train_net, self.params)
            if self.staleness > 0:
                self.state = init_ssp_state(self.params, self.err_groups,
                                            self.comm)
            log(f"Loaded weights from {path}", rank=self.rank)
        else:
            from .checkpoint import coerce_state
            params, state = restore(path)
            self.params, self.state = coerce_state(
                params, state, staleness=self.staleness,
                n_dev=self.err_groups, comm=self.comm)
            log(f"Restored solver state from {path} "
                f"(iter {self.iteration()})", rank=self.rank)

    def auto_resume(self) -> Optional[str]:
        """Restart-after-preemption without tracking filenames: sweep any
        stale snapshot tmp litter a killed predecessor left behind, find
        the newest ``<prefix>_iter_N.solverstate.npz`` under the solver's
        snapshot prefix, and restore it. Returns the restored path, or
        None when there is nothing to resume from (fresh start). Pairs
        with ``sp.snapshot`` cadence + the async tier's eviction/rejoin:
        a preempted worker relaunches with the same command line and
        continues from its last snapshot."""
        if not self.sp.snapshot_prefix:
            return None
        prefix = os.path.join(self.output_dir, self.sp.snapshot_prefix)
        removed = sweep_stale_tmp(prefix)
        if removed:
            log(f"auto-resume: swept {len(removed)} stale snapshot tmp "
                f"file(s): {', '.join(os.path.basename(r) for r in removed)}",
                rank=self.rank)
        path = latest_snapshot(prefix)
        if path is None:
            log(f"auto-resume: no snapshot under {prefix!r}; starting fresh",
                rank=self.rank)
            return None
        self.restore_from(path)
        return path

    def snapshot_now(self) -> Optional[str]:
        if not self.sp.snapshot_prefix:
            return None
        prefix = os.path.join(self.output_dir, self.sp.snapshot_prefix)
        if self._snap_writer is not None:
            model, statef = self._snap_writer.submit(
                prefix, self.train_net, self.params, self.state)
            log(f"Snapshotting (async) to {model} / {statef}",
                rank=self.rank)
            return statef
        model, statef = snapshot(prefix, self.train_net, self.params,
                                 self.state)
        log(f"Snapshotting to {model} / {statef}", rank=self.rank)
        return statef

    # ---------------------------------------------------------------- #
    def test(self, test_id: int = 0) -> Dict[str, float]:
        """Average metrics over test_iter batches (Solver::Test)."""
        net = self.test_nets[test_id]
        ev = self.eval_steps[test_id]
        iters = self.sp.test_iter[test_id] if test_id < len(self.sp.test_iter) \
            else 50
        acc: Dict[str, float] = {}
        h5_acc: Dict[str, list] = {}
        h5_specs = self._h5_outputs[test_id]
        multihost = jax.process_count() > 1
        for _ in range(iters):
            batch = self._next_batch(self.test_pipelines[test_id])
            if h5_specs:
                # one traced forward serves both metrics and dumped blobs
                blobs = self._h5_fetch[test_id](self.params, batch)
                m = {k: v for k, v in blobs.items()
                     if k in net.output_names and v.ndim == 0}
                for fname, bottoms in h5_specs:
                    for b in bottoms:
                        arr = blobs[b]
                        if multihost:
                            from jax.experimental import multihost_utils
                            arr = multihost_utils.process_allgather(
                                arr, tiled=True)
                        if self.rank == 0:
                            h5_acc.setdefault(f"{fname}\x00{b}", []).append(
                                np.asarray(arr))
            else:
                m = ev(self.params, batch)
            for k, v in m.items():
                acc[k] = acc.get(k, 0.0) + float(v)
        if h5_specs and self.rank == 0:
            self._write_h5_outputs(h5_acc)
        out = {k: v / iters for k, v in acc.items()}
        msg = ", ".join(f"{k} = {v:.4f}" for k, v in sorted(out.items()))
        log(f"    Test net #{test_id}: {msg}", rank=self.rank)
        self.test_metrics[test_id].accumulate(out)
        return out

    def _check_divergence(self, fetcher: AsyncScalarFetcher) -> None:
        """Abort on the first non-finite watched metric the async drain has
        seen. The report names the step that PRODUCED the bad value (the
        fetcher tags rows by iteration — the rewind), even though the loop
        has dispatched up to max_in_flight steps past it."""
        if fetcher.divergence is not None:
            it, key, value = fetcher.divergence
            raise TrainingDivergedError(it, key, value)

    def _absorb(self, rows, last: Dict[str, float]) -> Dict[str, float]:
        """Feed drained (iter, row) pairs into the metrics window."""
        for _, row in rows:
            self.metrics.accumulate(row)
            last = row
        return last

    def train(self, max_iter: Optional[int] = None) -> Dict[str, float]:
        sp = self.sp
        max_iter = max_iter or sp.max_iter
        it = self.iteration()
        t_start = time.time()
        last: Dict[str, float] = {}
        # the dispatch window: device metrics drain to host floats on the
        # fetcher's thread; put() blocks only when max_in_flight dispatches
        # are un-materialized, so the loop runs ahead of the device by a
        # bounded number of steps instead of hard-syncing every iteration
        fetcher = AsyncScalarFetcher(self.max_in_flight)
        if self._use_prefetch and self._device_feed is None:
            self._device_feed = DevicePrefetcher(
                self.train_pipelines, self._sample_sharding,
                depth=self.device_prefetch)
        if self._async_cfg is not None and self._async_tier is None:
            from .async_tier import AsyncSSPTier, FabricTier
            # two-tier fabric mode ("slice": True, --slice): this process
            # leads an SPMD slice and the DCN worker identity is the
            # SLICE id — membership, gates and the data shard below all
            # re-key to slice granularity (parallel/fabric.py)
            cfg = dict(self._async_cfg)
            tier_cls = FabricTier if cfg.pop("slice", False) else AsyncSSPTier
            self._async_tier = tier_cls(self.params, **cfg)
            # every worker starts from the service anchor: rank 0's view on
            # a fresh run, the surviving anchor (all applied clocks) when
            # this process is a preemption restart rejoining mid-job, and
            # the join-clock anchor for an elastic joiner admitted into a
            # live job
            self.params = jax.device_put(self._async_tier.resume_cache,
                                         self.train_step.replicated)
            # key the data assignment by the member list the join revealed
            # (a joiner built its pipelines with the placeholder shard;
            # everyone else no-ops unless the fleet already changed)
            self.reshard_data(self._async_tier.data_shard())
        # profiler window: skip a couple of warmup/compile steps
        profile_start = it + 2
        profiling = False

        if sp.test_interval and sp.test_initialization and self.test_nets:
            for i in range(len(self.test_nets)):
                self.test(i)
                self.test_metrics[i].flush_row(it)

        try:
            while it < max_iter:
                if sp.snapshot and it > 0 and it % sp.snapshot == 0:
                    # snapshot boundary = hard sync point: every in-flight
                    # step's metrics must be seen BEFORE persisting params,
                    # so a NaN that the drainer has not surfaced yet can
                    # never be snapshotted and then silently auto-resumed
                    with span_recorder.span("hard_sync", "sync",
                                            {"boundary": "snapshot"}):
                        last = self._absorb(fetcher.sync(), last)
                    self._check_divergence(fetcher)
                    with span_recorder.span("snapshot", "ckpt",
                                            {"iter": it}):
                        self.snapshot_now()
                if self.profile_steps and it == profile_start:
                    jax.profiler.start_trace(
                        os.path.join(self.output_dir, "profile"))
                    profiling = True

                # how many steps may run before the next host-side boundary
                # (display flush / debug pre-step / test / snapshot /
                # profile); a full steps_per_dispatch chunk runs as ONE
                # compiled dispatch
                chunk = 1
                if self._scan_step is not None:
                    room = max_iter - it
                    if sp.display:
                        d = sp.display - (it % sp.display)
                        room = min(room, d - 1 if self._debug_fn else d)
                    if sp.test_interval and self.test_nets:
                        room = min(room, sp.test_interval -
                                   (it % sp.test_interval))
                    if sp.snapshot:
                        room = min(room, sp.snapshot - (it % sp.snapshot))
                    if self.profile_steps and \
                            it < profile_start + self.profile_steps:
                        # single-step dispatches only until the trace window
                        # closes; afterwards chunking resumes
                        room = min(room, profile_start - it) \
                            if it < profile_start else 1
                    if room >= self.steps_per_dispatch:
                        chunk = self.steps_per_dispatch

                if chunk > 1:
                    t_in = time.perf_counter()
                    with span_recorder.span("prefetch_wait", "input",
                                            {"iter": it, "chunk": chunk}):
                        batch = self._next_batch_stack(
                            self.train_pipelines, chunk * self.iter_size,
                            lead_shape=((chunk, self.iter_size)
                                        if self.iter_size > 1 else None))
                    self.stats.add_time("input_stall",
                                        time.perf_counter() - t_in)
                    t0 = time.time()
                    # the scan step folds rng by global iteration internally
                    # (solver.it + offset): pass the session rng unfolded so
                    # a chunked run's per-step streams match single-step
                    # dispatch
                    with span_recorder.span("dispatch", "step",
                                            {"iter": it, "chunk": chunk}):
                        self.params, self.state, m = self._scan_step.step(
                            self.params, self.state, batch, self.rng)
                    it += chunk
                    at_display = bool(sp.display) and it % sp.display == 0
                else:
                    t_in = time.perf_counter()
                    with span_recorder.span("prefetch_wait", "input",
                                            {"iter": it}):
                        if self.iter_size > 1:
                            # one optimizer step = iter_size stacked
                            # micro-batches
                            batch = self._next_batch_stack(
                                self.train_pipelines, self.iter_size,
                                sharding=self.train_step.batch_sharding)
                        elif self._device_feed is not None:
                            # the prefetch stage already placed this batch
                            # on device with the step's sharding; steady
                            # state this dequeue is instant and input_stall
                            # measures any residual starvation
                            batch = next(self._device_feed)
                        else:
                            batch = self._next_batch(self.train_pipelines)
                    self.stats.add_time("input_stall",
                                        time.perf_counter() - t_in)
                    at_display = bool(sp.display) and \
                        (it + 1) % sp.display == 0
                    if at_display and self._debug_fn:
                        # BEFORE the step, on the step's own inputs
                        # (pre-update params, this iteration's rng/batch) —
                        # the values Caffe's ForwardDebugInfo/UpdateDebugInfo
                        # report for iteration it+1. Under iter_size the
                        # debug pass reads the first micro-batch (one
                        # representative forward).
                        dbatch = ({k: v[0] for k, v in batch.items()}
                                  if self.iter_size > 1 else batch)
                        stats = self._debug_fn(
                            self.params, dbatch,
                            jax.random.fold_in(self.rng, it))
                        for key in sorted(stats):
                            kind, name = key.split("\x00")
                            log(f"    [debug] {kind:<5} {name}: "
                                f"{float(stats[key]):.6g}", rank=self.rank)
                    t0 = time.time()
                    with span_recorder.span("dispatch", "step",
                                            {"iter": it}):
                        result = self._dispatch_train_step(
                            batch, jax.random.fold_in(self.rng, it))
                    if self._h5_train:
                        self.params, self.state, m, dumps = result
                        self._write_train_h5(dumps)
                    else:
                        self.params, self.state, m = result
                    it += 1
                if profiling and it >= profile_start + self.profile_steps:
                    jax.block_until_ready(m["loss"])
                    jax.profiler.stop_trace()
                    profiling = False
                    log(f"Wrote profiler trace to "
                        f"{os.path.join(self.output_dir, 'profile')}",
                        rank=self.rank)
                # metrics stay device arrays on this thread: the fetcher's
                # drainer materializes them to host floats off-thread, and
                # put() blocks only when max_in_flight dispatches are still
                # un-materialized — the bounded in-flight dispatch window
                # (the span measures exactly the window backpressure wait)
                with span_recorder.span("dispatch_window", "step",
                                        {"iter": it}):
                    fetcher.put(it - chunk, m)
                self._check_divergence(fetcher)
                self.stats.add("train_iters", chunk)
                self.stats.add_time("train_step", time.time() - t0)
                if self._async_tier is not None:
                    self._async_tier.after_iters(self, chunk)

                # absorb whatever the drainer finished — no display cadence
                # needed to keep the metrics window bounded
                last = self._absorb(fetcher.take_drained(), last)
                if at_display:  # same boundary: it has incremented since
                    # hard sync: the displayed window must cover every step
                    # through `it` (the drainer may lag by the in-flight
                    # window otherwise)
                    with span_recorder.span("hard_sync", "sync",
                                            {"boundary": "display"}):
                        last = self._absorb(fetcher.sync(), last)
                    self._check_divergence(fetcher)
                    row = self.metrics.flush_row(it)
                    lr = float(learning_rate(sp, jnp.asarray(it - 1)))
                    extras = ", ".join(
                        f"{k} = {v:.4f}" for k, v in sorted(row.items())
                        if k not in ("iter", "time"))
                    log(f"Iteration {it}, lr = {lr:.6g}, {extras}",
                        rank=self.rank)
                    # live telemetry rides the display cadence: gauges for
                    # the metrics endpoint, plus the atomic stats.yaml /
                    # span-timeline dump (a preempted run keeps both)
                    self.stats.set_gauge("iteration", it)
                    self.stats.set_gauge("lr", lr)
                    for k, v in row.items():
                        if k not in ("iter", "time"):
                            self.stats.set_gauge(f"train_{k}", round(v, 6))
                    self._dump_live_telemetry()
                    if self._async_tier is not None:
                        # membership churn rides the display cadence, so
                        # admissions/evictions are visible without
                        # log-grepping (comm_stats.membership_counters)
                        from .comm_stats import (format_comm,
                                                 format_membership)
                        log("    [membership] " + format_membership(
                            self._async_tier.membership_counters()),
                            rank=self.rank)
                        # the per-link managed-communication bill rides
                        # the same cadence: bytes on the wire, deferred
                        # fraction, measured goodput, cadence backoffs —
                        # gauges feed stats.yaml + the metrics endpoint
                        cc = self._async_tier.comm_counters()
                        if cc:
                            log("    [comm] " + format_comm(cc),
                                rank=self.rank)
                            for k, v in cc.items():
                                self.stats.set_gauge(f"async_comm_{k}",
                                                     round(float(v), 4))
                if sp.test_interval and it % sp.test_interval == 0 and \
                        self.test_nets:
                    # test boundary = hard sync point too: never spend a
                    # full eval sweep on a model a still-draining NaN has
                    # already poisoned
                    with span_recorder.span("hard_sync", "sync",
                                            {"boundary": "test"}):
                        last = self._absorb(fetcher.sync(), last)
                    self._check_divergence(fetcher)
                    for i in range(len(self.test_nets)):
                        self.test(i)
                        self.test_metrics[i].flush_row(it)

            # tail iterations past the last display boundary
            with span_recorder.span("hard_sync", "sync",
                                    {"boundary": "final"}):
                last = self._absorb(fetcher.sync(), last)
            self._check_divergence(fetcher)
        finally:
            self.stats.counters["steps_in_flight"] = round(
                fetcher.mean_in_flight(), 3)
            fetcher.close()
            if profiling:
                jax.profiler.stop_trace()
                log(f"Wrote profiler trace to "
                    f"{os.path.join(self.output_dir, 'profile')}",
                    rank=self.rank)
        if self._async_tier is not None:
            # flush the last clock + fold the final anchor into rank 0's
            # params BEFORE the after-train snapshot, so the snapshot holds
            # every worker's updates
            tier_stats = self._async_tier.finish(self)
            for k, v in tier_stats.items():
                self.stats.add(k, v)
            self._async_tier = None
        if sp.snapshot_after_train:
            with span_recorder.span("snapshot", "ckpt",
                                    {"boundary": "after_train"}):
                self.snapshot_now()
        if self._snap_writer is not None:
            # train() returning means the artifacts exist: join the last
            # background write (and surface its failure loudly)
            self._snap_writer.wait()
        self.stats.add_time("train_total", time.time() - t_start)
        self._write_artifacts()
        return last

    def _write_train_h5(self, dumps: Dict[str, jax.Array]):
        """Rewrite each TRAIN-net HDF5_OUTPUT file with the latest batch
        (hdf5_output_layer.cpp overwrites its datasets every Forward)."""
        import h5py
        host = {}
        multihost = jax.process_count() > 1
        for k, v in dumps.items():
            if multihost and not v.is_fully_addressable:
                from jax.experimental import multihost_utils
                v = multihost_utils.process_allgather(v, tiled=True)
            host[k] = np.asarray(v)
        if self.rank != 0:
            return
        for fname, bottoms in self._h5_train:
            path = os.path.join(self.output_dir, fname)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with h5py.File(path, "w") as f:
                for b in bottoms:
                    f.create_dataset(b.replace("/", "_"), data=host[b])

    def _write_h5_outputs(self, h5_acc: Dict[str, list]):
        import h5py
        by_file: Dict[str, Dict[str, np.ndarray]] = {}
        for key, chunks in h5_acc.items():
            fname, blob = key.split("\x00")
            by_file.setdefault(fname, {})[blob.replace("/", "_")] = \
                np.concatenate(chunks)
        for fname, datasets in by_file.items():
            path = os.path.join(self.output_dir, fname)
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with h5py.File(path, "w") as f:
                for name, arr in datasets.items():
                    f.create_dataset(name, data=arr)
            log(f"HDF5 output -> {path}", rank=self.rank)

    # ---------------------------------------------------------------- #
    def _trace_out_path(self) -> Optional[str]:
        """This rank's span-timeline path: rank 0 writes the requested
        file, workers write a ``.rank<k>`` sibling (every process records
        its own timeline — async push/gate spans live on the workers, and
        an output_dir may be shared)."""
        if self._trace_out is None:
            return None
        if self.rank == 0:
            return self._trace_out
        base, ext = os.path.splitext(self._trace_out)
        return f"{base}.rank{self.rank}{ext or '.json'}"

    def _dump_live_telemetry(self):
        """Display-boundary telemetry flush: stats.yaml (atomic tmp +
        rename — a crashed/preempted run keeps everything through its
        last boundary, rank 0 only) and, under --trace_out, this rank's
        span timeline. Best-effort: a full disk or NFS blip at a display
        boundary must never abort a training run that could keep going
        (the exit-time writers retry the same paths anyway)."""
        try:
            if self.rank == 0:
                self.stats.dump_yaml(os.path.join(self.output_dir,
                                                  "stats.yaml"))
            path = self._trace_out_path()
            if path is not None:
                span_recorder.dump(path)
        except OSError as e:
            if not getattr(self, "_telemetry_write_warned", False):
                self._telemetry_write_warned = True
                log(f"WARNING: telemetry write failed ({e}); training "
                    f"continues, will retry at the next boundary",
                    rank=self.rank)

    def _write_artifacts(self):
        if self.rank != 0:
            return
        # static per-layer comm accounting + comm/compute split estimate
        # (the stats.hpp bytes-per-clock analog, computed from shapes)
        from .comm_stats import comm_summary, layer_comm_table
        table = layer_comm_table(self.train_net, self.comm, self.mesh)
        iters = self.stats.counters.get("train_iters", 0)
        step_ms = (self.stats.timers.get("train_step", 0.0) / iters * 1e3
                   if iters else None)
        self.stats.set_section("comm", {
            "summary": comm_summary(table, step_ms),
            "per_layer": table,
        })
        name = self.train_net.name or "net"
        self.metrics.to_csv(os.path.join(self.output_dir,
                                         f"{name}_train_outputs.csv"))
        for i, tm in enumerate(self.test_metrics):
            if tm.rows:
                tm.to_csv(os.path.join(self.output_dir,
                                       f"{name}_test{i}_outputs.csv"))
        self.stats.dump_yaml(os.path.join(self.output_dir, "stats.yaml"))
        if self._trace_out is not None:
            try:
                log(f"Wrote span timeline to "
                    f"{span_recorder.dump(self._trace_out)}",
                    rank=self.rank)
            except OSError as e:
                log(f"WARNING: span timeline write failed: {e}",
                    rank=self.rank)

    def close(self):
        # close EVERYTHING before surfacing any failure: a snapshot-write
        # error must not strand the prefetcher/pipeline worker threads,
        # and an aborted (diverged/interrupted) run must not leak the
        # async tier's sockets behind the skipped finish() protocol
        err: Optional[BaseException] = None
        if self._owns_span_recorder:
            # final timeline flush (every rank writes its own file), then
            # stand the recorder down (it is process-global; a later
            # engine without --trace_out must not keep paying for spans
            # nobody will dump)
            path = self._trace_out_path()
            if path is not None:
                try:
                    span_recorder.dump(path)
                except OSError:
                    pass
            span_recorder.disable()
            self._owns_span_recorder = False
        if self._metrics_server is not None:
            try:
                self._metrics_server.close()
            except Exception:  # noqa: BLE001 — teardown best effort
                pass
            self._metrics_server = None
        if self._snap_writer is not None:
            try:
                self._snap_writer.close()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                err = e
        if self._async_tier is not None:
            for closer in (lambda: self._async_tier.client.close(),
                           lambda: (self._async_tier.service.close()
                                    if self._async_tier.service else None)):
                try:
                    closer()
                except Exception:  # noqa: BLE001 — teardown best effort
                    pass
            self._async_tier = None
        if self._device_feed is not None:
            # before the pipelines: the feed's worker consumes them
            self._device_feed.close()
            self._device_feed = None
        for p in self.train_pipelines:
            p.close()
        for pipes in self.test_pipelines:
            for p in pipes:
                p.close()
        if err is not None:
            raise err
