"""Cluster control plane: hostfile topology + JAX distributed runtime init.

The reference's control plane is a hostfile ("<id> <ip> <port>" lines,
machinefiles/localserver) plus a name-node rendezvous thread on client 0
(ps/src/petuum_ps/server/name_node_thread.cpp:57-90) over a ZeroMQ router
mesh. The TPU-native equivalent: the same hostfile names the processes, host 0
is the JAX distributed coordinator (the name-node role), and the data plane is
XLA collectives over ICI/DCN compiled into the step — no bg workers, no server
shards, no oplog wire protocol.

Fail-fast semantics match the reference (comm_bus.hpp:22-24): any rendezvous
or collective error aborts the process; recovery is via checkpoints.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import List, Optional


@dataclass(frozen=True)
class Host:
    id: int
    ip: str
    port: int


def parse_hostfile(path: str) -> List[Host]:
    hosts: List[Host] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 3:
                raise ValueError(f"{path}: bad hostfile line {line!r} "
                                 f"(want '<id> <ip> <port>')")
            hosts.append(Host(int(parts[0]), parts[1], int(parts[2])))
    ids = [h.id for h in hosts]
    if ids != list(range(len(hosts))):
        raise ValueError(f"{path}: host ids must be 0..N-1 in order, got {ids}")
    return hosts


def init_distributed(hostfile: Optional[str] = None,
                     node_id: Optional[int] = None,
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None) -> int:
    """Initialize the JAX distributed runtime from a hostfile (or explicit
    coordinator config / env). Host 0's entry is the coordinator — the
    name-node analog. Returns this process's id. No-op when single-process."""
    import jax

    if hostfile is not None:
        hosts = parse_hostfile(hostfile)
        if len(hosts) == 1:
            return 0
        if node_id is None:
            raise ValueError("node_id is required with a multi-host hostfile")
        coord = f"{hosts[0].ip}:{hosts[0].port}"
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=len(hosts),
                                   process_id=node_id)
        return node_id
    if coordinator_address is not None:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=node_id)
        return node_id or 0
    # Env-driven: the scripts/launch.py --local path sets these.
    coord = os.environ.get("POSEIDON_COORDINATOR")
    if coord:
        n = int(os.environ["POSEIDON_NUM_PROCS"])
        pid = int(os.environ["POSEIDON_PROC_ID"])
        if n > 1:
            jax.distributed.initialize(coordinator_address=coord,
                                       num_processes=n, process_id=pid)
        return pid
    return 0
