"""Cluster control plane: hostfile topology + JAX distributed runtime init.

The reference's control plane is a hostfile ("<id> <ip> <port>" lines,
machinefiles/localserver) plus a name-node rendezvous thread on client 0
(ps/src/petuum_ps/server/name_node_thread.cpp:57-90) over a ZeroMQ router
mesh. The TPU-native equivalent: the same hostfile names the processes, host 0
is the JAX distributed coordinator (the name-node role), and the data plane is
XLA collectives over ICI/DCN compiled into the step — no bg workers, no server
shards, no oplog wire protocol.

Collective errors stay fail-fast like the reference (comm_bus.hpp:22-24);
recovery is via checkpoints. Rendezvous, however, retries: under a real
launcher the coordinator process may come up seconds after its peers, and a
one-shot connect would abort workers that only needed to wait. The retry
policy is the shared one (runtime/retry.py: capped exponential backoff +
full jitter, seeded per process id so a whole pod's restarts de-synchronize).
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass
from typing import List, Optional, Tuple

from .retry import retry_with_backoff

# rendezvous deadline: how long a process keeps redialing the coordinator
# before giving up (env-overridable for tests and slow pod bring-up)
_RENDEZVOUS_DEADLINE_S = float(
    os.environ.get("POSEIDON_RENDEZVOUS_DEADLINE_S", "60"))


def env_world() -> Tuple[int, int, Optional[str]]:
    """(rank, n_procs, coordinator) from the launcher env contract
    (POSEIDON_PROC_ID / POSEIDON_NUM_PROCS / POSEIDON_COORDINATOR).

    Elastic contract: under ``--async_ssp`` the roster is a STARTING
    point, not a bound — a process launched with ``POSEIDON_PROC_ID >=
    POSEIDON_NUM_PROCS`` is an elastic JOINER: it dials the same
    coordinator, and the async tier admits it into the live job at the
    service's rendezvous anchor clock (no relaunch, no new hostfile).
    The canonical home is here (jax-free, like the rest of the control
    plane) so socket-tier processes can read the contract without paying
    the jax import."""
    return (int(os.environ.get("POSEIDON_PROC_ID", "0")),
            int(os.environ.get("POSEIDON_NUM_PROCS", "1")),
            os.environ.get("POSEIDON_COORDINATOR"))


def is_elastic_joiner(rank: int, n_procs: int) -> bool:
    """True when this process is joining a live async-SSP job from outside
    the launch roster (the POSEIDON_PROC_ID >= POSEIDON_NUM_PROCS
    convention above)."""
    return rank >= n_procs


@dataclass(frozen=True)
class SliceAssignment:
    """This process's place in the two-tier fabric (parallel/fabric.py):
    which slice it belongs to, how many processes the slice spans, and its
    rank within the slice (0 = the designated DCN leader)."""

    slice_id: int
    slice_size: int
    rank_in_slice: int
    n_slices: int        # whole slices in the launch roster

    @property
    def is_leader(self) -> bool:
        return self.rank_in_slice == 0

    @property
    def is_joiner_slice(self) -> bool:
        """The slice sits outside the launch roster — the slice-granular
        analog of :func:`is_elastic_joiner` (admitted mid-run, not
        launched)."""
        return self.slice_id >= self.n_slices


def slice_env(n_visible_devices: Optional[int] = None
              ) -> Optional[Tuple[int, int]]:
    """(slice_id, slice_size) from POSEIDON_SLICE_ID / POSEIDON_SLICE_SIZE,
    or None when neither is set — plain per-process mode stays byte-for-
    byte unchanged. Refusals are loud and permanent (a half-set or
    impossible slice contract would otherwise become N silently
    mis-sharded runs):

    - one variable set without the other;
    - slice_size < 1 or slice_id < 0;
    - a slice larger than the visible device count (every member pins at
      least one device, so slice_size > n_visible_devices cannot be
      scheduled; pass the count from the jax side — this module stays
      jax-free)."""
    sid = os.environ.get("POSEIDON_SLICE_ID")
    ssz = os.environ.get("POSEIDON_SLICE_SIZE")
    if sid is None and ssz is None:
        return None
    if sid is None or ssz is None:
        raise ValueError(
            "POSEIDON_SLICE_ID and POSEIDON_SLICE_SIZE must be set "
            f"together (got SLICE_ID={sid!r}, SLICE_SIZE={ssz!r}); the "
            "slice contract is all-or-nothing")
    slice_id, slice_size = int(sid), int(ssz)
    if slice_id < 0:
        raise ValueError(f"POSEIDON_SLICE_ID must be >= 0, got {slice_id}")
    if slice_size < 1:
        raise ValueError(
            f"POSEIDON_SLICE_SIZE must be >= 1, got {slice_size}")
    if n_visible_devices is not None and slice_size > n_visible_devices:
        raise ValueError(
            f"slice {slice_id} spans {slice_size} processes but only "
            f"{n_visible_devices} device(s) are visible — a slice member "
            f"cannot share a device; shrink POSEIDON_SLICE_SIZE or widen "
            f"the device set")
    return slice_id, slice_size


def slice_world(n_visible_devices: Optional[int] = None
                ) -> Optional[SliceAssignment]:
    """The full slice contract for THIS process, or None when the slice
    env is unset. Slices own CONTIGUOUS rank blocks — slice k is exactly
    processes [k*size, (k+1)*size) — so every process can derive the
    whole assignment from its own env with no coordination, and any two
    processes that disagree are refused loudly:

    - a rank outside its declared slice's block is an OVERLAPPING
      assignment (some other slice already owns that rank);
    - a launch roster that is not a whole number of slices leaves orphan
      ranks no slice owns.

    A joiner slice (slice_id >= roster slices) follows the elastic
    convention: its ranks sit past the roster, the fabric admits the
    whole slice mid-run."""
    se = slice_env(n_visible_devices)
    if se is None:
        return None
    slice_id, slice_size = se
    rank, n_procs, _ = env_world()
    if n_procs % slice_size:
        raise ValueError(
            f"launch roster of {n_procs} processes is not a whole number "
            f"of {slice_size}-process slices — "
            f"{n_procs % slice_size} rank(s) would belong to no slice")
    rank_in_slice = rank - slice_id * slice_size
    if not (0 <= rank_in_slice < slice_size):
        owner = rank // slice_size
        raise ValueError(
            f"overlapping slice assignment: rank {rank} declares slice "
            f"{slice_id} but the contiguous-block contract puts it in "
            f"slice {owner} (slice k owns ranks [k*{slice_size}, "
            f"(k+1)*{slice_size}))")
    return SliceAssignment(slice_id=slice_id, slice_size=slice_size,
                           rank_in_slice=rank_in_slice,
                           n_slices=n_procs // slice_size)


@dataclass(frozen=True)
class Host:
    id: int
    ip: str
    port: int


def parse_hostfile(path: str) -> List[Host]:
    hosts: List[Host] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            if len(parts) != 3:
                raise ValueError(f"{path}: bad hostfile line {line!r} "
                                 f"(want '<id> <ip> <port>')")
            hosts.append(Host(int(parts[0]), parts[1], int(parts[2])))
    ids = [h.id for h in hosts]
    if ids != list(range(len(hosts))):
        raise ValueError(f"{path}: host ids must be 0..N-1 in order, got {ids}")
    return hosts


# jax.distributed.initialize signals both transient handshake failures and
# permanent misconfiguration as RuntimeError; only messages matching these
# look like a coordinator that has not come up YET (worth redialing) —
# anything else ("should only be called once", mismatched world size, ...)
# must fail fast, and must NOT trigger the shutdown teardown, which would
# destroy a healthy live client on a double-init call.
_TRANSIENT_RENDEZVOUS = ("deadline", "unavailable", "connect", "timed out",
                         "timeout", "refused")


def _initialize_with_retry(coordinator_address: str,
                           num_processes: Optional[int],
                           process_id: Optional[int]) -> None:
    """jax.distributed.initialize with the shared backoff policy: keep
    redialing a not-yet-listening coordinator instead of aborting the
    worker (the coordinator process routinely starts seconds later under
    a launcher that brings processes up in any order)."""
    import jax

    class _Transient(OSError):
        pass

    def attempt() -> None:
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id)
        except RuntimeError as e:
            low = str(e).lower()
            if not any(s in low for s in _TRANSIENT_RENDEZVOUS):
                raise  # permanent misconfiguration: fail fast, no teardown
            # a failed handshake can leave a half-initialized client that
            # must be torn down before the redial
            try:
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001 — best-effort teardown
                pass
            raise _Transient(str(e)) from e

    retry_with_backoff(
        attempt, deadline=_RENDEZVOUS_DEADLINE_S, base=0.2, cap=5.0,
        rng=random.Random(process_id if process_id is not None else 0),
        retry_on=(OSError,))


def init_distributed(hostfile: Optional[str] = None,
                     node_id: Optional[int] = None,
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None) -> int:
    """Initialize the JAX distributed runtime from a hostfile (or explicit
    coordinator config / env). Host 0's entry is the coordinator — the
    name-node analog. Returns this process's id. No-op when single-process."""
    if hostfile is not None:
        hosts = parse_hostfile(hostfile)
        if len(hosts) == 1:
            return 0
        if node_id is None:
            raise ValueError("node_id is required with a multi-host hostfile")
        coord = f"{hosts[0].ip}:{hosts[0].port}"
        _initialize_with_retry(coord, len(hosts), node_id)
        return node_id
    if coordinator_address is not None:
        # node_id=None passes through: jax.distributed auto-detects the
        # process id from the cluster environment
        _initialize_with_retry(coordinator_address, num_processes, node_id)
        return node_id or 0
    # Env-driven: the scripts/launch.py --local path sets these.
    coord = os.environ.get("POSEIDON_COORDINATOR")
    if coord:
        n = int(os.environ["POSEIDON_NUM_PROCS"])
        pid = int(os.environ["POSEIDON_PROC_ID"])
        if n > 1:
            _initialize_with_retry(coord, n, pid)
        return pid
    return 0
