"""CLI: the caffe_main-equivalent command registry — ALL brew commands live.

The reference's ``caffe_main <command>`` exposes train and device_query, with
test/time compiled out behind #if 0 (tools/caffe_main.cpp:49-350). Here every
command works: train, test, time, device_query, plus the dataset tools and the
feature extractor.

    python -m poseidon_tpu train --solver=examples/mnist/lenet_solver.prototxt
    python -m poseidon_tpu test --model=net.prototxt --weights=x.caffemodel --iterations=50
    python -m poseidon_tpu time --model=net.prototxt --iterations=50
    python -m poseidon_tpu device_query
    python -m poseidon_tpu convert_imageset|compute_image_mean|partition_data|extract_features ...
"""

from __future__ import annotations

import argparse
import os
import sys
import time as _time
from typing import List, Optional

import numpy as np


def cmd_device_query(args) -> int:
    import jax
    for d in jax.devices():
        print(f"device {d.id}: platform={d.platform} kind={d.device_kind} "
              f"process={d.process_index}")
    print(f"process_count={jax.process_count()} "
          f"local_devices={jax.local_device_count()}")
    return 0


def _engine_from_args(args, phase_nets=True):
    from ..parallel.strategies import CommConfig
    from ..proto.messages import load_solver
    from .engine import Engine

    import dataclasses
    sp = getattr(args, "_loaded_solver", None) or load_solver(args.solver)
    # sentinel None = "no explicit flag": the TunedPlan resolution in
    # cmd_train already replaced these with plan/default values; a direct
    # _engine_from_args caller (tests) gets the built-in defaults
    arena_mb = getattr(args, "arena_bucket_mb", None)
    # --wire_dtype rides TWO tiers: the compiled collectives (CommConfig,
    # bf16/f16 only) and the managed DCN payload codec (async tier, which
    # also takes int8). int8 never enters the compiled config — the local
    # mesh stays at gradient dtype while the DCN frames compress.
    wd_flag = getattr(args, "wire_dtype", None) or None
    if wd_flag == "int8":
        if not getattr(args, "async_ssp", False):
            raise SystemExit(
                "--wire_dtype int8 is a managed-tier (async DCN) wire "
                "format; compiled collectives take bf16/f16")
        wd_flag = None
    comm = CommConfig(default_strategy=args.strategy,
                      reduce=args.grad_reduce,
                      topk_policy=getattr(args, "topk_policy", "magnitude"),
                      wire_dtype=wd_flag,
                      topk_block=getattr(args, "topk_block", 0) or None,
                      dwbp_bucket_mb=(
                          None if getattr(args, "dwbp_bucket_mb", -1.0) < 0
                          else args.dwbp_bucket_mb),
                      param_arena=(getattr(args, "param_arena", "true")
                                   == "true"),
                      arena_bucket_mb=4.0 if arena_mb is None else arena_mb,
                      server_logic=getattr(args, "server_logic", "inc"),
                      adarev_init_step=getattr(args, "adarev_init_step", 0.1))
    if args.sfb_auto:
        # same config, default strategy reset (auto_strategies fills in SFB)
        comm = dataclasses.replace(comm, default_strategy="dense")
    mesh = None
    mesh_cfg = None
    mesh_spec = getattr(args, "mesh", "")
    if mesh_spec:
        from ..config import MeshConfig
        mesh_cfg = MeshConfig.parse(mesh_spec)
        if getattr(args, "dcn_slices", 0) > 1:
            raise SystemExit("--mesh and --dcn_slices do not compose: the "
                             "named mesh's axes carry the whole topology")
        import jax
        if mesh_cfg.n_devices > jax.device_count():
            raise SystemExit(
                f"--mesh {mesh_spec} needs {mesh_cfg.n_devices} devices; "
                f"{jax.device_count()} available")
    dcn_slices = getattr(args, "dcn_slices", 0)
    if dcn_slices > 1:
        # two-tier mesh: slices over the slow (DCN) axis, devices within a
        # slice over the fast (ICI) axis; TOPK layers compress inter-slice
        import jax
        from ..parallel import make_mesh
        n = jax.device_count()
        if n % dcn_slices:
            raise SystemExit(f"--dcn_slices {dcn_slices} does not divide "
                             f"{n} devices")
        mesh = make_mesh(axes=("dcn", "data"),
                         shape=(dcn_slices, n // dcn_slices))
        comm.dcn_axis = "dcn"
    staleness = getattr(args, "staleness", 0)
    async_cfg = None
    if getattr(args, "async_ssp", False):
        # the staleness bound belongs to the ASYNC tier; the local step
        # stays plain sync SGD on this process's own mesh
        async_cfg = {"staleness": staleness,
                     "sync_every": getattr(args, "async_sync_every", 1)}
        # fault-tolerance knobs: negative flag values mean "use the
        # FaultConfig defaults" (config.py) — only explicit settings ride
        for key, flag in (("heartbeat_s", "async_heartbeat_s"),
                          ("liveness_timeout_s",
                           "async_liveness_timeout_s"),
                          ("reconnect_deadline_s",
                           "async_reconnect_deadline_s"),
                          ("gate_timeout_s", "async_gate_timeout_s"),
                          ("first_gate_timeout_s",
                           "async_first_gate_timeout_s")):
            v = getattr(args, flag, -1.0)
            if v is not None and v >= 0:
                async_cfg[key] = v
        # managed communication (SSPAggr): negative budget = the
        # ManagedCommConfig default (off); 0 is an explicit "unlimited"
        v = getattr(args, "comm_budget_mbps", -1.0)
        if v is not None and v >= 0:
            async_cfg["comm_budget_mbps"] = v
        v = getattr(args, "comm_priority_frac", -1.0)
        if v is not None and v > 0:
            async_cfg["comm_priority_frac"] = v
        if getattr(args, "comm_adaptive", False):
            async_cfg["comm_adaptive"] = True
        # wire dtype resolution, flag > TunedPlan > default: an explicit
        # flag rides here (overriding the ManagedCommConfig the TunedPlan
        # resolution installed); args.wire_dtype itself is NEVER mutated,
        # so a plan-resolved dtype cannot leak into the compiled-tier
        # CommConfig above
        wd = getattr(args, "wire_dtype", "") or ""
        if wd:
            async_cfg["comm_wire_dtype"] = wd
        # two-tier fabric: this process leads an SPMD slice and the DCN
        # worker identity is the slice id (runtime/async_tier.FabricTier;
        # needs the POSEIDON_SLICE_ID/POSEIDON_SLICE_SIZE env contract)
        if getattr(args, "slice", False):
            async_cfg["slice"] = True
        staleness = 0
    elif getattr(args, "slice", False):
        raise SystemExit("--slice composes the two-tier fabric on top of "
                         "the async tier; it requires --async_ssp")
    metrics_port = getattr(args, "metrics_port", -1)
    spd = getattr(args, "steps_per_dispatch", None)
    return Engine(sp, comm=comm, mesh=mesh, mesh_cfg=mesh_cfg,
                  output_dir=args.output_dir,
                  staleness=staleness, sfb_auto=args.sfb_auto,
                  steps_per_dispatch=1 if spd is None else spd,
                  device_transform=getattr(args, "device_transform", False),
                  async_ssp=async_cfg,
                  device_prefetch=getattr(args, "device_prefetch", None),
                  max_in_flight=getattr(args, "max_in_flight", None),
                  async_snapshot=getattr(args, "async_snapshot", None),
                  trace_out=getattr(args, "trace_out", "") or None,
                  metrics_port=metrics_port if metrics_port >= 0 else None,
                  hbm_budget_gb=getattr(args, "hbm_budget_gb", None),
                  remat=getattr(args, "remat", None) or None)


def _enable_compile_cache_from_args(args) -> None:
    """Stage the fast-restart layers (persistent XLA compile cache + AOT
    step store) before any program is compiled. Shared by train/serve/
    bench_serve; empty --compile_cache_dir leaves both off."""
    from .. import config
    # the flag wins; the POSEIDON_COMPILE_CACHE_DIR env default (seeded
    # into CompileCacheConfig at import) covers launcher-managed fleets
    cache_dir = (getattr(args, "compile_cache_dir", "")
                 or config.compile_cache_config().cache_dir)
    if not cache_dir:
        return
    from .compile_cache import enable_compile_cache
    resolved = enable_compile_cache(cache_dir)
    config.set_compile_cache_config(
        cache_dir=resolved,
        aot_steps=getattr(args, "aot_steps", "true") == "true")
    from .metrics import log
    log(f"compile cache: persistent XLA cache at {resolved} "
        f"(aot_steps={getattr(args, 'aot_steps', 'true')})")


def _apply_tuned_plan_train(args) -> None:
    """TunedPlan auto-load for cmd_train (runtime/tuned_plan.py): fold the
    persisted plan for (train net, backend, n_devices) under the EXPLICIT
    flags — flag > plan > built-in default, per knob — install the policy
    (conv_layout / conv_strategy / pipeline config), publish the
    resolution (the engine writes its provenance into stats.yaml), and
    mutate the sentinel-defaulted args in place with the resolved values.
    ``--tuned_plan off`` skips the store entirely (defaults + flags
    only)."""
    from .metrics import log
    from .tuned_plan import (apply_training_resolution, load_plan, resolve,
                             store_dir)

    explicit = {}
    if getattr(args, "conv_layout", ""):
        explicit["conv_layout"] = args.conv_layout.upper()
    if getattr(args, "conv_strategy", ""):
        explicit["conv_strategy"] = args.conv_strategy
    if getattr(args, "arena_bucket_mb", None) is not None:
        explicit["arena_bucket_mb"] = args.arena_bucket_mb
    if getattr(args, "mesh", ""):
        explicit["mesh"] = args.mesh
    if getattr(args, "device_prefetch", None) is not None:
        explicit["device_prefetch"] = args.device_prefetch
    if getattr(args, "max_in_flight", None) is not None:
        explicit["max_in_flight"] = args.max_in_flight
    if getattr(args, "steps_per_dispatch", None) is not None:
        explicit["steps_per_dispatch"] = args.steps_per_dispatch
    if getattr(args, "wire_dtype", ""):
        explicit["wire_dtype"] = args.wire_dtype
    if getattr(args, "remat", None) is not None:
        explicit["remat"] = args.remat
    if getattr(args, "hbm_budget_gb", None) is not None:
        explicit["hbm_budget_gb"] = args.hbm_budget_gb

    doc, store = None, ""
    if getattr(args, "tuned_plan", "auto") != "off":
        from ..proto.messages import load_solver
        from .engine import resolve_nets
        # parse once; _engine_from_args reuses the loaded SolverParameter
        # instead of re-reading the solver + net prototxt from disk
        args._loaded_solver = load_solver(args.solver)
        train_param, _ = resolve_nets(args._loaded_solver)
        model = (train_param.name or "net").lower()
        store = store_dir()
        doc = load_plan(model, cache_dir=store)
        if doc is None:
            log(f"[tuned_plan] no plan for {model!r} in {store}; "
                f"built-in defaults apply (run `python -m poseidon_tpu "
                f"tune --model ...` to measure one)")
    res = resolve(doc, explicit, store=store)
    knobs = apply_training_resolution(res)
    log(f"[tuned_plan] {res.describe()}")
    args.arena_bucket_mb = knobs["arena_bucket_mb"]
    args.mesh = knobs["mesh"]
    args.steps_per_dispatch = knobs["steps_per_dispatch"]
    args.device_prefetch = knobs["device_prefetch"]
    args.max_in_flight = knobs["max_in_flight"]
    args.remat = knobs["remat"]
    args.hbm_budget_gb = knobs["hbm_budget_gb"]


def cmd_train(args) -> int:
    from .cluster import init_distributed
    _enable_compile_cache_from_args(args)
    if args.bf16:
        from .. import config
        config.set_perf_policy()
    # TunedPlan resolution replaces the old ad-hoc per-flag policy pokes:
    # conv_strategy / conv_layout land in the numeric policy, the pipeline
    # knobs in PipelineConfig, and the engine-level knobs back onto args —
    # explicit flags always win, plan values fill the gaps, built-in
    # defaults bat last, with every source recorded in stats.yaml
    _apply_tuned_plan_train(args)
    if getattr(args, "async_ssp", False):
        # async-SSP: the processes stay INDEPENDENT jax runtimes — no
        # jax.distributed world, no collective rendezvous; the only
        # cross-process channel is the tier's parameter service. The tier
        # reads the LOCAL launcher's env contract; a hostfile launch does
        # not set it, and silently degrading to N isolated full-data runs
        # would be worse than refusing.
        import os as _os
        if args.hostfile and "POSEIDON_PROC_ID" not in _os.environ:
            raise SystemExit(
                "--async_ssp currently rides the launch_local env contract "
                "(POSEIDON_PROC_ID/NUM_PROCS/COORDINATOR); for a hostfile "
                "cluster, start each node under that env (see "
                "scripts/launch.py) instead of --hostfile/--node_id")
    else:
        init_distributed(hostfile=args.hostfile or None,
                         node_id=args.node_id if args.node_id >= 0 else None)
    eng = _engine_from_args(args)
    eng.profile_steps = args.profile
    if args.snapshot == "auto":
        # engine-level auto-resume: sweep stale snapshot tmp litter a
        # killed predecessor left behind, then restore the newest
        # solverstate under the solver's snapshot prefix
        restored = eng.auto_resume()
        if restored is None and args.weights:
            # first run of an auto-resume launch still honors init weights
            eng.restore_from(args.weights)
    elif args.snapshot:
        eng.restore_from(args.snapshot)
    elif args.weights:
        eng.restore_from(args.weights)
    try:
        eng.train()
    finally:
        eng.close()
    return 0


def cmd_test(args) -> int:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..core.net import Net
    from ..data.pipeline import build_phase_pipelines
    from ..data.workload import Shard
    from ..parallel import build_eval_step, make_mesh
    from ..proto.messages import load_net
    from .checkpoint import load_caffemodel
    from .cluster import init_distributed

    init_distributed(hostfile=args.hostfile or None,
                     node_id=args.node_id if args.node_id >= 0 else None)
    net_param = load_net(args.model)
    mesh = make_mesh()
    rank, nproc = jax.process_index(), jax.process_count()
    # each host scores a DISJOINT shard of the record space and contributes
    # only its addressable devices' rows (Engine._build_pipelines semantics)
    pipes, shapes = build_phase_pipelines(
        net_param, "TEST", batch_multiplier=jax.local_device_count(),
        shard=Shard(rank, nproc))
    net = Net(net_param, "TEST", source_shapes=shapes)
    params = net.init(jax.random.PRNGKey(0))
    if args.weights:
        params = load_caffemodel(args.weights, net, params)
    ev = build_eval_step(net, mesh)
    sharding = NamedSharding(mesh, P("data"))
    acc = {}
    for _ in range(args.iterations):
        batch = {}
        for pipe in pipes:
            for k, v in next(pipe).items():
                if nproc > 1:
                    batch[k] = jax.make_array_from_process_local_data(
                        sharding, v)
                else:
                    batch[k] = jax.device_put(v, sharding)
        for k, v in ev(params, batch).items():
            acc[k] = acc.get(k, 0.0) + float(v)
    if rank == 0:
        for k in sorted(acc):
            print(f"{k}: {acc[k] / args.iterations:.4f}")
    for p in pipes:
        p.close()
    return 0


def cmd_time(args) -> int:
    """Per-layer forward timing + whole-graph forward/backward timing
    (the reference's `caffe time`, tools/caffe_main.cpp:256-328)."""
    import jax
    import jax.numpy as jnp
    from ..core.net import Net
    from ..proto.messages import load_net

    net_param = load_net(args.model)
    shapes = {}
    if net_param.input:
        net = Net(net_param, "TRAIN")
    else:
        # synthesize source shapes for data layers
        from ..core.net import filter_net
        from ..proto.messages import NetState
        from ..core.layers import DATA_SOURCE_TYPES
        for lp in filter_net(net_param, NetState(phase="TRAIN")):
            if lp.canonical_type() in DATA_SOURCE_TYPES:
                from ..data.pipeline import layer_batch_size
                b = layer_batch_size(lp) or args.batch_size
                chw = None
                src = (lp.data_param.source or lp.image_data_param.source
                       or lp.hdf5_data_param.source
                       or lp.window_data_param.source)
                if src:
                    # read one record for the true (C, H, W) — a synthesized
                    # 3x224x224 guess would mis-size every downstream layer
                    try:
                        from ..data.pipeline import build_source
                        from ..data.workload import Shard
                        s = build_source(lp, Shard(0, 1))
                        arr, _ = s.read(0)
                        chw = arr.shape
                    except Exception:
                        chw = None
                if chw is None:
                    c = lp.transform_param.crop_size or 224
                    chw = (3, c, c)
                if lp.transform_param.crop_size:
                    chw = (chw[0], lp.transform_param.crop_size,
                           lp.transform_param.crop_size)
                shapes[lp.top[0]] = (b,) + tuple(chw)
                if len(lp.top) > 1:
                    shapes[lp.top[1]] = (b,)
        net = Net(net_param, "TRAIN", source_shapes=shapes)
    # the benchmark batch is whatever the model actually declares
    batch = net.blob_shapes[net.input_names[0]][0]

    rng = jax.random.PRNGKey(0)
    params = net.init(rng)
    inputs = {name: (jnp.zeros(net.blob_shapes[name], jnp.float32)
                     if len(net.blob_shapes[name]) > 1 else
                     jnp.zeros(net.blob_shapes[name], jnp.int32))
              for name in net.input_names}

    fwd = jax.jit(lambda p, x: net.apply(p, x, train=True,
                                         rng=jax.random.PRNGKey(1)).loss)
    grad = jax.jit(jax.grad(lambda p, x: net.apply(
        p, x, train=True, rng=jax.random.PRNGKey(1)).loss))

    jax.block_until_ready(fwd(params, inputs))  # compile
    t0 = _time.perf_counter()
    for _ in range(args.iterations):
        out = fwd(params, inputs)
    jax.block_until_ready(out)
    fwd_ms = (_time.perf_counter() - t0) / args.iterations * 1e3

    jax.block_until_ready(jax.tree_util.tree_leaves(grad(params, inputs))[0])
    t0 = _time.perf_counter()
    for _ in range(args.iterations):
        g = grad(params, inputs)
    jax.block_until_ready(jax.tree_util.tree_leaves(g)[0])
    fb_ms = (_time.perf_counter() - t0) / args.iterations * 1e3

    # Per-layer forward timing (the reference's per-layer breakdown,
    # caffe_main.cpp:256-328). Layers are timed in isolation, so totals can
    # differ from the fused whole-graph time — that fusion gap is itself
    # useful signal.
    if args.per_layer:
        from ..core.layers import ApplyCtx
        print(f"{'layer':<24}{'type':<22}{'fwd ms':>10}{'bwd ms':>10}")
        for layer in net.layers:
            bottoms = [jnp.zeros(net.blob_shapes[bname], jnp.float32)
                       for bname in layer.lp.bottom]
            lp_params = {pd.name: params[layer.name][pd.name]
                         for pd in layer.params} if layer.params else {}

            def run(ps, bs, _l=layer):
                ctx = ApplyCtx(train=True, rng=jax.random.PRNGKey(0))
                return _l.apply(ps, bs, ctx)

            def timed(fn, *fargs):
                jitted = jax.jit(fn)
                jax.block_until_ready(jitted(*fargs))
                t0 = _time.perf_counter()
                for _ in range(args.iterations):
                    out = jitted(*fargs)
                leaves = jax.tree_util.tree_leaves(out)
                jax.block_until_ready(leaves[0] if leaves else jnp.zeros(()))
                return (_time.perf_counter() - t0) / args.iterations * 1e3

            try:
                fwd_l = timed(run, lp_params, bottoms)
            except Exception as e:  # e.g. int-labeled losses fed zeros
                print(f"{layer.name:<24}{layer.TYPE:<22}{'skip':>10}"
                      f"{'skip':>10} ({e})")
                continue
            # per-layer backward: grad wrt params+bottoms of a scalarized
            # output (the reference's Backward timing, caffe_main.cpp:300+).
            # jax.grad re-runs the forward inside, so subtract fwd time to
            # report the backward alone like the reference does.
            try:
                def bwd(ps, bs, _l=layer):
                    out = run(ps, bs, _l=_l)
                    return sum(jnp.sum(o.astype(jnp.float32))
                               for o in jax.tree_util.tree_leaves(out))

                fb_l = timed(jax.grad(bwd, argnums=(0, 1)),
                             lp_params, bottoms)
                bwd_l = max(fb_l - fwd_l, 0.0)
                print(f"{layer.name:<24}{layer.TYPE:<22}{fwd_l:>10.3f}"
                      f"{bwd_l:>10.3f}")
            except Exception:  # non-differentiable layer (data/accuracy/...)
                print(f"{layer.name:<24}{layer.TYPE:<22}{fwd_l:>10.3f}"
                      f"{'-':>10}")

    # Static per-layer comm accounting over a hypothetical mesh — what each
    # strategy moves per step and what it saves vs dense (stats.hpp analog).
    if args.per_layer and args.comm_devices > 1:
        from ..parallel import CommConfig, auto_strategies
        from .comm_stats import comm_summary, layer_comm_table
        n = args.comm_devices
        slices = args.dcn_slices
        # purely static accounting — a {axis: size} shape dict models the
        # requested topology without needing that many physical devices
        wire = getattr(args, "wire_dtype", "") or None
        blockk = getattr(args, "topk_block", 0) or None
        if slices > 1:
            if n % slices:
                raise SystemExit(f"--dcn_slices {slices} does not divide "
                                 f"--comm_devices {n}")
            mesh_shape = {"dcn": slices, "data": n // slices}
            cc = CommConfig(dcn_axis="dcn", default_strategy=args.strategy,
                            wire_dtype=wire, topk_block=blockk)
        else:
            mesh_shape = {"data": n}
            cc = CommConfig(default_strategy=args.strategy,
                            wire_dtype=wire, topk_block=blockk)
        if args.sfb_auto:
            cc.layer_strategies.update(auto_strategies(net))
        table = layer_comm_table(net, cc, mesh_shape)
        print(f"\nComm bytes/step/device over {n} devices"
              + (f" ({slices} DCN slices)" if slices > 1 else "") + ":")
        print(f"{'layer':<24}{'strategy':<8}{'ici B':>12}{'dcn B':>12}"
              f"{'vs dense':>10}{'est ms':>9}")
        for lname, row in table.items():
            print(f"{lname:<24}{row['strategy']:<8}"
                  f"{row['ici_bytes_per_step']:>12}"
                  f"{row['dcn_bytes_per_step']:>12}"
                  f"{str(row['savings_vs_dense'] or '-'):>10}"
                  f"{row['est_comm_ms']:>9}")
        s = comm_summary(table, fb_ms)
        print(f"total: {s['total_bytes_per_step']} B/step/dev, "
              f"{s['savings_vs_dense'] or '-'}x vs dense, "
              f"est comm {s['est_comm_ms_per_step']} ms "
              f"({s.get('est_comm_fraction_if_unoverlapped', 0):.0%} of "
              f"measured step if unoverlapped)")

    print(f"Average Forward pass: {fwd_ms:.3f} ms")
    print(f"Average Forward-Backward: {fb_ms:.3f} ms")
    print(f"Throughput: {batch / (fb_ms / 1e3):.1f} images/s "
          f"(batch {batch})")
    return 0


# --------------------------------------------------------------------------- #
# serving tier (poseidon_tpu/serving/)
# --------------------------------------------------------------------------- #

_BENCH_SERVE_NET = """
name: "bench_serve_synthetic"
input: "data"
input_dim: 1 input_dim: 3 input_dim: 32 input_dim: 32
layers { name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 16 kernel_size: 3
    weight_filler { type: "xavier" } } }
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "pool1" type: POOLING bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layers { name: "fc" type: INNER_PRODUCT bottom: "pool1" top: "fc"
  inner_product_param { num_output: 10 weight_filler { type: "xavier" } } }
layers { name: "prob" type: SOFTMAX bottom: "fc" top: "prob" }
"""


def _resolve_serve_buckets(args) -> str:
    """The serving bucket ladder through TunedPlan resolution: an explicit
    --buckets flag wins; else the persisted plan for the deploy net (keyed
    like train's: net name, backend, n_devices) supplies its measured
    ladder; else the built-in default. The source is logged so a serving
    log always says where its ladder came from."""
    from .metrics import log
    from .tuned_plan import BUILTIN_DEFAULTS, load_plan

    spec = getattr(args, "buckets", "")
    if spec:
        return spec
    if getattr(args, "model", "") and \
            getattr(args, "tuned_plan", "auto") != "off":
        try:
            from ..proto.messages import load_net
            model_name = (load_net(args.model).name or "").lower()
        except Exception as e:  # noqa: BLE001 — the executor build will
            model_name = ""     # surface a real model problem loudly
            log(f"[tuned_plan] could not read {args.model!r} for plan "
                f"lookup ({type(e).__name__}: {e}); default ladder")
        if model_name:
            doc = load_plan(model_name)
            ladder = (doc or {}).get("knobs", {}).get("serve_buckets")
            if ladder:
                log(f"[tuned_plan] serve_buckets={ladder} "
                    f"(plan {str(doc.get('key', '?'))[:12]})")
                return ladder
    return BUILTIN_DEFAULTS["serve_buckets"]


def _build_serving_executor(model: str, weights: str, buckets: str,
                            device=None):
    """Shared by serve/bench_serve: deploy net (or the built-in synthetic
    one) + optional weights -> warmed BucketedExecutor, optionally pinned
    to one local device (the fleet's placement unit)."""
    from ..serving.executor import BucketedExecutor, parse_buckets
    bucket_sizes = parse_buckets(buckets)
    if model:
        return BucketedExecutor.from_files(model, weights or None,
                                           buckets=bucket_sizes,
                                           device=device)
    import jax
    from ..core.net import Net
    from ..proto.messages import load_net_from_string
    net = Net(load_net_from_string(_BENCH_SERVE_NET), "TEST")
    params = net.init(jax.random.PRNGKey(0))
    if weights:
        from ..serving.executor import load_serving_params
        params = load_serving_params(net, params, weights)
    return BucketedExecutor(net, params, buckets=bucket_sizes,
                            device=device)


def _resolve_fleet_devices(spec: str, n_replicas: int):
    """``--devices "0,2,3"`` -> the named jax devices; "" -> round-robin
    over every local device when the fleet has more than one replica (a
    single replica keeps the default device). Asking for an index that
    does not exist fails loudly — the make_mesh lesson: never silently
    truncate a placement request."""
    import jax
    local = jax.devices()
    if spec:
        try:
            idxs = [int(tok) for tok in spec.split(",") if tok.strip()]
        except ValueError:
            raise SystemExit(f"--devices {spec!r}: expected comma-separated "
                             f"device indices") from None
        bad = [i for i in idxs if i < 0 or i >= len(local)]
        if bad:
            raise SystemExit(f"--devices {spec!r}: no such device index "
                             f"{bad} (have {len(local)} local devices)")
        return [local[i] for i in idxs]
    if n_replicas <= 1:
        return []
    return list(local)


def build_serving_fleet(model: str, weights: str, buckets: str,
                        n_replicas: int, devices_spec: str = "",
                        max_delay_s: float = 0.005, max_queue: int = 64,
                        warm_async: bool = False, **manager_kw):
    """N warmed replicas under one :class:`ReplicaManager`, round-robin
    pinned across the resolved devices (replicas > devices is fine — CPU
    proxies and oversubscribed hosts still get N independent engines)."""
    from ..serving.fleet import ReplicaManager
    devices = _resolve_fleet_devices(devices_spec, n_replicas)

    def factory(device):
        return _build_serving_executor(model, weights, buckets,
                                       device=device)

    return ReplicaManager.build(factory, n_replicas, devices=devices,
                                warm_async=warm_async,
                                max_delay_s=max_delay_s,
                                max_queue=max_queue, **manager_kw)


LLM_PRESETS = ("tiny", "gpt_small")


def _resolve_llm_knobs(args) -> dict:
    """The LLM serving knobs through TunedPlan resolution (same idiom as
    :func:`_resolve_serve_buckets`): a persisted plan's measured
    ``llm_page_size``/``llm_decode_rungs``/``llm_prompt_buckets`` win over
    the built-in defaults; the source is logged either way."""
    from .metrics import log
    from .tuned_plan import BUILTIN_DEFAULTS, load_plan

    keys = ("llm_page_size", "llm_decode_rungs", "llm_prompt_buckets")
    knobs = {k: BUILTIN_DEFAULTS[k] for k in keys}
    if getattr(args, "tuned_plan", "auto") != "off":
        doc = load_plan(args.model)
        hits = {k: (doc or {}).get("knobs", {}).get(k) for k in keys}
        knobs.update({k: v for k, v in hits.items() if v})
        if any(hits.values()):
            log(f"[tuned_plan] llm serving knobs {knobs} "
                f"(plan {str((doc or {}).get('key', '?'))[:12]})")
    return knobs


def _build_generate_executor(preset: str, knobs: dict, device=None):
    """A warmed paged-KV :class:`GenerateExecutor` over a named transformer
    preset. ``--generate`` serving has no snapshot format yet, so params
    are preset-initialized (the same smoke contract as an empty
    ``--weights`` on the CNN path)."""
    import jax
    from ..models.transformer import (TransformerConfig, gpt_small_config,
                                      init_params)
    from ..serving.continuous import GenerateExecutor, parse_rungs

    if preset == "gpt_small":
        cfg = gpt_small_config(max_seq=512, remat=False)
    elif preset == "tiny":
        cfg = TransformerConfig(vocab_size=256, d_model=32, n_heads=4,
                                n_layers=2, d_ff=128, max_seq=128)
    else:
        raise SystemExit(
            f"--generate serves a transformer preset, not a deploy "
            f"prototxt; --model must be one of {'|'.join(LLM_PRESETS)} "
            f"(got {preset!r})")
    params = init_params(cfg, jax.random.PRNGKey(0))
    # a preset smaller than the default ladder drops the buckets it
    # cannot hold rather than refusing to serve
    buckets = tuple(b for b in parse_rungs(knobs["llm_prompt_buckets"])
                    if b < cfg.max_seq)
    return GenerateExecutor(
        cfg, params, page_size=int(knobs["llm_page_size"]),
        decode_rungs=parse_rungs(knobs["llm_decode_rungs"]),
        prompt_buckets=buckets, device=device)


def _cmd_serve_generate(args) -> int:
    """The LLM branch of ``serve``: paged-KV continuous batching behind
    the same front door — ``generate`` wire op, streaming ``gen_chunk``
    frames, fleet routing/failover when ``--replicas > 1``."""
    import json
    import signal

    from ..config import fleet_config
    from ..serving.server import InferenceServer
    from .metrics import log

    _enable_compile_cache_from_args(args)
    if args.weights or args.watch:
        raise SystemExit("--generate serves preset-initialized params; "
                         "--weights/--watch have no LLM snapshot format "
                         "to load yet")
    knobs = _resolve_llm_knobs(args)
    replicas = max(1, getattr(args, "replicas", 1))
    fleet_mode = replicas > 1 or bool(getattr(args, "devices", ""))
    manager = None
    if fleet_mode:
        from ..serving.fleet import ReplicaManager
        devices = _resolve_fleet_devices(getattr(args, "devices", ""),
                                         replicas)

        def factory(device):
            return _build_generate_executor(args.model, knobs,
                                            device=device)

        manager = ReplicaManager.build(factory, replicas, devices=devices,
                                       max_queue=args.max_queue)
        ref = manager.reference_executor()
        log(f"serve: warmed {len(manager.replicas)} generate replicas "
            f"({args.model}, page_size={ref.page_size}, "
            f"rungs={ref.decode_rungs}, buckets={ref.prompt_buckets})")
    else:
        executor = _build_generate_executor(args.model, knobs)
        log(f"serve: warmed generate executor ({args.model}, "
            f"page_size={executor.page_size}, "
            f"rungs={executor.decode_rungs}, "
            f"buckets={executor.prompt_buckets})")
    if args.host not in ("127.0.0.1", "localhost", "::1"):
        log(f"serve: WARNING: binding {args.host!r} — the wire format is "
            f"pickled frames (arbitrary code execution for anyone who can "
            f"connect); serve only on loopback or a trusted network")
    metrics_port = getattr(args, "metrics_port", -1)
    server = InferenceServer(
        executor=None if fleet_mode else executor,
        fleet=manager,
        host=args.host, port=args.port, max_queue=args.max_queue,
        default_deadline_s=(args.deadline_ms / 1e3
                            if args.deadline_ms > 0 else None),
        stats_refresh_s=(fleet_config().stats_refresh_s
                         if fleet_mode or metrics_port >= 0 else 0.0))
    log(f"serve: listening on {server.host}:{server.port} (generate op"
        + (f", {replicas} replicas)" if fleet_mode else ")"))
    metrics_srv = None
    if metrics_port >= 0:
        from .metrics import MetricsServer
        server.stats_snapshot()
        metrics_srv = MetricsServer(server.stats, port=metrics_port)
        log(f"serve: metrics endpoint on "
            f"http://127.0.0.1:{metrics_srv.port}/")

    def _graceful(signum, frame):
        log(f"serve: signal {signum}; draining in-flight requests")
        server.request_stop()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        server.wait_until_stopped()
    except KeyboardInterrupt:
        pass
    server.shutdown(drain=True)
    if metrics_srv is not None:
        metrics_srv.close()
    print(json.dumps({"serving_final_stats": server.stats_snapshot()}),
          flush=True)
    return 0


def cmd_serve(args) -> int:
    """Serve a trained snapshot over TCP: dynamic micro-batching, a
    shape-bucketed AOT compile cache, checkpoint hot-reload, and graceful
    drain on SIGTERM/SIGINT (exit 0, no request silently dropped).
    ``--replicas N`` puts a replica fleet behind the same front door:
    least-loaded routing, per-replica health/failover, rolling reload.
    ``--generate`` serves a transformer preset through the paged-KV
    continuous batcher instead (the ``generate`` wire op)."""
    import json
    import signal

    if getattr(args, "generate", False):
        return _cmd_serve_generate(args)

    from ..config import fleet_config
    from ..serving.reloader import CheckpointReloader, FleetReloader
    from ..serving.server import InferenceServer
    from .metrics import log

    # a serving replica's bucket warm-up is the same cold-start bill the
    # training tier pays: the persistent cache turns a restarted replica's
    # AOT bucket compiles into disk reads
    _enable_compile_cache_from_args(args)
    args.buckets = _resolve_serve_buckets(args)
    watch = args.watch
    if watch == "auto":
        # derive the snapshot prefix from the weights path:
        # out/snap/lenet_iter_500.solverstate.npz -> out/snap/lenet
        if args.weights and "_iter_" in args.weights:
            watch = args.weights.split("_iter_")[0]
        else:
            # refusing beats silently serving without the reloader the
            # operator asked for; checked BEFORE the (slow) bucket warm-up
            raise SystemExit(
                "--watch auto needs --weights pointing at a "
                "<prefix>_iter_N artifact to derive the prefix from; "
                "pass the snapshot prefix explicitly instead")
    # when --weights is itself a snapshot under the watch prefix, seed
    # the reloader with it so the first poll only swaps to something
    # strictly newer (never a redundant or backwards swap)
    serving_snap = (args.weights if watch and args.weights
                    and "_iter_" in args.weights
                    and args.weights.split("_iter_")[0] == watch
                    else None)
    replicas = max(1, getattr(args, "replicas", 1))
    fleet_mode = replicas > 1 or bool(getattr(args, "devices", ""))
    reloader = None
    if fleet_mode:
        manager = build_serving_fleet(
            args.model, args.weights, args.buckets, replicas,
            getattr(args, "devices", ""),
            max_delay_s=args.max_delay_ms / 1e3, max_queue=args.max_queue)
        ref = manager.reference_executor()
        log(f"serve: warmed {len(manager.replicas)} replicas, buckets "
            f"{ref.buckets} ({ref.net.name or 'net'}, "
            f"{ref.net.param_count()} params each)")
        if watch:
            reloader = FleetReloader(manager, watch, poll_s=args.poll_s,
                                     current_path=serving_snap)
    else:
        executor = _build_serving_executor(args.model, args.weights,
                                           args.buckets)
        log(f"serve: warmed buckets {executor.buckets} "
            f"({executor.net.name or 'net'}, "
            f"{executor.net.param_count()} params)")
        if watch:
            reloader = CheckpointReloader(executor, watch,
                                          poll_s=args.poll_s,
                                          current_path=serving_snap)
    if watch:
        log(f"serve: watching {watch!r} for newer snapshots "
            f"(every {args.poll_s}s)")
    if args.host not in ("127.0.0.1", "localhost", "::1"):
        log(f"serve: WARNING: binding {args.host!r} — the wire format is "
            f"pickled frames (arbitrary code execution for anyone who can "
            f"connect); serve only on loopback or a trusted network")
    metrics_port = getattr(args, "metrics_port", -1)
    server = InferenceServer(
        executor=None if fleet_mode else executor,
        fleet=manager if fleet_mode else None,
        host=args.host, port=args.port,
        max_delay_s=args.max_delay_ms / 1e3, max_queue=args.max_queue,
        default_deadline_s=(args.deadline_ms / 1e3
                            if args.deadline_ms > 0 else None),
        reloader=reloader,
        # the refresher keeps the registry section live for ANY metrics
        # endpoint (single-engine included — a once-seeded section would
        # read as a frozen server), and for the fleet health surface
        stats_refresh_s=(fleet_config().stats_refresh_s
                         if fleet_mode or metrics_port >= 0 else 0.0))
    log(f"serve: listening on {server.host}:{server.port}"
        + (f" ({replicas} replicas)" if fleet_mode else ""))
    metrics_srv = None
    if metrics_port >= 0:
        from .metrics import MetricsServer
        server.stats_snapshot()        # seed the section before first poll
        metrics_srv = MetricsServer(server.stats, port=metrics_port)
        log(f"serve: metrics endpoint on "
            f"http://127.0.0.1:{metrics_srv.port}/ (fleet health surface)")

    def _graceful(signum, frame):
        log(f"serve: signal {signum}; draining in-flight requests")
        # the handler only flips flags; the drain (thread joins) runs on
        # the main thread below — not signal-handler work
        server.request_stop()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    try:
        server.wait_until_stopped()
    except KeyboardInterrupt:
        pass
    server.shutdown(drain=True)
    if metrics_srv is not None:
        metrics_srv.close()
    print(json.dumps({"serving_final_stats": server.stats_snapshot()}),
          flush=True)
    return 0


def run_serving_bench(executor, requests: int, concurrency: int, batch: int,
                      max_delay_ms: float = 5.0, max_queue: int = 64,
                      deadline_ms=None, fleet=None, offered_rps=None):
    """The in-process serving bench driver shared by `bench_serve` and
    bench.py's serving mode: port-0 server + the load generator, request
    sizes cycling 1..batch over the bucket ladder. Pass ``fleet`` (a
    ReplicaManager; ``executor=None``) to stand the whole fleet behind
    the front door, and ``offered_rps`` for the open-loop arrival-rate
    mode. Returns (run_load result, server stats snapshot)."""
    import numpy as np

    from ..serving.client import run_load
    from ..serving.server import InferenceServer

    # batching/admission knobs live on the REPLICAS in fleet mode (each
    # batcher was configured at build_serving_fleet time); passing them to
    # the server there would be a silent no-op
    server = (InferenceServer(fleet=fleet) if fleet is not None else
              InferenceServer(executor=executor,
                              max_delay_s=max_delay_ms / 1e3,
                              max_queue=max_queue))
    ref = executor if executor is not None else fleet.reference_executor()
    name = ref.input_names[0]
    row_shape = tuple(ref.net.blob_shapes[name][1:])
    max_rows = max(1, min(batch, ref.max_batch))
    frames = np.random.RandomState(0).randn(
        max_rows, *row_shape).astype(np.float32)

    def make_inputs(i):
        return {name: frames[: 1 + i % max_rows]}

    try:
        result = run_load(server.addr, make_inputs, n_requests=requests,
                          concurrency=concurrency, deadline_ms=deadline_ms,
                          offered_rps=offered_rps)
        stats = server.stats_snapshot()
    finally:
        server.shutdown()
    return result, stats


def cmd_bench_serve(args) -> int:
    """In-process serving latency microbenchmark: stand the server up on
    port 0, drive it with the shared load generator, print ONE JSON line
    (p50/p99/throughput + shed/fill telemetry). ``--replicas N`` benches
    the fleet path; ``--offered_rps R`` switches the generator to the
    open-loop arrival-rate mode (goodput-vs-offered-load measurable)."""
    import json

    _enable_compile_cache_from_args(args)
    args.buckets = _resolve_serve_buckets(args)
    replicas = max(1, getattr(args, "replicas", 1))
    offered = (args.offered_rps if getattr(args, "offered_rps", 0) > 0
               else None)
    if replicas > 1 or getattr(args, "devices", ""):
        fleet = build_serving_fleet(
            args.model, args.weights, args.buckets, replicas,
            getattr(args, "devices", ""),
            max_delay_s=args.max_delay_ms / 1e3, max_queue=args.max_queue)
        executor = None
    else:
        fleet = None
        executor = _build_serving_executor(args.model, args.weights,
                                           args.buckets)
    result, stats = run_serving_bench(
        executor, args.requests, args.concurrency, args.batch,
        max_delay_ms=args.max_delay_ms, max_queue=args.max_queue,
        deadline_ms=args.deadline_ms if args.deadline_ms > 0 else None,
        fleet=fleet, offered_rps=offered)
    if fleet is not None:
        result["replicas"] = replicas
        result["routing"] = stats["routing"]
        result["states"] = stats["states"]
        result["batches"] = stats["batches"]
        fills = [r.get("batch_fill") for r in stats["replicas"].values()
                 if r.get("batch_fill") is not None]
        result["batch_fill"] = (round(sum(fills) / len(fills), 4)
                                if fills else None)
    else:
        result["batch_fill"] = stats["batch_fill"]
        result["batches"] = stats["batches"]
        result["bucket_calls"] = stats["bucket_calls"]
    if not result.get("ok") or result.get("p99_ms") is None:
        # every request shed/errored: fail loudly, never a clean 0.0 line
        # (spread result FIRST — it carries an integer "error" counter that
        # must not clobber the diagnostic string)
        print(json.dumps({**result, "metric": "serving_p99_ms",
                          "value": 0.0, "unit": "ms",
                          "error_counts": result.get("error"),
                          "error": "no successful requests"}),
              flush=True)
        return 1
    print(json.dumps({"metric": "serving_p99_ms",
                      "value": result["p99_ms"],
                      "unit": "ms", **result}), flush=True)
    return 0


def cmd_tune(args) -> int:
    """The measured autotuner (runtime/tuned_plan.py, ROADMAP item 5):
    short wall-clock trials over the whole policy space — conv_layout,
    per-layer conv_strategy, arena_bucket_mb, mesh factorization, the
    step-pipeline knobs, serving bucket rungs — persisted as ONE TunedPlan
    with provenance next to the AOT executables. train/serve/bench_serve
    auto-load the matching plan at startup; a second ``tune`` memo-hits
    the store and skips re-measurement (--force re-tunes). Prints one
    JSON summary line."""
    import json

    from .. import config
    from .tuned_plan import run_tune

    # the plan store rides the compile-cache dir when one is configured
    # (plans live next to the executables they tuned); the store_dir()
    # default covers the zero-flag tune -> train round trip
    _enable_compile_cache_from_args(args)
    cache_dir = (getattr(args, "compile_cache_dir", "")
                 or config.compile_cache_config().cache_dir)
    result = run_tune(args.model, smoke=args.smoke, force=args.force,
                      cache_dir=cache_dir or None, deploy=args.deploy,
                      windows=args.windows or None,
                      iters=args.iters or None)
    doc = result["doc"]
    if args.out:
        os.makedirs(os.path.dirname(os.path.abspath(args.out)),
                    exist_ok=True)
        tmp = f"{args.out}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, args.out)
    print(json.dumps({
        "metric": "tune", "model": doc["model"],
        "backend": doc["backend"], "device_kind": doc["device_kind"],
        "source": result["source"], "path": result["path"],
        "knobs": doc["knobs"],
        "search_cost_s": doc.get("search_cost_s"),
        "tuned_vs_default_speedup": doc.get("ab", {}).get("speedup"),
    }), flush=True)
    return 0


def cmd_convert_imageset(args) -> int:
    from .tools import convert_imageset
    convert_imageset(args.listfile, args.out_db, root_folder=args.root_folder,
                     resize_height=args.resize_height,
                     resize_width=args.resize_width, shuffle=args.shuffle,
                     gray=args.gray)
    return 0


def cmd_compute_image_mean(args) -> int:
    from .tools import compute_image_mean
    compute_image_mean(args.db, args.out_file)
    return 0


def cmd_partition_data(args) -> int:
    from .tools import partition_data
    partition_data(args.db, args.num_shards)
    return 0


def cmd_convert_db(args) -> int:
    from .tools import convert_db
    convert_db(args.src, args.out, args.backend)
    return 0


def cmd_extract_features(args) -> int:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..core.net import Net
    from ..data.pipeline import build_phase_pipelines
    from ..data.workload import Shard
    from ..parallel import make_mesh
    from ..proto.messages import load_net
    from .checkpoint import load_caffemodel
    from .cluster import init_distributed
    from .tools import extract_features

    init_distributed(hostfile=args.hostfile or None,
                     node_id=args.node_id if args.node_id >= 0 else None)
    rank, nproc = jax.process_index(), jax.process_count()
    net_param = load_net(args.model)
    # each process extracts a disjoint record shard and writes its own DBs —
    # the reference's per-(client,thread) LevelDB naming
    # (feature_extractor.cpp:43-80)
    pipes, shapes = build_phase_pipelines(net_param, "TEST", 1,
                                          shard=Shard(rank, nproc))
    net = Net(net_param, "TEST", source_shapes=shapes)
    params = net.init(jax.random.PRNGKey(0))
    if args.weights:
        params = load_caffemodel(args.weights, net, params)
    prefix = args.out_prefix if nproc == 1 else \
        f"{args.out_prefix}_client{rank}"
    # batches land with the train path's batch sharding (engine.py), not
    # defaulted onto device 0
    sharding = NamedSharding(make_mesh(), P("data"))
    extract_features(net, params, args.blobs.split(","), pipes[0],
                     args.num_batches, prefix, sharding=sharding)
    for p in pipes:
        p.close()
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="poseidon_tpu",
                                description=__doc__.split("\n")[0])
    sub = p.add_subparsers(dest="command", required=True)

    t = sub.add_parser("train", help="train a model from a solver prototxt")
    t.add_argument("--solver", required=True)
    t.add_argument("--snapshot", default="",
                   help="resume from a .solverstate.npz, or 'auto' to pick "
                        "the newest one under the solver's snapshot_prefix")
    t.add_argument("--weights", default="",
                   help="finetune from a .caffemodel")
    t.add_argument("--output_dir", default=".")
    t.add_argument("--strategy", default="dense",
                   choices=["dense", "sfb", "topk"],
                   help="default gradient sync strategy")
    t.add_argument("--sfb-auto", action="store_true",
                   help="pick SFB per FC layer by cost model (SACP)")
    t.add_argument("--grad-reduce", default="mean", choices=["mean", "sum"])
    t.add_argument("--topk_policy", default="magnitude",
                   choices=["magnitude", "random", "fixed_order"],
                   help="which entries the TOPK budget sends (the server's "
                        "UpdateSortPolicy)")
    t.add_argument("--wire_dtype", default="",
                   choices=["", "f32", "bf16", "f16", "int8"],
                   help="reduced-precision gradient exchange: cast grads to "
                        "this dtype for every collective (DenseRowFloat16 "
                        "analog); with --async_ssp it also compresses the "
                        "managed DCN delta frames with exact error feedback "
                        "(int8 is DCN-only); empty = exchange at gradient "
                        "dtype (flag > TunedPlan knob > f32 default)")
    t.add_argument("--topk_block", type=int, default=0,
                   help="blocked top-k selection: pick top-k within blocks "
                        "of this many elements instead of one global sort "
                        "(row-granular, like the reference server); 0 = "
                        "global top-k")
    t.add_argument("--dwbp_bucket_mb", type=float, default=-1.0,
                   help="chain DWBP gradient psums into ~N-MB buckets so "
                        "each bucket stays a DISTINCT collective issued "
                        "mid-backward (the reference's per-blob sync-thread "
                        "structure, solver.cpp:419-449); 0 = one per blob, "
                        "negative = off (XLA's combiner decides)")
    t.add_argument("--param_arena", default="true",
                   choices=["true", "false"],
                   help="flat parameter arena (ON by default): pack DENSE "
                        "param/grad/momentum leaves into one flat buffer, "
                        "sync gradients as ceil(bytes/arena_bucket_mb) "
                        "bucketed collectives instead of one per leaf, and "
                        "run the optimizer update as one fused pass; same "
                        "numbers as the per-leaf path (update rule bitwise, "
                        "steps within 1 ulp of collective reduction order)")
    t.add_argument("--arena_bucket_mb", type=float, default=None,
                   help="arena gradient-sync bucket size in MB (DWBP-"
                        "ordered exact element ranges; <= 0 = one bucket "
                        "per leaf). Unset = TunedPlan value if one is "
                        "persisted, else 4.0")
    t.add_argument("--hbm_budget_gb", type=float, default=None,
                   help="per-device HBM budget (GiB) for the measured "
                        "remat planner (core/remat.py): the no-remat "
                        "train step compiles once, its real "
                        "memory_analysis() peak is read, and a greedy "
                        "cheapest-recompute-per-byte knapsack drops "
                        "stored activations (jax.checkpoint on the "
                        "chosen layers) until the step fits. Negative = "
                        "auto-detect the device's own HBM limit; 0 = "
                        "off. Unset = TunedPlan value if persisted, "
                        "else off")
    t.add_argument("--remat", default=None,
                   help="activation remat override: a comma-separated "
                        "layer list checkpoints exactly those layers "
                        "(no measuring compile), 'auto' plans against "
                        "--hbm_budget_gb, 'none' forces remat off. "
                        "Unset = TunedPlan value if persisted, else "
                        "off. Conflicts with a persisted plan refuse "
                        "loudly rather than silently arbitrating")
    t.add_argument("--bf16", action="store_true",
                   help="the documented bf16 training path: bfloat16 "
                        "compute (MXU-native) + the exact space-to-depth "
                        "stem rewrite; params/optimizer state/softmax "
                        "stats stay f32. Accuracy guardrail: the LeNet "
                        "loss-trajectory smoke must track f32 within "
                        "numeric.BF16_SMOKE_* (tests/test_kernels.py). "
                        "Default f32 matches Caffe numerics exactly")
    t.add_argument("--conv_strategy", default="",
                   choices=["", "auto", "direct", "im2col", "s2d"],
                   help="conv lowering strategy: 'auto' MEASURES direct/"
                        "im2col/s2d per conv layer at net construction "
                        "(short micro-runs; winners logged and persisted "
                        "via --compile_cache_dir so the next run skips "
                        "re-measurement), a concrete value forces one "
                        "strategy net-wide; empty = the TunedPlan value "
                        "if one is persisted, else the legacy global "
                        "conv_s2d policy (on under --bf16)")
    t.add_argument("--conv_layout", default="",
                   type=lambda s: s.lower(),
                   choices=["", "nchw", "nhwc", "auto"],
                   help="internal activation layout for the whole graph "
                        "(core/net.py plans conv/pool/LRN natively in it; "
                        "checkpoints stay canonical NCHW). Unset = the "
                        "TunedPlan's measured row if one is persisted, "
                        "else 'auto' (the per-backend table in "
                        "numeric.resolve_conv_layout)")
    t.add_argument("--tuned_plan", default="auto", choices=["auto", "off"],
                   help="TunedPlan auto-load (runtime/tuned_plan.py): "
                        "'auto' loads the persisted plan matching (train "
                        "net, backend, device kind, devices) and fills "
                        "every knob no explicit flag set — provenance "
                        "lands in stats.yaml; 'off' = built-in defaults "
                        "+ flags only")
    t.add_argument("--mesh", default="",
                   help="named SPMD mesh spec, e.g. 'dp2,fsdp2,tp1' "
                        "(axes: dp = data parallel, fsdp = sharded "
                        "parameter arena with reduce-scatter/all-gather "
                        "buckets, tp = tensor-parallel FC column/row "
                        "shards planned per layer); sizes of 1 "
                        "deactivate an axis. Empty = the flat data mesh")
    t.add_argument("--dcn_slices", type=int, default=0,
                   help="split devices into N slices on a slow (DCN) mesh "
                        "axis: dense sync intra-slice, TOPK-compressed "
                        "exchange inter-slice (managed comm / SSPAggr)")
    t.add_argument("--staleness", type=int, default=0,
                   help="SSP bound s: devices run local steps, reconciling "
                        "every s+1 iters (0 = synchronous, the reference's "
                        "recommended setting)")
    t.add_argument("--server_logic", default="inc",
                   choices=["inc", "adarevision"],
                   help="SSP anchor update rule: plain delta increment "
                        "(inc) or delay-corrected AdaGrad (the server's "
                        "adarevision_server_table_logic); needs --staleness")
    t.add_argument("--adarev_init_step", type=float, default=0.1,
                   help="adarevision server init_step_size; scales the SUM "
                        "of group updates (reduce is ignored — the server "
                        "applies every group's full update, the reference's "
                        "RowBatchInc semantics), so ~base_lr/n_groups is "
                        "the stable regime")
    t.add_argument("--async_ssp", action="store_true",
                   help="wait-free asynchronous SSP across launcher "
                        "processes (the Bösen execution model, "
                        "parallel/async_ssp.py): each process trains on "
                        "its LOCAL mesh, parameter increments stream to a "
                        "rank-0 service, reads gate on --staleness; no "
                        "jax.distributed world, no cross-process barrier")
    t.add_argument("--async_sync_every", type=int, default=1,
                   help="optimizer iterations per async-SSP flush clock")
    t.add_argument("--slice", action="store_true",
                   help="two-tier fabric (parallel/fabric.py): this "
                        "process LEADS an SPMD slice and the async-SSP "
                        "worker identity is the SLICE id — synchronous "
                        "dp/fsdp/tp math inside the slice, bounded-"
                        "staleness exchange between slices, admit/retire/"
                        "failover at slice granularity. Requires "
                        "--async_ssp plus the POSEIDON_SLICE_ID/"
                        "POSEIDON_SLICE_SIZE env contract; only the "
                        "slice leader (rank-in-slice 0) may run it")
    t.add_argument("--comm_budget_mbps", type=float, default=-1.0,
                   help="managed communication (SSPAggr): per-link "
                        "bandwidth budget in Mbit/s for the async-SSP "
                        "tier, metered as a token bucket over ACTUAL "
                        "frame bytes on both push and pull channels. A "
                        "tight budget switches to magnitude-prioritized "
                        "PARTIAL pushes (top --comm_priority_frac of the "
                        "delta by |value|, TOPK index+value wire form) "
                        "with the exact complement carried locally and "
                        "force-flushed every staleness+1 clocks; read "
                        "gates run on fully-flushed (durable) clocks so "
                        "the SSP bound is preserved exactly. <= 0 = "
                        "unlimited — byte-for-byte the dense path")
    t.add_argument("--comm_priority_frac", type=float, default=-1.0,
                   help="fraction of delta entries a budget-tight partial "
                        "push ships, ranked by |value| across the whole "
                        "update (default 0.1); negative = the "
                        "ManagedCommConfig default")
    t.add_argument("--comm_adaptive", action="store_true",
                   help="adaptive push cadence: under congestion (token-"
                        "bucket deficit or flushes queuing behind a slow "
                        "link) intermediate clocks ship as ~100-byte "
                        "ticks and the payload rides the next boundary "
                        "flush, recovering as the link drains "
                        "(cadence_backoffs counts escalations)")
    t.add_argument("--async_heartbeat_s", type=float, default=-1.0,
                   help="async-SSP client heartbeat cadence (liveness "
                        "signal when the flush queue is idle); negative = "
                        "FaultConfig default")
    t.add_argument("--async_liveness_timeout_s", type=float, default=-1.0,
                   help="async-SSP service evicts a worker silent this "
                        "long (survivors' gates unblock; 0 disables — the "
                        "reference's hang-forever semantics); negative = "
                        "FaultConfig default")
    t.add_argument("--async_reconnect_deadline_s", type=float, default=-1.0,
                   help="async-SSP client gives up reconnecting (and "
                        "surfaces permanent failure to the training loop) "
                        "after this long; negative = FaultConfig default")
    t.add_argument("--async_gate_timeout_s", type=float, default=-1.0,
                   help="async-SSP read-gate backstop per clock; negative "
                        "= tier default (120 s)")
    t.add_argument("--async_first_gate_timeout_s", type=float, default=-1.0,
                   help="async-SSP FIRST-clock gate backstop (covers "
                        "peers' initial multi-minute JIT compile); "
                        "negative = max(1800 s, 10x gate timeout)")
    t.add_argument("--hostfile", default="",
                   help="cluster hostfile ('<id> <ip> <port>' lines)")
    t.add_argument("--node_id", type=int, default=-1,
                   help="this process's hostfile id")
    t.add_argument("--steps_per_dispatch", type=int, default=None,
                   help="run K optimizer steps per compiled dispatch "
                        "(lax.scan): amortizes per-dispatch runtime "
                        "round-trip; falls back to single steps near "
                        "display/test/snapshot boundaries (unset = "
                        "TunedPlan value if persisted, else 1)")
    t.add_argument("--device_prefetch", type=int, default=None,
                   help="device-side input prefetch depth: a background "
                        "stage device_puts the next N host batches with "
                        "the step's batch sharding while the current step "
                        "runs, and the batch buffers become donated step "
                        "inputs (no steady-state batch allocations); 0 "
                        "restores the inline device_put (default: the "
                        "PipelineConfig policy, 2)")
    t.add_argument("--max_in_flight", type=int, default=None,
                   help="bounded in-flight dispatch window: dispatch step "
                        "k+1 before step k's metrics are read, blocking "
                        "only when this many dispatches are un-"
                        "materialized; 1 = the serial loop. Loss display "
                        "and NaN detection lag by at most this many steps "
                        "(default: the PipelineConfig policy, 2)")
    t.add_argument("--async_snapshot", action="store_true", default=None,
                   help="serialize mid-train snapshots on a background "
                        "thread (host copy taken at the sync point; the "
                        "atomic tmp-rename protocol and auto-resume "
                        "semantics are unchanged; default: the "
                        "PipelineConfig policy, off)")
    t.add_argument("--compile_cache_dir", default="",
                   help="fast restart: persistent XLA compile cache "
                        "directory (every backend compile becomes a disk "
                        "hit on restart) plus an AOT step-executable store "
                        "under <dir>/aot keyed by (model, shapes, mesh) — "
                        "a matching restart skips trace AND compile. "
                        "Empty = off (full JIT per start). Env default: "
                        "POSEIDON_COMPILE_CACHE_DIR")
    t.add_argument("--aot_steps", default="true", choices=["true", "false"],
                   help="with --compile_cache_dir: also serialize/reload "
                        "the compiled train-step executable itself "
                        "(best-effort; false keeps only the XLA cache)")
    t.add_argument("--profile", type=int, default=0,
                   help="capture an xplane trace over N steps (from step 10)")
    t.add_argument("--trace_out", default="",
                   help="host-side span timeline: record dispatch/hard-"
                        "sync/snapshot/prefetch-stall and async-tier "
                        "push/pull/gate/admit spans and write Chrome "
                        "trace-event JSON here (relative to --output_dir), "
                        "refreshed atomically at every display boundary; "
                        "load in chrome://tracing or Perfetto")
    t.add_argument("--metrics_port", type=int, default=-1,
                   help="serve live training counters over HTTP on this "
                        "loopback port (0 = ephemeral, logged at startup): "
                        "curl it mid-run for text key=value — iteration, "
                        "loss, input_stall, membership churn; negative = "
                        "off")
    t.add_argument("--device_transform", action="store_true",
                   help="ship uint8 crops and apply (x - mean_value) * "
                        "scale on device (4x fewer host->device bytes; "
                        "needs the native batcher, mean_value-style mean)")
    t.set_defaults(fn=cmd_train)

    te = sub.add_parser("test", help="score a model")
    te.add_argument("--model", required=True)
    te.add_argument("--weights", default="")
    te.add_argument("--iterations", type=int, default=50)
    te.add_argument("--hostfile", default="")
    te.add_argument("--node_id", type=int, default=-1)
    te.set_defaults(fn=cmd_test)

    ti = sub.add_parser("time", help="benchmark model fwd/bwd")
    ti.add_argument("--model", required=True)
    ti.add_argument("--iterations", type=int, default=50)
    ti.add_argument("--batch_size", type=int, default=64)
    ti.add_argument("--per_layer", action="store_true",
                    help="also print per-layer forward times")
    ti.add_argument("--comm_devices", type=int, default=0,
                    help="with --per_layer: print static per-layer comm "
                         "bytes/savings over this many devices")
    ti.add_argument("--dcn_slices", type=int, default=0,
                    help="with --comm_devices: model a two-tier mesh with "
                         "this many DCN slices")
    ti.add_argument("--strategy", default="dense",
                    choices=["dense", "sfb", "topk"])
    ti.add_argument("--sfb-auto", action="store_true",
                    help="pick SFB per FC layer by cost model")
    ti.add_argument("--wire_dtype", default="",
                    choices=["", "f32", "bf16", "f16"],
                    help="bill the comm table at this wire width")
    ti.add_argument("--topk_block", type=int, default=0)
    ti.set_defaults(fn=cmd_time)

    dq = sub.add_parser("device_query", help="show accelerator info")
    dq.set_defaults(fn=cmd_device_query)

    sv = sub.add_parser(
        "serve", help="serve a trained snapshot over TCP (dynamic "
                      "micro-batching, bucketed AOT compile cache, "
                      "checkpoint hot-reload)")
    sv.add_argument("--model", required=True,
                    help="deploy-style prototxt (explicit input/input_dim)")
    sv.add_argument("--weights", default="",
                    help="a .caffemodel or .solverstate.npz to serve; "
                         "empty serves filler init (smoke mode)")
    sv.add_argument("--watch", default="",
                    help="snapshot prefix to poll for hot-reload (e.g. "
                         "out/snap/lenet), or 'auto' to derive it from "
                         "--weights' _iter_ naming")
    sv.add_argument("--host", default="127.0.0.1",
                    help="bind address; the protocol is pickle-framed and "
                         "UNAUTHENTICATED — loopback/trusted networks only")
    sv.add_argument("--port", type=int, default=0,
                    help="0 = ephemeral (printed at startup)")
    sv.add_argument("--buckets", default="",
                    help="batch bucket ladder; every bucket is AOT-"
                         "compiled at startup (no trace on a request). "
                         "Unset = the deploy net's TunedPlan ladder if "
                         "one is persisted, else 1,4,16,64")
    sv.add_argument("--tuned_plan", default="auto", choices=["auto", "off"],
                    help="'auto' resolves an unset --buckets through the "
                         "persisted TunedPlan; 'off' = built-in default")
    sv.add_argument("--max_delay_ms", type=float, default=5.0,
                    help="micro-batcher flush deadline: a queued request "
                         "never waits longer than this for batch company")
    sv.add_argument("--max_queue", type=int, default=64,
                    help="admission bound; a full queue sheds explicitly")
    sv.add_argument("--deadline_ms", type=float, default=0.0,
                    help="default per-request deadline (0 = none)")
    sv.add_argument("--poll_s", type=float, default=1.0,
                    help="hot-reload watch cadence")
    sv.add_argument("--replicas", type=int, default=1,
                    help="serving replicas behind the one front door, "
                         "each its own bucketed executor + micro-batcher "
                         "(least-loaded routing, per-replica health, "
                         "rolling hot-reload); 1 = the single-engine path")
    sv.add_argument("--devices", default="",
                    help="comma-separated jax.devices() indices to pin "
                         "replicas to (e.g. '0,1,2'); empty round-robins "
                         "over all local devices when --replicas > 1")
    sv.add_argument("--metrics_port", type=int, default=-1,
                    help="serve live fleet health over HTTP on this port "
                         "(0 = ephemeral, printed at startup; the same "
                         "read-only endpoint as train's --metrics_port)")
    sv.add_argument("--compile_cache_dir", default="",
                    help="persistent XLA compile cache: a restarted "
                         "replica's bucket warm-up compiles become disk "
                         "reads (same flag as train; empty = off)")
    sv.add_argument("--generate", action="store_true",
                    help="LLM decode serving: --model names a transformer "
                         "preset (tiny|gpt_small) served through the "
                         "paged-KV continuous batcher — 'generate' wire "
                         "op with streaming gen_chunk frames; page size/"
                         "decode rungs/prompt buckets resolve through the "
                         "persisted TunedPlan")
    sv.set_defaults(fn=cmd_serve)

    bs = sub.add_parser(
        "bench_serve", help="serving latency microbenchmark (in-process "
                            "server + load generator, ONE JSON line)")
    bs.add_argument("--model", default="",
                    help="deploy prototxt; empty uses a built-in synthetic "
                         "conv net")
    bs.add_argument("--weights", default="")
    bs.add_argument("--buckets", default="",
                    help="unset = TunedPlan ladder if persisted, else "
                         "1,4,16,64")
    bs.add_argument("--tuned_plan", default="auto",
                    choices=["auto", "off"])
    bs.add_argument("--requests", type=int, default=200)
    bs.add_argument("--concurrency", type=int, default=4)
    bs.add_argument("--batch", type=int, default=8,
                    help="request sizes cycle 1..batch (exercises the "
                         "bucket ladder)")
    bs.add_argument("--max_delay_ms", type=float, default=5.0)
    bs.add_argument("--max_queue", type=int, default=64)
    bs.add_argument("--deadline_ms", type=float, default=0.0)
    bs.add_argument("--replicas", type=int, default=1,
                    help="bench the fleet path with this many replicas")
    bs.add_argument("--devices", default="",
                    help="device indices to pin the replicas to")
    bs.add_argument("--offered_rps", type=float, default=0.0,
                    help="open-loop mode: fixed arrival rate (req/s); "
                         "0 = closed loop")
    bs.add_argument("--compile_cache_dir", default="")
    bs.set_defaults(fn=cmd_bench_serve)

    tu = sub.add_parser(
        "tune", help="measured autotuner: short wall-clock trials over "
                     "the policy space (conv layout/strategy, arena "
                     "buckets, mesh, pipeline, serving rungs), persisted "
                     "as ONE TunedPlan that train/serve auto-load")
    tu.add_argument("--model", default="lenet",
                    choices=["lenet", "alexnet", "googlenet"],
                    help="tune target (plan keyed by the net's name, so "
                         "a train run on the same model auto-loads it)")
    tu.add_argument("--smoke", action="store_true",
                    help="tier-1-safe smoke: tiny shapes, 2-point search "
                         "spaces, spmd mesh arms skipped (recorded as "
                         "only-candidate rows, never silently)")
    tu.add_argument("--force", action="store_true",
                    help="re-measure even when a matching plan is "
                         "persisted (default: memo-hit and skip)")
    tu.add_argument("--deploy", default="",
                    help="deploy prototxt for the serving-ladder trials "
                         "(default: a synthetic probe net, labeled)")
    tu.add_argument("--windows", type=int, default=0,
                    help="interleaved timing windows per knob (0 = 2 "
                         "smoke / 4 full)")
    tu.add_argument("--iters", type=int, default=0,
                    help="timed calls per window (0 = 2 smoke / 4 full)")
    tu.add_argument("--out", default="",
                    help="also write the plan JSON here (evidence copy; "
                         "the store copy always lands next to the AOT "
                         "executables)")
    tu.add_argument("--compile_cache_dir", default="",
                    help="plan store override (default: the configured "
                         "compile-cache dir, else POSEIDON_TUNED_DIR, "
                         "else ~/.cache/poseidon_tpu)")
    tu.add_argument("--aot_steps", default="true",
                    choices=["true", "false"], help=argparse.SUPPRESS)
    tu.set_defaults(fn=cmd_tune)

    ci = sub.add_parser("convert_imageset", help="image list -> LMDB")
    ci.add_argument("listfile")
    ci.add_argument("out_db")
    ci.add_argument("--root_folder", default="")
    ci.add_argument("--resize_height", type=int, default=0)
    ci.add_argument("--resize_width", type=int, default=0)
    ci.add_argument("--shuffle", action="store_true")
    ci.add_argument("--gray", action="store_true")
    ci.set_defaults(fn=cmd_convert_imageset)

    cm = sub.add_parser("compute_image_mean", help="LMDB -> mean binaryproto")
    cm.add_argument("db")
    cm.add_argument("out_file")
    cm.set_defaults(fn=cmd_compute_image_mean)

    pd = sub.add_parser("partition_data", help="split LMDB into k shards")
    pd.add_argument("db")
    pd.add_argument("num_shards", type=int)
    pd.set_defaults(fn=cmd_partition_data)

    cd = sub.add_parser("convert_db", help="copy LevelDB<->LMDB")
    cd.add_argument("src")
    cd.add_argument("out")
    cd.add_argument("--backend", default="LMDB", choices=["LMDB", "LEVELDB"])
    cd.set_defaults(fn=cmd_convert_db)

    ef = sub.add_parser("extract_features",
                        help="dump named blobs to LMDBs")
    ef.add_argument("--model", required=True)
    ef.add_argument("--weights", default="")
    ef.add_argument("--blobs", required=True,
                    help="comma-separated blob names")
    ef.add_argument("--num_batches", type=int, default=10)
    ef.add_argument("--out_prefix", required=True)
    ef.add_argument("--hostfile", default="")
    ef.add_argument("--node_id", type=int, default=-1)
    ef.set_defaults(fn=cmd_extract_features)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    # async collective fusion must be staged into LIBTPU_INIT_ARGS before
    # any command initializes the backend (it is the DWBP-overlap mechanism
    # on TPU; a no-op on CPU runs — see config.enable_tpu_async_collectives)
    from .. import config as _config
    _config.enable_tpu_async_collectives()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
