"""Deterministic fault injection for the host-driven socket tier.

A loopback TCP proxy that sits between an :class:`AsyncSSPClient` (or any
socket peer) and the upstream service, applying explicit, reproducible
fault rules per accepted connection — the chaos-test substrate for the
tier's liveness/eviction/reconnect protocol. Nothing here is random: rules
match on the accepted-connection index and cut on exact byte counts, so a
chaos test replays identically run after run (the analog of the
deterministic 8-virtual-device CPU mesh for the parallel strategies).

Rules (:class:`FaultRule`):

- ``drop``     — accept, then close immediately: the peer's connect()
                 succeeds but its first read/write sees EOF/RST. Models a
                 service behind a dead load-balancer slot; exercises the
                 client's backoff-and-redial loop.
- ``delay``    — forward both directions, adding ``delay_s`` at the
                 ``delay_per`` billing granularity: ``"chunk"`` (legacy:
                 once per 64 KB read — a large frame pays it many times),
                 ``"frame"`` (once per length-prefixed wire frame — one
                 rule models the SAME latency for small and large frames;
                 tracks proto/wire.py's 8-byte big-endian framing, so do
                 not combine with the raw-byte auth preamble), or
                 ``"once"`` (once per connection direction — pure
                 connection-setup latency). Models a congested DCN hop;
                 exercises that slow != dead (heartbeats keep the worker
                 un-evicted).
- ``throttle`` — token-bucket bytes/sec shaping PER DIRECTION
                 (``rate_bps`` refill, ``burst_bytes`` capacity): each
                 pump sleeps exactly long enough that its cumulative
                 forwarded bytes never exceed the budget. The
                 deterministic substrate for bandwidth-constrained-link
                 chaos (managed communication's A/B and throttled-fleet
                 scenarios are reproducible run after run).
- ``truncate`` — forward exactly ``after_bytes`` of client->server
                 payload, then hard-close both sides. The upstream sees a
                 mid-message EOF (a torn frame); exercises the service's
                 FrameError containment + the client's replay.
- ``sever``    — same cut mechanics as truncate (``after_bytes`` of
                 client->server traffic, 0 = on first activity), named for
                 intent: a hard mid-run partition.

Any rule can be made ONE-SHOT with ``nth=N``: it fires on exactly the Nth
connection that passes its other filters, then expires — the targeting
mode the elasticity chaos suite uses to kill a specific handshake (e.g.
"sever precisely the admit rendezvous, not the dials before it").

Runtime controls: :meth:`FaultProxy.sever_all` hard-drops every live
connection at once (worker preemption / network partition mid-run);
:meth:`FaultProxy.sever_group` hard-drops every live connection belonging
to a worker-id SET in one atomic event (a whole slice preempted at once —
the two-tier fabric's failure unit); :meth:`FaultProxy.refuse_new`
black-holes reconnect attempts (the partition persists) until lifted.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Tuple

__all__ = ["FaultRule", "FaultProxy"]

# worker-id sniffing gives up on any first frame bigger than this (a data
# frame on a connection that skipped the hello — never the in-repo client)
_SNIFF_CAP = 1 << 20


@dataclass
class _Pair:
    """One live proxied connection. ``worker`` is discovered from the
    client's first wire frame (the ``hello`` every AsyncSSPClient sends on
    every socket) so group-targeted faults can address connections by the
    worker they serve, not by accept order. Token-authenticated links put
    a raw-byte HMAC preamble before the first frame, so — like the
    per-frame delay billing — worker tagging assumes token-free links
    (the chaos suites' configuration); an unparsable first frame just
    leaves the pair untagged."""

    client: socket.socket
    upstream: socket.socket
    worker: Optional[int] = None
    sniff: bytes = b""
    sniffed: bool = False


@dataclass
class FaultRule:
    """One deterministic fault. ``conn`` matches the nth accepted
    connection (0-based; None = every connection); ``max_conns`` expires
    the rule after it has matched that many connections (None = never).

    ``nth`` is the ONE-SHOT targeting mode: the rule fires on exactly the
    Nth (0-based) connection that passes its other filters, then expires
    forever — connections before the Nth pass through untouched and do
    not consume the rule. ``conn`` can only address an absolute accepted
    index and ``max_conns`` only a leading prefix, so neither can express
    "kill specifically the 3rd connection from now" — e.g. the rejoin or
    admit handshake of a worker whose earlier dials already consumed
    unpredictable indices. ``nth`` can."""

    action: str = "sever"       # drop | delay | truncate | sever | throttle
    conn: Optional[int] = None
    after_bytes: int = 0           # truncate/sever: client->server budget
    delay_s: float = 0.0           # delay: added latency per billing unit
    delay_per: str = "chunk"       # delay billing: chunk | frame | once
    rate_bps: float = 0.0          # throttle: bytes/sec per direction
    burst_bytes: int = 65536       # throttle: token-bucket capacity
    max_conns: Optional[int] = None
    nth: Optional[int] = None      # one-shot: fire on the Nth match only
    hits: int = field(default=0, repr=False)  # connections matched so far
    seen: int = field(default=0, repr=False)  # candidates examined (nth)
    expired: bool = field(default=False, repr=False)  # nth fired already

    def __post_init__(self):
        if self.action not in ("drop", "delay", "truncate", "sever",
                               "throttle"):
            raise ValueError(f"unknown fault action {self.action!r}")
        if self.nth is not None and self.nth < 0:
            raise ValueError(f"nth must be >= 0, got {self.nth}")
        if self.delay_per not in ("chunk", "frame", "once"):
            raise ValueError(f"unknown delay_per {self.delay_per!r}")
        if self.action == "throttle" and self.rate_bps <= 0:
            raise ValueError("throttle needs rate_bps > 0")


class FaultProxy:
    """Loopback TCP proxy with per-connection fault rules (port 0 bind —
    no fixed ports, no flakes). ``proxy.addr`` is what the client dials."""

    def __init__(self, upstream: Tuple[str, int], host: str = "127.0.0.1",
                 port: int = 0):
        self.upstream = upstream
        self._rules: List[FaultRule] = []
        self._lock = threading.Lock()
        self._pairs: List[_Pair] = []
        self.accepted = 0      # connections accepted (rule index space)
        self.dropped = 0       # connections refused (drop rule/refuse_new)
        self.bytes_c2s = 0
        self.bytes_s2c = 0
        self._refusing = False
        self._stop = threading.Event()
        self._srv = socket.create_server((host, port))
        self.port = self._srv.getsockname()[1]
        self.addr = (host, self.port)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()

    # ---- rule management ------------------------------------------------ #
    def add_rule(self, rule: FaultRule) -> FaultRule:
        with self._lock:
            self._rules.append(rule)
        return rule

    def clear_rules(self) -> None:
        with self._lock:
            self._rules.clear()

    def refuse_new(self, refusing: bool = True) -> None:
        """Black-hole (accept+close) every NEW connection until lifted —
        the persistent half of a partition; live pairs are untouched."""
        self._refusing = refusing

    def sever_all(self) -> int:
        """Hard-close every live connection pair at once (both sides, both
        directions) — the instantaneous half of a partition. Returns how
        many pairs were cut."""
        with self._lock:
            pairs, self._pairs = self._pairs, []
        return self._cut(pairs)

    def sever_group(self, worker_ids: Iterable[int]) -> int:
        """Hard-close every live connection whose identified worker id is
        in ``worker_ids``, as ONE atomic event: the victim set is chosen
        under the lock, so a chaos test killing a whole slice (every
        member's push + pull channel at once) cannot race per-link
        ``sever_all`` calls against the victims' reconnect loops — the
        deterministic analog of a slice preemption. Connections whose
        hello frame has not yet crossed the proxy carry no worker tag and
        are never matched (sever them by killing the slice AFTER its
        first exchange, the way the fabric chaos suite does). Returns how
        many pairs were cut."""
        ids = frozenset(worker_ids)
        with self._lock:
            cut = [p for p in self._pairs if p.worker in ids]
            self._pairs = [p for p in self._pairs if p.worker not in ids]
        return self._cut(cut)

    @staticmethod
    def _cut(pairs: List[_Pair]) -> int:
        for p in pairs:
            for s in (p.client, p.upstream):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
        return len(pairs)

    def _match(self, idx: int) -> Optional[FaultRule]:
        with self._lock:
            for r in self._rules:
                if r.expired:
                    continue
                if r.conn is not None and r.conn != idx:
                    continue
                if r.max_conns is not None and r.hits >= r.max_conns:
                    continue
                if r.nth is not None:
                    # one-shot targeting: count candidates deterministically;
                    # only the Nth consumes (and expires) the rule — earlier
                    # candidates pass through and may match LATER rules
                    k = r.seen
                    r.seen += 1
                    if k != r.nth:
                        continue
                    r.expired = True
                r.hits += 1
                return r
        return None

    # ---- data plane ----------------------------------------------------- #
    def _accept_loop(self) -> None:
        self._srv.settimeout(0.1)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # refusal is handled BEFORE the connection enters the rule
            # index space: a refused connection must consume neither a
            # rule's conn index nor its max_conns budget, or rule firing
            # would depend on how many retries land inside the refusal
            # window — goodbye determinism
            if self._refusing:
                self.dropped += 1
                conn.close()
                continue
            idx = self.accepted
            self.accepted += 1
            rule = self._match(idx)
            if rule is not None and rule.action == "drop":
                self.dropped += 1
                conn.close()
                continue
            try:
                up = socket.create_connection(self.upstream, timeout=5.0)
            except OSError:
                conn.close()
                continue
            pair = _Pair(conn, up)
            with self._lock:
                self._pairs.append(pair)
            for src, dst, c2s in ((conn, up, True), (up, conn, False)):
                threading.Thread(target=self._pump,
                                 args=(src, dst, rule, c2s, pair),
                                 daemon=True).start()

    def _sniff_worker(self, pair: _Pair, data: bytes) -> None:
        """Walk the FIRST client->server wire frame (8-byte big-endian
        length + pickled payload — the client's hello) and tag the pair
        with its worker id. One-shot: success, an oversized frame, or an
        unparsable payload all end sniffing for the connection."""
        with self._lock:
            if pair.sniffed:
                return
            pair.sniff += data
            buf = pair.sniff
            if len(buf) < 8:
                return
            (ln,) = struct.unpack("!Q", buf[:8])
            if ln > _SNIFF_CAP:
                pair.sniffed, pair.sniff = True, b""
                return
            if len(buf) < 8 + ln:
                return
            pair.sniffed = True
            payload, pair.sniff = buf[8:8 + ln], b""
            try:
                msg = pickle.loads(payload)
                if isinstance(msg, dict) and isinstance(
                        msg.get("worker"), int):
                    pair.worker = msg["worker"]
            except Exception:  # noqa: BLE001 — not a hello; stay untagged
                pass

    def _pump(self, src: socket.socket, dst: socket.socket,
              rule: Optional[FaultRule], c2s: bool,
              pair: Optional[_Pair] = None) -> None:
        budget = None
        if rule is not None and rule.action in ("truncate", "sever") and c2s:
            budget = max(0, rule.after_bytes)
        forwarded = 0
        # delay billing state: "frame" walks the length-prefixed framing
        # (8-byte big-endian header + payload) through the byte stream and
        # bills delay_s once per frame STARTED in a chunk; "once" bills a
        # single time per direction; "chunk" is the legacy per-read bill
        delaying = (rule is not None and rule.action == "delay"
                    and rule.delay_s > 0)
        fr_hdr = b""        # partial header bytes accumulated
        fr_left = 0         # payload bytes remaining in the current frame
        delayed_once = False
        # throttle state: one token bucket PER DIRECTION (each pump call
        # is one direction), deficit model — overdraft sleeps exactly the
        # time the budget needs to cover it, so cumulative goodput is
        # deterministically <= burst + rate * elapsed. Reuses the managed-
        # communication TokenBucket (parallel/async_ssp.py, jax-free) so
        # the shaping arithmetic and the client's accounting arithmetic
        # can never drift apart.
        throttling = rule is not None and rule.action == "throttle"
        if throttling:
            from ..parallel.async_ssp import TokenBucket
            bucket = TokenBucket(rule.rate_bps,
                                 burst_bytes=float(rule.burst_bytes))
        try:
            while not self._stop.is_set():
                data = src.recv(65536)
                if not data:
                    break
                if c2s and pair is not None and not pair.sniffed:
                    self._sniff_worker(pair, data)
                if delaying:
                    if rule.delay_per == "chunk":
                        time.sleep(rule.delay_s)
                    elif rule.delay_per == "once":
                        if not delayed_once:
                            delayed_once = True
                            time.sleep(rule.delay_s)
                    else:  # per frame
                        frames = 0
                        i = 0
                        while i < len(data):
                            if fr_left == 0:
                                take = min(8 - len(fr_hdr), len(data) - i)
                                fr_hdr += data[i:i + take]
                                i += take
                                if len(fr_hdr) == 8:
                                    frames += 1
                                    (fr_left,) = struct.unpack("!Q", fr_hdr)
                                    fr_hdr = b""
                            else:
                                take = min(fr_left, len(data) - i)
                                fr_left -= take
                                i += take
                        if frames:
                            time.sleep(rule.delay_s * frames)
                if throttling:
                    bucket.consume(len(data))
                    deficit = -bucket.available()
                    if deficit > 0:
                        # sleep off the deficit before forwarding: bytes
                        # only ever cross at <= the shaped rate (the
                        # bucket refills during the sleep)
                        time.sleep(deficit / rule.rate_bps)
                if budget is not None and forwarded + len(data) >= budget:
                    cut = data[:budget - forwarded]
                    if cut:
                        dst.sendall(cut)
                        self.bytes_c2s += len(cut)
                    break  # -> finally closes BOTH sides: the torn frame
                dst.sendall(data)
                forwarded += len(data)
                if c2s:
                    self.bytes_c2s += len(data)
                else:
                    self.bytes_s2c += len(data)
        except OSError:
            pass
        finally:
            # closing both sockets finishes the sibling pump too — a cut is
            # always a FULL connection loss, never a half-open zombie
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    s.close()
                except OSError:
                    pass
            with self._lock:
                self._pairs = [p for p in self._pairs
                               if p.client is not src
                               and p.client is not dst]

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        self.sever_all()
