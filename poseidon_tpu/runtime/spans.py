"""Host-side span timeline: the telemetry spine's wall-clock half.

``jax.named_scope`` + the xplane trace (runtime/attribution.py) attribute
DEVICE time; this module attributes HOST time — where the engine loop, the
async tier, and the serving path actually block. A span is a context
manager around one hot-path region (dispatch, hard sync, snapshot write,
prefetch stall, async push/pull/gate/admit); the recorder buffers them in
a bounded thread-safe deque and dumps Chrome trace-event JSON
(``chrome://tracing`` / Perfetto load it directly) — the same artifact
shape as the device trace, so one viewer shows both.

Overhead discipline: the recorder ships DISABLED. ``span()`` on a
disabled recorder returns a shared no-op context manager — one attribute
read and a call, no allocation — so instrumentation can live permanently
in the hot path (tests/test_attribution.py pins the enabled cost at <2%
of a CPU LeNet step). Everything here is jax-free at import: the async
socket tier records spans from processes that must never pay the jax
import.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = ["SpanRecorder", "recorder", "span", "enabled"]


class _NullSpan:
    """Shared do-nothing context manager — the disabled fast path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _Span:
    __slots__ = ("_rec", "name", "cat", "args", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str, cat: str, args):
        self._rec = rec
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        rec = self._rec
        rec._record(self.name, self.cat, self._t0, t1 - self._t0, self.args)
        return False


class SpanRecorder:
    """Bounded, thread-safe buffer of completed spans.

    ``maxlen`` bounds memory on long runs (oldest spans fall off — the
    timeline is a sliding window, like LatencyWindow); ``dump()`` writes
    the Chrome trace-event JSON atomically (tmp + rename) so a reader
    polling the file mid-run never sees a torn document.
    """

    def __init__(self, maxlen: int = 65536):
        self.enabled = False
        self._events: deque = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._epoch_us = time.time() * 1e6 - self._t0 * 1e6
        self.dropped = 0          # spans recorded past maxlen (overwrote)

    # ---- lifecycle ---------------------------------------------------- #
    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.dropped = 0

    # ---- recording ---------------------------------------------------- #
    def span(self, name: str, cat: str = "engine",
             args: Optional[Dict] = None):
        """Context manager timing one region. Near-free when disabled."""
        if not self.enabled:
            return _NULL
        return _Span(self, name, cat, args)

    def instant(self, name: str, cat: str = "engine",
                args: Optional[Dict] = None) -> None:
        """Zero-duration marker (Chrome trace 'i' events)."""
        if not self.enabled:
            return
        self._record(name, cat, time.perf_counter(), None, args)

    def _record(self, name, cat, t0, dur_s, args) -> None:
        ev = (name, cat, t0, dur_s, threading.get_ident(), args)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    # ---- export ------------------------------------------------------- #
    def trace_events(self) -> List[Dict]:
        """Chrome trace-event dicts ('X' complete / 'i' instant), ts/dur
        in microseconds on the wall-clock epoch."""
        with self._lock:
            snap = list(self._events)
        pid = os.getpid()
        out: List[Dict] = []
        for name, cat, t0, dur_s, tid, args in snap:
            ev: Dict = {
                "name": name, "cat": cat, "pid": pid, "tid": tid,
                "ts": round(self._epoch_us + t0 * 1e6, 3),
            }
            if dur_s is None:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = round(dur_s * 1e6, 3)
            if args:
                ev["args"] = dict(args)
            out.append(ev)
        return out

    def dump(self, path: str) -> str:
        """Write the Chrome trace JSON atomically; returns the path.
        A killed writer leaves only sweepable ``.tmp.<pid>`` litter."""
        doc = {"traceEvents": self.trace_events(),
               "displayTimeUnit": "ms",
               "metadata": {"tool": "poseidon_tpu spans",
                            "dropped_spans": self.dropped}}
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


# The process-wide recorder: the engine enables it under --trace_out and
# every instrumented module records into it (one timeline per process).
recorder = SpanRecorder()


def span(name: str, cat: str = "engine", args: Optional[Dict] = None):
    """Module-level shorthand for ``recorder.span`` (the common call)."""
    return recorder.span(name, cat, args)


def enabled() -> bool:
    return recorder.enabled
