"""Per-layer device-time attribution from a ``jax.profiler`` trace.

The measurement ROADMAP item 2 is blocked on: which layers actually spend
the step's device time (AlexNet sits at 4.1% MFU and nobody can name the
top-3 sinks). The pipeline:

1. ``core/net.py`` wraps every layer's apply in ``jax.named_scope``, so
   each HLO instruction's ``op_name`` metadata carries the layer path —
   forward ops as ``.../jvp(conv1)/...``, backward ops as
   ``.../transpose(jvp(conv1))/...`` (autodiff preserves the scope). The
   arena/update phases (core/arena.py, solvers/updates.py) are scoped the
   same way.
2. A profiled step dumps an xplane protobuf. ``parse_xspace`` reads it
   with a ~100-line protobuf wire-format walker (shared varint helpers,
   data/varint.py) — no ``tensorflow.python.profiler`` import, the
   dependency the PR-4 attempt timed out fighting. A Chrome trace-event
   JSON (``*.trace.json[.gz]``) parses as the fallback.
3. Each op event joins back to its layer through the COMPILED module text
   (``compiled.as_text()``): instruction name -> op_name metadata ->
   layer scope (``hlo_scope_map``). This works identically on the CPU
   thunk runtime (events per HLO op on host threads) and the TPU device
   planes, because both name events after HLO instructions.
4. ``attribute`` folds event durations into a per-layer table — fwd/bwd
   ms, %-of-traced-op-time, analytic FLOPs (``layer_cost_table``), arithmetic
   intensity, per-layer MFU against a peak — with an ``(unattributed)``
   residual row so coverage is honest: named rows + residual always sum
   to the traced op time.

Everything here is host-side postprocessing: nothing runs inside a timed
loop (``measure_then_trace`` pins the discipline — timing first, trace
capture after).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import struct
from typing import Dict, List, Optional, Sequence, Tuple

from ..data.varint import read_varint

__all__ = [
    "parse_xspace", "load_trace_events", "hlo_scope_map", "scope_of",
    "comm_axis_of", "layer_cost_table", "attribute", "format_table",
    "measure_then_trace",
]


# --------------------------------------------------------------------------- #
# minimal protobuf wire-format walker (xplane.proto subset)
# --------------------------------------------------------------------------- #

def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes.
    Varints decode to int; length-delimited fields yield their bytes;
    fixed64/fixed32 yield raw bytes (decoded by the caller if needed)."""
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = read_varint(buf, pos)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = read_varint(buf, pos)
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        elif wt == 2:
            ln, pos = read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield fno, wt, v


def _map_entry(buf: bytes) -> Tuple[int, bytes]:
    """proto3 map<int64, Message> entry: {1: key varint, 2: value bytes}."""
    key, val = 0, b""
    for fno, _wt, v in _fields(buf):
        if fno == 1:
            key = v
        elif fno == 2:
            val = v
    return key, val


def _parse_stat(buf: bytes, stat_names: Dict[int, str]):
    """XStat -> (name, value). The oneof value: double(2)/uint64(3)/
    int64(4)/str(5)/bytes(6)/ref(7 — an id into stat_metadata whose NAME
    is the value, the xplane string-interning trick)."""
    name, value = None, None
    for fno, wt, v in _fields(buf):
        if fno == 1:
            name = stat_names.get(v, str(v))
        elif fno == 2:
            value = struct.unpack("<d", v)[0]
        elif fno in (3, 4):
            value = v
        elif fno == 5:
            value = v.decode("utf-8", "replace")
        elif fno == 6:
            value = v
        elif fno == 7:
            value = stat_names.get(v, str(v))
    return name, value


def parse_xspace(data: bytes) -> List[Dict]:
    """XSpace bytes -> [{name, lines: [{name, timestamp_ns, events:
    [{name, dur_ps, offset_ps, stats}]}]}] — exactly the subset
    attribution needs, parsed with the wire walker above."""
    planes: List[Dict] = []
    for fno, _wt, pbuf in _fields(data):
        if fno != 1:           # XSpace.planes
            continue
        plane = {"name": "", "lines": []}
        event_names: Dict[int, str] = {}
        stat_names: Dict[int, str] = {}
        line_bufs: List[bytes] = []
        for pf, _pw, pv in _fields(pbuf):
            if pf == 2:
                plane["name"] = pv.decode("utf-8", "replace")
            elif pf == 3:      # XPlane.lines
                line_bufs.append(pv)
            elif pf == 4:      # map<int64, XEventMetadata>
                k, mbuf = _map_entry(pv)
                for mf, _mw, mv in _fields(mbuf):
                    if mf == 2:
                        event_names[k] = mv.decode("utf-8", "replace")
            elif pf == 5:      # map<int64, XStatMetadata>
                k, mbuf = _map_entry(pv)
                for mf, _mw, mv in _fields(mbuf):
                    if mf == 2:
                        stat_names[k] = mv.decode("utf-8", "replace")
        for lbuf in line_bufs:
            line = {"name": "", "timestamp_ns": 0, "events": []}
            for lf, _lw, lv in _fields(lbuf):
                if lf == 2:
                    line["name"] = lv.decode("utf-8", "replace")
                elif lf == 3:
                    line["timestamp_ns"] = lv
                elif lf == 4:  # XLine.events
                    ev = {"name": "", "dur_ps": 0, "offset_ps": 0,
                          "stats": {}}
                    for ef, _ew, evv in _fields(lv):
                        if ef == 1:
                            ev["name"] = event_names.get(evv, str(evv))
                        elif ef == 2:
                            ev["offset_ps"] = evv
                        elif ef == 3:
                            ev["dur_ps"] = evv
                        elif ef == 4:
                            sn, sv = _parse_stat(evv, stat_names)
                            if sn is not None:
                                ev["stats"][sn] = sv
                    line["events"].append(ev)
            plane["lines"].append(line)
        planes.append(plane)
    return planes


# --------------------------------------------------------------------------- #
# trace loading (xplane preferred, Chrome trace-event JSON fallback)
# --------------------------------------------------------------------------- #

def _newest_run_dir(trace_dir: str) -> Optional[str]:
    runs = sorted(glob.glob(os.path.join(trace_dir, "plugins", "profile",
                                         "*")))
    return runs[-1] if runs else None


def load_trace_events(trace_dir: str) -> List[Dict]:
    """Flatten a ``jax.profiler`` dump into op-level events:
    ``[{name, dur_us, plane, line, stats}]``. Prefers the newest run's
    ``*.xplane.pb``; falls back to ``*.trace.json[.gz]``."""
    run = _newest_run_dir(trace_dir) or trace_dir
    out: List[Dict] = []
    for pb in sorted(glob.glob(os.path.join(run, "*.xplane.pb"))):
        with open(pb, "rb") as f:
            data = f.read()
        for plane in parse_xspace(data):
            for line in plane["lines"]:
                for ev in line["events"]:
                    out.append({"name": ev["name"],
                                "dur_us": ev["dur_ps"] / 1e6,
                                "t0_us": ev["offset_ps"] / 1e6,
                                "plane": plane["name"],
                                "line": line["name"],
                                "stats": ev["stats"]})
    if out:
        return out
    for tj in sorted(glob.glob(os.path.join(run, "*.trace.json*"))):
        opener = gzip.open if tj.endswith(".gz") else open
        with opener(tj, "rb") as f:
            doc = json.loads(f.read())
        for ev in doc.get("traceEvents", []):
            if ev.get("ph") != "X":
                continue
            out.append({"name": ev.get("name", ""),
                        "dur_us": float(ev.get("dur", 0.0)),
                        "t0_us": float(ev.get("ts", 0.0)),
                        "plane": str(ev.get("pid", "")),
                        "line": str(ev.get("tid", "")),
                        "stats": dict(ev.get("args", {}) or {})})
    return out


# --------------------------------------------------------------------------- #
# HLO instruction -> layer scope (the join key)
# --------------------------------------------------------------------------- #

# transform wrappers that PRESERVE the scope they wrap (peel to the
# inside); anything else in wrapper(..) form — jit(fn), pjit(fn), named
# computation frames — is a CALL frame whose argument is a function name,
# not a scope, and must be dropped (jit(loss) is the traced function
# 'loss', not the layer 'loss')
_PEELABLE = frozenset({
    "jvp", "transpose", "vmap", "remat", "rematted_computation",
    "checkpoint", "custom_jvp", "custom_vjp", "custom_jvp_call",
    "custom_vjp_call",
})

_WRAP_OPEN = re.compile(r"^([\w.\-]+)\(")


def _scope_components(op_name: str) -> List[str]:
    """Path components with wrappers peeled — aware that a SLASHED scope
    name splits a wrapper across components: in
    'transpose(jvp(inception_3a/3x3))/conv', the wrapper opens in the
    'transpose(jvp(inception_3a' component and closes two components
    later, so per-component peeling (the old ``_peel``) mangled every
    wrapped GoogLeNet scope into 'jvp(inception_3a' + '3x3)' and the
    whole model fell into the residual row. Leading PEELABLE wrapper
    opens are stripped wherever they appear, call frames (jit(fn)) drop
    their component entirely, and trailing close-parens — ours or an
    enclosing component's — are shed."""
    comps: List[str] = []
    for comp in op_name.split("/"):
        while True:
            m = _WRAP_OPEN.match(comp)
            if not m:
                break
            if m.group(1) in _PEELABLE:
                comp = comp[m.end():]
            else:
                comp = ""       # call frame: not a scope, drop it
                break
        comp = comp.rstrip(")")
        if comp:
            comps.append(comp)
    return comps


# collective named scopes emitted by the comm machinery (strategies.py
# arena buckets, spmd.py mesh collectives): each carries its mesh axis in
# the name, so a profiled step attributes comm time PER AXIS instead of
# lumping it into the residual row. Matched as whole path components.
COMM_SCOPE_RE = re.compile(
    r"^(grad_sync_bucket\d+|grad_rs_bucket\d+|grad_ar_bucket\d+"
    r"|param_ag_bucket\d+|hist_ag_bucket\d+|delta_rs_bucket\d+"
    r"|delta_ar_bucket\d+|delta_ag_bucket\d+"
    r"|tp_fwd_[\w.\-]+|tp_dx_[\w.\-]+"
    r"|grad_tp_[\w.\-]+|grad_fused_[\w.\-]+)$")

_COMM_AXIS_PREFIX = (
    ("grad_rs_bucket", "fsdp"), ("param_ag_bucket", "fsdp"),
    ("hist_ag_bucket", "fsdp"), ("delta_rs_bucket", "fsdp"),
    ("delta_ag_bucket", "fsdp"), ("grad_ar_bucket", "data"),
    ("delta_ar_bucket", "data"), ("grad_sync_bucket", "data"),
    ("tp_fwd_", "tp"), ("tp_dx_", "tp"),
)


def comm_axis_of(scope: str) -> Optional[str]:
    """Mesh axis a comm scope's collective rides, or None for non-comm
    scopes. The hierarchical per-leaf psums carry the axis as a suffix
    (``grad_tp_<layer>_<param>_fsdp`` / ``_data``)."""
    for prefix, axis in _COMM_AXIS_PREFIX:
        if scope.startswith(prefix):
            return axis
    if scope.startswith(("grad_tp_", "grad_fused_")):
        if scope.endswith("_fsdp"):
            return "fsdp"
        if scope.endswith("_data"):
            return "data"
    return None


def scope_of(op_name: str, layer_names, extra_scopes=frozenset()):
    """(scope, phase) for one op_name metadata path, or (None, None).

    ``layer_names`` may contain '/' (GoogLeNet's inception blobs), so the
    peeled path components are matched against each layer's own component
    sequence — longest layer first, contiguous subsequence. Phase is
    'bwd' when the path went through an autodiff transpose, else 'fwd';
    extra (non-layer) scopes — arena/update phases — report 'misc', and
    the comm machinery's per-bucket/per-axis collective scopes
    (``COMM_SCOPE_RE``) are recognized unconditionally so comm time
    lands in named per-axis rows rather than the residual."""
    comps = _scope_components(op_name)
    joined = "/".join(comps)
    for lname in sorted(layer_names, key=lambda s: -s.count("/")):
        ln = lname.split("/")
        for i in range(len(comps) - len(ln) + 1):
            if comps[i:i + len(ln)] == ln:
                phase = "bwd" if "transpose(" in op_name else "fwd"
                return lname, phase
    for c in comps:
        if COMM_SCOPE_RE.match(c):
            return c, "misc"
    for extra in extra_scopes:
        if extra in comps or extra in joined:
            return extra, "misc"
    return None, None


_COMP_HDR = re.compile(r"^(?:ENTRY\s+)?%([\w.\-]+)\s*\(")
_INST = re.compile(r"^(ROOT\s+)?%([\w.\-]+)\s*=")
_OP_NAME = re.compile(r'op_name="([^"]*)"')
_CALLEE = re.compile(r"(?:calls|to_apply|body|condition)=%([\w.\-]+)")


def hlo_scope_map(hlo_text: str, layer_names,
                  extra_scopes=frozenset()) -> Dict[str, Tuple[str, str]]:
    """Compiled-module text -> {instruction_name: (scope, phase)}.

    Trace events are named after HLO instructions (CPU thunks and TPU
    device lines alike), and instructions carry their source scope in
    ``op_name`` metadata; this is the whole join. Two wrinkles make it a
    small graph problem instead of one regex pass: XLA:CPU wraps
    multi-threaded kernels in metadata-less ``call``s to ``%parallel_*``
    computations, and parallelized fusion clones lose their own metadata
    — in both cases the scope lives on the instructions INSIDE the called
    computation. So: collect per-instruction direct scopes, then resolve
    call/fusion/while instructions through their callee computations
    (root's scope, else the members' majority) to a fixpoint.
    Instructions that still name no known scope are simply absent — they
    fall into the residual row."""
    layer_names = frozenset(layer_names)
    extra_scopes = frozenset(extra_scopes)
    resolved: Dict[str, Tuple[str, str]] = {}
    direct: Dict[str, Tuple[str, str]] = {}   # from own metadata only
    inst_callees: Dict[str, List[str]] = {}
    operand_users: Dict[str, List[str]] = {}  # operand -> [user insts]
    comp_insts: Dict[str, List[str]] = {}
    comp_root: Dict[str, str] = {}
    comp = None
    for line in hlo_text.splitlines():
        if line and not line[0].isspace():
            m = _COMP_HDR.match(line)
            comp = m.group(1) if m else comp
            continue
        ls = line.strip()
        m = _INST.match(ls)
        if not m:
            continue
        inst = m.group(2)
        rhs = ls.split("=", 1)[1]
        om = _OP_NAME.search(ls)
        if om and inst not in resolved:
            scope, phase = scope_of(om.group(1), layer_names, extra_scopes)
            if scope is not None:
                resolved[inst] = direct[inst] = (scope, phase)
        callees = [c.group(1) for c in _CALLEE.finditer(ls)]
        if callees:
            inst_callees.setdefault(inst, []).extend(callees)
        for ref in re.finditer(r"%([\w.\-]+)", rhs):
            operand_users.setdefault(ref.group(1), []).append(inst)
        if comp:
            comp_insts.setdefault(comp, []).append(inst)
            if m.group(1):
                comp_root[comp] = inst
    # one-hop neighbor inheritance: backend rewrites (the CPU layout pass
    # re-materializing a convolution) drop the op's own metadata but leave
    # it on the adjacent bitcast/copy — an unresolved instruction takes
    # the majority scope of its DIRECT-metadata users. One hop only, so
    # the residual row stays honest (no transitive flooding).
    for inst, users in operand_users.items():
        if inst in resolved:
            continue
        counts: Dict[Tuple[str, str], int] = {}
        for u in users:
            if u in direct:
                counts[direct[u]] = counts.get(direct[u], 0) + 1
        if counts:
            resolved[inst] = max(counts.items(), key=lambda kv: kv[1])[0]
    # fixpoint over the call graph (a parallel call wraps a fusion clone
    # wraps the fused computation — a few levels at most)
    for _ in range(8):
        cscope: Dict[str, Tuple[str, str]] = {}
        for c, insts in comp_insts.items():
            root = comp_root.get(c)
            if root in resolved:
                cscope[c] = resolved[root]
                continue
            counts: Dict[Tuple[str, str], int] = {}
            for i in insts:
                if i in resolved:
                    counts[resolved[i]] = counts.get(resolved[i], 0) + 1
            if counts:
                cscope[c] = max(counts.items(), key=lambda kv: kv[1])[0]
        changed = False
        for inst, callees in inst_callees.items():
            if inst in resolved:
                continue
            for c in callees:
                if c in cscope:
                    resolved[inst] = cscope[c]
                    changed = True
                    break
        if not changed:
            break
    # DOWNWARD inheritance: XLA:CPU's thunk registry names the CLONED
    # fusion instruction INSIDE a %parallel_* computation
    # ('copy_bitcast_fusion.2.clone' in %parallel_copy_bitcast_fusion.2),
    # which carries no metadata of its own — the upward fixpoint resolves
    # the CALLER, so push each called computation's caller scope down onto
    # its unresolved members (majority across call sites, a few levels)
    for _ in range(8):
        comp_counts: Dict[str, Dict[Tuple[str, str], int]] = {}
        for inst, callees in inst_callees.items():
            s = resolved.get(inst)
            if s is None:
                continue
            for c in callees:
                cc = comp_counts.setdefault(c, {})
                cc[s] = cc.get(s, 0) + 1
        changed = False
        for c, counts in comp_counts.items():
            s = max(counts.items(), key=lambda kv: kv[1])[0]
            for i in comp_insts.get(c, ()):
                if i not in resolved:
                    resolved[i] = s
                    changed = True
        if not changed:
            break
    # last-chance neighbor rescue, ONE snapshot pass: a backend-rewritten
    # instruction whose metadata is gone AND whose direct-metadata
    # neighbors are all metadata-less calls (the CPU layout pass
    # re-materializing a backward convolution between two parallel calls)
    # takes the majority scope of its RESOLVED operands/users. Snapshot
    # semantics — rescued instructions never feed further rescues — so
    # there is no transitive flooding and the residual row stays honest.
    snapshot = dict(resolved)
    inst_operands: Dict[str, List[str]] = {}
    for op, users in operand_users.items():
        for u in users:
            inst_operands.setdefault(u, []).append(op)
    for inst in {i for insts in comp_insts.values() for i in insts}:
        if inst in snapshot:
            continue
        counts = {}
        for nb in operand_users.get(inst, []) + inst_operands.get(inst, []):
            s = snapshot.get(nb)
            if s is not None:
                counts[s] = counts.get(s, 0) + 1
        if counts:
            resolved[inst] = max(counts.items(), key=lambda kv: kv[1])[0]
    return resolved


# --------------------------------------------------------------------------- #
# analytic per-layer cost model (FLOPs + bytes -> arithmetic intensity)
# --------------------------------------------------------------------------- #

def _shape_elems(shape) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


def layer_cost_table(net, dtype_bytes: int = 4) -> Dict[str, Dict]:
    """{layer: {flops, bytes, act_bytes, intensity}} for one train step
    (fwd+bwd), from blob/param shapes — the analytic model the FLOPs
    column joins from (XLA's cost_analysis reports only the whole-module
    total).

    Conv/FC are exact MAC counts (x2 for mul+add; backward = dW + dX =
    2x forward). Pool/LRN/elementwise are per-element op estimates —
    they exist to rank sinks and compute intensity, not to be a
    simulator. Bytes = activations in + out + params, x3 for the
    backward's re-reads and gradient writes.

    ``act_bytes`` is the layer's STORED forward activation footprint —
    the top blobs autodiff keeps live until the backward pass consumes
    them. It is the per-layer column core/remat.py's budget knapsack
    ranks against recompute FLOPs; an in-place top (same name as a
    bottom) still counts once, matching what the trace stores."""
    out: Dict[str, Dict] = {}
    for layer in net.layers:
        lp = layer.lp
        tops = [net.blob_shapes[t] for t in lp.top if t in net.blob_shapes]
        bots = [net.blob_shapes[b] for b in lp.bottom
                if b in net.blob_shapes]
        out_elems = sum(_shape_elems(s) for s in tops)
        in_elems = sum(_shape_elems(s) for s in bots)
        defs = net.param_defs.get(layer.name, [])
        pcount = sum(p.count for p in defs)
        t = layer.TYPE
        if t == "CONVOLUTION" and defs and len(defs[0].shape) == 4:
            k, cg, r, s = defs[0].shape
            n, _, ho, wo = tops[0]
            fwd = 2.0 * n * ho * wo * k * cg * r * s
        elif t in ("INNER_PRODUCT",) and defs:
            batch = bots[0][0] if bots else 1
            wcount = max((p.count for p in defs if len(p.shape) == 2),
                         default=pcount)
            fwd = 2.0 * batch * wcount
        elif t == "POOLING":
            ksz = max(1, int(getattr(lp.pooling_param, "kernel_size", 2)))
            fwd = float(out_elems) * ksz * ksz
        elif t == "LRN":
            local = max(1, int(getattr(lp.lrn_param, "local_size", 5)))
            fwd = float(in_elems) * (2 * local + 4)
        elif t in ("SOFTMAX", "SOFTMAX_LOSS"):
            fwd = 5.0 * in_elems
        else:
            fwd = float(max(in_elems, out_elems))
        flops = 3.0 * fwd                       # fwd + (dW + dX) backward
        bytes_ = 3.0 * (in_elems + out_elems + pcount) * dtype_bytes
        out[layer.name] = {
            "flops": flops,
            "bytes": bytes_,
            "act_bytes": int(out_elems) * int(dtype_bytes),
            "intensity": round(flops / bytes_, 3) if bytes_ else None,
        }
    return out


# --------------------------------------------------------------------------- #
# the attribution table
# --------------------------------------------------------------------------- #

RESIDUAL = "(unattributed)"


def attribute(events: Sequence[Dict], scope_map: Dict[str, Tuple[str, str]],
              cost_table: Optional[Dict[str, Dict]] = None,
              peak_flops: Optional[float] = None,
              steps: int = 1,
              tracer_overhead_ms: Optional[float] = None) -> Dict:
    """Fold trace events into the per-layer table.

    Only OP events enter the accounting: an event whose ``stats`` carry an
    ``hlo_op`` (the profiler's own op marker), whose name is a known
    instruction, or that sits on a device plane (TPU op lines carry the
    instruction name but not always the stat). Python/TraceMe/runtime
    housekeeping events are excluded from both numerator and denominator —
    the table answers "where does the traced op time go", and the residual
    row reports op time whose instruction metadata named no known scope.

    Accounting is SELF time: op events nest (a while op contains its body
    ops on the same thread line, a fusion its producers), so each event is
    billed its duration minus its direct op children's — flame-graph
    attribution, never double-counted. ``steps`` divides a multi-step
    trace down to per-step ms.

    ``tracer_overhead_ms``: on the CPU thunk runtime the tracer costs
    ~10 us PER OP EVENT, so a loopy op (pool backward's select-and-scatter
    runs one thunk per window) reads far slower traced than untraced. Pass
    ``traced_wall - untimed_wall`` here and the overhead is stripped
    uniformly per event before accounting (reported back as
    ``tracer_overhead_ms_stripped``). Leave None on TPU — device-plane
    events are hardware timings and carry no host tracer cost."""
    steps = max(1, int(steps))

    # 1) select op events, keyed for the scope join
    ops: List[Tuple] = []          # (plane, line, t0, dur, key, known)
    for ev in events:
        key = ev.get("stats", {}).get("hlo_op") or ev.get("name", "")
        if isinstance(key, bytes):
            key = key.decode("utf-8", "replace")
        known = key in scope_map
        if not known:
            # device event names sometimes decorate the instruction name
            # ('%fusion.3', an extra trailing '.<n>'); strip and retry
            # before consigning the event to the residual row
            alt = key.lstrip("%")
            if alt not in scope_map:
                alt = re.sub(r"\.\d+$", "", alt)
            if alt in scope_map:
                key, known = alt, True
        # TPU device planes also carry whole-step lines ("XLA Modules",
        # "Steps") whose events span the entire dispatch — counting those
        # as residual would halve coverage. Only the op line ("XLA Ops")
        # qualifies an unknown device event as op time.
        on_device_op_line = (
            str(ev.get("plane", "")).startswith("/device:")
            and "op" in str(ev.get("line", "")).lower())
        if not known and "hlo_op" not in ev.get("stats", {}) \
                and not on_device_op_line:
            continue                       # not an op event at all
        ops.append((ev.get("plane", ""), ev.get("line", ""),
                    float(ev.get("t0_us", 0.0)),
                    float(ev.get("dur_us", 0.0)), key, known))

    # 2) per thread line, subtract each op's direct op-children time
    self_us: List[float] = [0.0] * len(ops)
    children: List[int] = [0] * len(ops)   # direct op-children count
    by_line: Dict[Tuple, List[int]] = {}
    for i, op in enumerate(ops):
        by_line.setdefault((op[0], op[1]), []).append(i)
    for idxs in by_line.values():
        idxs.sort(key=lambda i: (ops[i][2], -ops[i][3]))
        stack: List[int] = []              # enclosing-op indices
        for i in idxs:
            _, _, t0, dur, _, _ = ops[i]
            while stack and t0 >= ops[stack[-1]][2] + ops[stack[-1]][3]:
                stack.pop()
            self_us[i] = dur
            if stack:
                self_us[stack[-1]] -= dur  # parent loses the child's time
                children[stack[-1]] += 1
            stack.append(i)

    # the tracer bills ~c per EVENT, and a child's bookkeeping lands in
    # its parent's self-time window — so debit each op c * (1 + its
    # direct children). This is what rescues the while-loop ops (one
    # thunk event per loop trip) from reading as the top sink.
    per_event_oh = 0.0
    if tracer_overhead_ms and ops:
        per_event_oh = max(tracer_overhead_ms, 0.0) * 1e3 / len(ops)

    per_scope: Dict[str, Dict[str, float]] = {}
    residual_us = 0.0
    residual_ops: Dict[str, float] = {}
    total_us = 0.0
    for (_, _, _t0, _dur, key, known), dur, nchild in zip(ops, self_us,
                                                          children):
        dur = max(dur - per_event_oh * (1 + nchild), 0.0)
        total_us += dur
        if not known:
            residual_us += dur
            residual_ops[key] = residual_ops.get(key, 0.0) + dur
            continue
        scope, phase = scope_map[key]
        row = per_scope.setdefault(scope, {"fwd": 0.0, "bwd": 0.0,
                                           "misc": 0.0})
        row[phase if phase in row else "misc"] += dur
    rows: List[Dict] = []
    for scope, acc in per_scope.items():
        tot_ms = (acc["fwd"] + acc["bwd"] + acc["misc"]) / 1e3 / steps
        row = {
            "layer": scope,
            "fwd_ms": round(acc["fwd"] / 1e3 / steps, 4),
            "bwd_ms": round(acc["bwd"] / 1e3 / steps, 4),
            "total_ms": round(tot_ms, 4),
            "pct_of_traced": round(100.0 * (acc["fwd"] + acc["bwd"] +
                                          acc["misc"]) / total_us, 2)
            if total_us else 0.0,
        }
        cost = (cost_table or {}).get(scope)
        if cost:
            row["flops"] = cost["flops"]
            row["intensity"] = cost["intensity"]
            if peak_flops and tot_ms > 0:
                row["mfu"] = round(cost["flops"] / (tot_ms / 1e3)
                                   / peak_flops, 4)
        rows.append(row)
    rows.sort(key=lambda r: -r["total_ms"])
    total_ms = total_us / 1e3 / steps
    res_ms = residual_us / 1e3 / steps
    coverage = 1.0 - (residual_us / total_us) if total_us else 0.0
    top_res = sorted(residual_ops.items(), key=lambda kv: -kv[1])[:5]
    return {
        "rows": rows,
        "residual": {
            "layer": RESIDUAL,
            "total_ms": round(res_ms, 4),
            "pct_of_traced": round(100.0 * residual_us / total_us, 2)
            if total_us else 0.0,
            "top_ops": [{"op": k, "ms": round(v / 1e3 / steps, 4)}
                        for k, v in top_res],
        },
        "total_ms": round(total_ms, 4),
        "coverage": round(coverage, 4),
        "top_sinks": [r["layer"] for r in rows[:3]],
        "op_events": len(ops),
        "tracer_overhead_ms_stripped": round(per_event_oh * len(ops) / 1e3,
                                             3),
    }


def format_table(result: Dict, title: str = "") -> str:
    """Human-readable rendering of one attribution result."""
    lines = []
    if title:
        lines.append(title)
    hdr = (f"{'layer':<28}{'fwd ms':>9}{'bwd ms':>9}{'total':>9}"
           f"{'%traced':>8}{'GFLOPs':>9}{'F/B':>7}{'MFU':>7}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for r in result["rows"]:
        gf = r.get("flops")
        lines.append(
            f"{r['layer']:<28}{r['fwd_ms']:>9.3f}{r['bwd_ms']:>9.3f}"
            f"{r['total_ms']:>9.3f}{r['pct_of_traced']:>8.2f}"
            f"{(gf / 1e9 if gf else 0):>9.2f}"
            f"{(r.get('intensity') or 0):>7.1f}"
            f"{(r.get('mfu') if r.get('mfu') is not None else float('nan')):>7.3f}")
    res = result["residual"]
    lines.append(f"{res['layer']:<28}{'':>9}{'':>9}"
                 f"{res['total_ms']:>9.3f}{res['pct_of_traced']:>8.2f}")
    lines.append(f"named coverage: {result['coverage']:.1%} of "
                 f"{result['total_ms']:.3f} ms traced op time; top sinks: "
                 f"{', '.join(result['top_sinks'])}")
    return "\n".join(lines)


# --------------------------------------------------------------------------- #
# capture discipline: timing FIRST, trace capture AFTER
# --------------------------------------------------------------------------- #

def measure_then_trace(run_step, trace_dir: str, iters: int = 3) -> Dict:
    """Run the TIMED loop first (min-wall over ``iters`` calls, the
    one-sided-noise estimator bench.py uses), then capture exactly one
    traced step into ``trace_dir``. Profiler overhead can therefore never
    contaminate the reported step time — the same discipline as the
    headline trace capture at the bottom of bench.main (and pinned by
    tests/test_attribution.py::test_trace_capture_stays_after_timing).

    ``run_step`` is a zero-arg callable that dispatches one step and
    blocks until it completes. Returns {"step_ms", "walls_ms"}."""
    import time as _time

    import jax

    walls = []
    for _ in range(max(1, iters)):
        t0 = _time.perf_counter()
        run_step()
        walls.append(_time.perf_counter() - t0)
    jax.profiler.start_trace(trace_dir)
    try:
        t0 = _time.perf_counter()
        run_step()
        traced_wall = _time.perf_counter() - t0
    finally:
        jax.profiler.stop_trace()
    return {"step_ms": round(min(walls) * 1e3, 4),
            "walls_ms": [round(w * 1e3, 3) for w in walls],
            # traced-vs-untraced gap = total tracer overhead; attribute()
            # strips it per event on host-traced (CPU) runs
            "traced_step_ms": round(traced_wall * 1e3, 4)}
