"""Static communication accounting: the stats.hpp analog for compiled SPMD.

The reference instruments its data plane at runtime — bytes serialized per
oplog clock, server push bytes, per-table Get/Inc latencies — via 198
compile-time macros (ps/src/petuum_ps_common/util/stats.hpp:19-80) dumped as
YAML at shutdown. In a compiled SPMD step the data plane is the set of
collectives XLA emits, and their cost is *statically determined* by parameter
shapes, the per-layer strategy, and the mesh — so the equivalent accounting
can be computed exactly, per layer, before the first step runs:

- DENSE  — ring all-reduce: each device sends/receives 2*(n-1)/n of the
           param bytes per step.
- SFB    — all-gather of the two sufficient factors (B_global, M) and
           (B_global, K): each device receives (n-1)/n of both.
- TOPK   — managed-comm tier: only the top-k entries are *logically*
           exchanged (k * (4B index + value bytes)), the SSPAggr budget
           accounting. (The compiled flat-mesh implementation psums a
           sparsified dense tensor — logical bytes are what a wire-format
           DCN transport pays, and what the bandwidth budget meters.)
- LOCAL  — nothing crosses the wire.

On a two-tier mesh (CommConfig.dcn_axis) bytes are split per tier: DENSE/SFB
ride both axes; TOPK pays dense all-reduce intra-slice (fast ICI) and
compressed exchange inter-slice (slow DCN).

The per-run stats.yaml gains a ``comm:`` section with this table plus an
estimated comm/compute split (TransTimeEstimate's mbps math,
trans_time_estimate.hpp:10-15, applied to the static bytes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np

from ..parallel.strategies import (DENSE, LOCAL, SFB, TOPK, CommConfig,
                                   budget_topk_fraction)

# Default link-speed assumptions for the estimated comm-time split, in GB/s
# per device. Overridable via CommCostModel; the absolute numbers matter less
# than the ICI:DCN ratio that motivates the two-tier design.
ICI_GBPS = 100.0   # intra-slice interconnect, per-device
DCN_GBPS = 6.25    # inter-slice data-center network, per-device (~50 Gbit)


@dataclass
class CommCostModel:
    ici_gbps: float = ICI_GBPS
    dcn_gbps: float = DCN_GBPS
    topk_index_bytes: int = 4


def _allreduce_bytes(param_bytes: float, n: int) -> float:
    """Ring all-reduce: reduce-scatter + all-gather, 2*(n-1)/n each way."""
    if n <= 1:
        return 0.0
    return 2.0 * (n - 1) / n * param_bytes


def _allgather_bytes(total_bytes: float, n: int) -> float:
    """Ring all-gather: each device receives everyone else's shard."""
    if n <= 1:
        return 0.0
    return (n - 1) / n * total_bytes


def layer_comm_table(
    net,
    comm: Optional[CommConfig],
    mesh,
    cost: Optional[CommCostModel] = None,
) -> Dict[str, Dict]:
    """Per-layer static comm accounting: strategy, bytes per step per device
    split by tier, the dense-alternative bytes, and the saving factor.

    ``net`` is a built Net (param shapes + blob shapes known); bytes use the
    active compute dtype for gradients.
    """
    from ..config import policy
    comm = comm or CommConfig()
    cost = cost or CommCostModel()
    dtype_bytes = np.dtype(policy().compute_dtype).itemsize
    # exchanged bytes ride the wire dtype when one is set (DenseRowFloat16
    # analog); the dense-alternative baseline stays at the compute dtype
    wd = comm.wire_jnp_dtype()  # validates the string
    wire_bytes = np.dtype(wd).itemsize if wd is not None else dtype_bytes

    # accounting is purely static — accept a real Mesh OR a plain
    # {axis: size} dict, so hypothetical topologies need no physical devices
    shape = dict(mesh) if isinstance(mesh, dict) else dict(mesh.shape)
    n_ici = shape[comm.axis]
    n_dcn = shape[comm.dcn_axis] if comm.dcn_axis else 1
    n_total = n_ici * n_dcn
    topk_fraction = budget_topk_fraction(net, comm)

    table: Dict[str, Dict] = {}
    for layer in net.layers:
        defs = net.param_defs.get(layer.name)
        if not defs:
            continue
        strategy = comm.strategy_for(layer.name)
        param_count = sum(p.count for p in defs)
        param_bytes = param_count * dtype_bytes
        sent_param_bytes = param_count * wire_bytes
        dense_ici = _allreduce_bytes(param_bytes, n_total if n_dcn == 1
                                     else n_ici)
        dense_dcn = _allreduce_bytes(param_bytes, n_dcn) if n_dcn > 1 else 0.0
        sent_ici = _allreduce_bytes(sent_param_bytes, n_total if n_dcn == 1
                                    else n_ici)
        sent_dcn = (_allreduce_bytes(sent_param_bytes, n_dcn)
                    if n_dcn > 1 else 0.0)

        ici_b = dcn_b = 0.0
        if strategy == DENSE:
            ici_b, dcn_b = sent_ici, sent_dcn
        elif strategy == SFB:
            # factors: a = top diff (B_global, M), b = bottom data (B_global, K)
            wdef = next((p for p in defs if len(p.shape) == 2), None)
            if wdef is not None:
                m, k = wdef.shape
                b_global = net.blob_shapes[layer.lp.bottom[0]][0] * n_total
                total = b_global * (m + k) * wire_bytes
                ici_b = _allgather_bytes(total, n_total if n_dcn == 1
                                         else n_ici)
                dcn_b = _allgather_bytes(total, n_dcn) if n_dcn > 1 else 0.0
                # bias still rides a dense psum
                bias = sum(p.count for p in defs) - m * k
                ici_b += _allreduce_bytes(bias * wire_bytes,
                                          n_total if n_dcn == 1 else n_ici)
            else:
                ici_b, dcn_b = sent_ici, sent_dcn
        elif strategy == TOPK:
            k_entries = max(1, int(param_count * topk_fraction))
            logical = k_entries * (cost.topk_index_bytes + wire_bytes)
            if n_dcn > 1:
                # hierarchical: dense all-reduce intra-slice, compressed
                # exchange inter-slice
                ici_b = sent_ici
                dcn_b = _allreduce_bytes(logical, n_dcn)
            else:
                ici_b = _allreduce_bytes(logical, n_total)
        elif strategy == LOCAL:
            pass

        dense_total = dense_ici + dense_dcn
        sent_total = ici_b + dcn_b
        est_ms = (ici_b / (cost.ici_gbps * 1e9) +
                  dcn_b / (cost.dcn_gbps * 1e9)) * 1e3
        table[layer.name] = {
            "strategy": strategy,
            "param_count": int(param_count),
            "ici_bytes_per_step": int(ici_b),
            "dcn_bytes_per_step": int(dcn_b),
            "dense_alternative_bytes": int(dense_total),
            # None (YAML null) when nothing is sent — inf is not valid YAML
            "savings_vs_dense": round(dense_total / sent_total, 2)
            if sent_total else None,
            "est_comm_ms": round(est_ms, 4),
        }
    return table


def comm_summary(table: Dict[str, Dict],
                 measured_step_ms: Optional[float] = None) -> Dict:
    """Run-level totals + the comm/compute split estimate."""
    ici = sum(r["ici_bytes_per_step"] for r in table.values())
    dcn = sum(r["dcn_bytes_per_step"] for r in table.values())
    dense = sum(r["dense_alternative_bytes"] for r in table.values())
    est_ms = sum(r["est_comm_ms"] for r in table.values())
    out = {
        "ici_bytes_per_step": int(ici),
        "dcn_bytes_per_step": int(dcn),
        "total_bytes_per_step": int(ici + dcn),
        "dense_alternative_bytes": int(dense),
        "savings_vs_dense": round(dense / (ici + dcn), 2)
        if (ici + dcn) else None,
        "est_comm_ms_per_step": round(est_ms, 4),
    }
    if measured_step_ms:
        # upper bound: assumes zero overlap; the DWBP-style in-backward taps
        # exist precisely to hide this fraction behind compute
        out["measured_step_ms"] = round(measured_step_ms, 4)
        out["est_comm_fraction_if_unoverlapped"] = round(
            min(1.0, est_ms / measured_step_ms), 4)
    return out


# --------------------------------------------------------------------------- #
# elastic-membership telemetry (the async-SSP tier's churn counters)
# --------------------------------------------------------------------------- #

def membership_counters(service=None, client=None) -> Dict[str, float]:
    """The async tier's membership-churn counters, normalized for the
    engine's periodic display and stats.yaml — churn must be visible
    without log-grepping. ``service`` (the rank-0 ParamService) carries
    the authoritative admissions/evictions/rejoins counters; every other
    rank reports its client-side view (member count, failed peers,
    reconnects). Either argument may be None."""
    out: Dict[str, float] = {}
    if service is not None:
        # full membership: a finished worker is still a member (only
        # retire removes a slot), matching the data-assignment key
        out["members"] = float(len(service.members))
        out["admissions"] = float(service.admissions)
        out["evictions"] = float(service.evictions)
        out["rejoins"] = float(service.rejoins)
        out["failed"] = float(len(service.failed_workers))
        out["retired"] = float(len(service.retired))
    elif client is not None:
        out["members"] = float(len(client.members))
        out["failed"] = float(len(client.failed))
        out["reconnects"] = float(client.reconnects)
    return out


def format_membership(counters: Dict[str, float]) -> str:
    """One display line: ``members = 3, admissions = 1, ...`` (ints — the
    counters are counts; float is just the stats-registry convention)."""
    return ", ".join(f"{k} = {int(v)}" for k, v in sorted(counters.items()))


# --------------------------------------------------------------------------- #
# managed-communication telemetry (the async-SSP tier's per-link counters)
# --------------------------------------------------------------------------- #

def managed_comm_counters(client=None) -> Dict[str, float]:
    """The async tier's per-link managed-communication counters (SSPAggr
    accounting), normalized for the engine's periodic display, stats.yaml
    and the metrics endpoint: actual frame bytes on both channels,
    the fraction of flush traffic deferred into the residual, measured
    link goodput, and cadence-backoff escalations. Empty when no client
    exists (sync tiers)."""
    if client is None or not hasattr(client, "comm_counters"):
        return {}
    return dict(client.comm_counters())


def format_comm(counters: Dict[str, float]) -> str:
    """One display line next to ``[membership]``:
    ``bytes_sent = 1.2 MB, deferred_fraction = 0.31, ...``."""
    def fmt(k: str, v: float) -> str:
        # byte gauges (bytes_sent/bytes_recv/wire_bytes_saved) scale to
        # kB/MB; everything else is a fraction, rate or count
        if k.startswith("bytes") or k == "wire_bytes_saved":
            if v >= 1e6:
                return f"{k} = {v / 1e6:.1f} MB"
            return f"{k} = {v / 1e3:.1f} kB"
        if k in ("deferred_fraction", "effective_mbps"):
            return f"{k} = {v:.3f}"
        return f"{k} = {int(v)}"
    return ", ".join(fmt(k, v) for k, v in sorted(counters.items()))
