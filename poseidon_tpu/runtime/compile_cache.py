"""Fast restart: persistent compile cache + AOT-serialized step executables.

Elasticity is only cheap if (re)starting a process is cheap, and today a
(re)start pays full JIT — multi-minute on GoogLeNet (it is why the async
tier's FIRST-clock gate needed a generously scaled timeout). Two layers
attack that, both keyed so a restarted-or-new worker with the same job
config hits them:

1. **Persistent XLA compile cache** (``jax.experimental.compilation_cache``
   riding the ``jax_compilation_cache_dir`` config): every XLA compile is
   content-addressed into ``cache_dir``; a restart re-traces but the
   multi-minute backend compile becomes a disk read. Wired through train
   AND serve (``--compile_cache_dir``), because a serving replica's bucket
   warm-up is the same cold-start bill.

2. **AOT step-executable store** (``jax.experimental.serialize_executable``):
   the compiled train-step executable itself, serialized under
   ``<cache_dir>/aot/`` keyed by (model, shapes, mesh, backend, policy).
   A restart that matches the key skips tracing AND compilation — the
   engine loads the executable and dispatches it directly (building on the
   abstract-topology lower/compile flow of ``scripts/aot_tpu_check.py``,
   but serialized for the REAL local topology and reloaded across process
   boundaries).

Layer 2 is strictly best-effort: any mismatch (jax version, device kind,
donation flags, numeric policy — all folded into the key) or
deserialization failure falls back to the jit path, which layer 1 still
makes fast. Nothing here is load-bearing for numerics: the executable IS
the jit-compiled program, byte-identified by its lowering.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
from typing import Any, Dict, Optional

__all__ = ["enable_compile_cache", "disable_compile_cache",
           "cache_entries", "step_key",
           "save_step_executable", "load_step_executable", "aot_entries",
           "load_tuned", "save_tuned", "tuned_path"]


def enable_compile_cache(cache_dir: str,
                         min_compile_time_s: float = 0.0) -> str:
    """Point jax's persistent compilation cache at ``cache_dir`` (created
    if missing). ``min_compile_time_s=0`` caches every program — the
    tier-1/CPU default, where even sub-second compiles are worth a disk
    hit; raise it on TPU if tiny-program churn ever matters. Returns the
    resolved absolute path. Must run before the programs it should cache
    are compiled (already-compiled programs in this process stay in the
    in-memory jit cache either way)."""
    import jax

    cache_dir = os.path.abspath(os.path.expanduser(cache_dir))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_s))
    try:
        # cache even tiny programs (the knob exists from jax 0.4.16 on;
        # -1 disables the entry-size floor)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # noqa: BLE001 — older jax: floor simply stays
        pass
    try:
        # the cache object memoizes its first initialization: a process
        # that already compiled something (with NO cache configured) must
        # reset it or the new dir is silently ignored
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — fresh process: nothing to reset
        pass
    return cache_dir


def disable_compile_cache() -> None:
    """Turn the persistent cache back OFF for this process.

    The cache config is process-global: a test (or embedder) that enabled
    it against a temporary directory and walks away leaves EVERY later
    compile in the process serializing/deserializing through that path —
    and once the directory is garbage-collected out from under jax
    (pytest keeps only the last few tmp_path dirs), later cache reads
    deserialize torn entries and take the whole process down with a
    SIGSEGV/abort deep inside jax. This was the long-standing flaky
    tier-1 crash: the PR-6 compile-cache tests enabled the cache at a
    tmp_path and never disabled it. Pair every test-scoped
    ``enable_compile_cache`` with a ``finally: disable_compile_cache()``.
    """
    import jax

    jax.config.update("jax_compilation_cache_dir", None)
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — nothing initialized: nothing to do
        pass


def cache_entries(cache_dir: str) -> int:
    """How many compiled programs the persistent cache holds (the ``-atime``
    sidecar files jax writes per entry are not counted)."""
    try:
        return sum(1 for n in os.listdir(cache_dir) if n.endswith("-cache"))
    except OSError:
        return 0


# --------------------------------------------------------------------------- #
# AOT step-executable store
# --------------------------------------------------------------------------- #

def _canon(obj: Any) -> Any:
    """JSON-stable canonicalization for key parts (tuples -> lists, dict
    keys sorted by json, numpy dtypes -> str)."""
    if isinstance(obj, dict):
        return {str(k): _canon(v) for k, v in sorted(obj.items(),
                                                     key=lambda kv: str(kv[0]))}
    if isinstance(obj, (list, tuple)):
        return [_canon(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


def step_key(**parts: Any) -> str:
    """Content key for a serialized step executable. Callers fold in
    everything that changes the compiled program: model name, param
    shapes, batch shapes/dtypes, mesh axes/shape, backend + device kind,
    jax version, donation flags, numeric policy. Same parts -> same key on
    a restarted process; ANY drift -> clean miss (never a stale load)."""
    blob = json.dumps(_canon(parts), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:32]


def _aot_dir(cache_dir: str) -> str:
    return os.path.join(cache_dir, "aot")


def _aot_path(cache_dir: str, key: str) -> str:
    return os.path.join(_aot_dir(cache_dir), f"step_{key}.aotexec")


def aot_entries(cache_dir: str) -> int:
    try:
        return sum(1 for n in os.listdir(_aot_dir(cache_dir))
                   if n.endswith(".aotexec"))
    except OSError:
        return 0


def save_step_executable(cache_dir: str, key: str, compiled) -> Optional[str]:
    """Serialize a jax Compiled object under the AOT store (atomic tmp +
    rename — a torn write can never shadow a good entry). Returns the
    entry path, or None when serialization is unsupported for this
    program/backend (best-effort by design)."""
    from jax.experimental.serialize_executable import serialize

    try:
        payload = pickle.dumps(serialize(compiled))
    except Exception as e:  # noqa: BLE001 — fall back to the compile cache
        from .metrics import log
        log(f"compile_cache: step executable not serializable "
            f"({type(e).__name__}: {e}); persistent cache still applies")
        return None
    path = _aot_path(cache_dir, key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
    return path


def load_step_executable(cache_dir: str, key: str):
    """Reload a serialized step executable; None on miss or ANY failure
    (a stale/foreign entry must degrade to a recompile, never an abort).
    The returned object is directly callable with the original call
    signature."""
    path = _aot_path(cache_dir, key)
    if not os.path.exists(path):
        return None
    from jax.experimental.serialize_executable import deserialize_and_load

    try:
        with open(path, "rb") as f:
            payload, in_tree, out_tree = pickle.loads(f.read())
        return deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:  # noqa: BLE001 — miss, not abort
        from .metrics import log
        log(f"compile_cache: failed to reload AOT step {key} "
            f"({type(e).__name__}: {e}); recompiling")
        return None


def describe(cache_dir: str) -> Dict[str, int]:
    """Telemetry: entry counts for stats/bench output."""
    return {"xla_cache_entries": cache_entries(cache_dir),
            "aot_step_entries": aot_entries(cache_dir)}


# --------------------------------------------------------------------------- #
# Tuned-policy store: measured decisions persisted next to the executables
# --------------------------------------------------------------------------- #
#
# The per-layer conv lowering-strategy choice (ops/conv_tune.py) is a
# MEASURED decision keyed by (layer shape, backend, device kind) — the same
# restart economics as the AOT executables above, so it lives in the same
# cache directory: a restarted (or brand-new, elastically admitted) process
# with the same job config loads the winner instead of re-measuring. One
# JSON file per (namespace, key), atomic rename, any read failure = clean
# miss. ROADMAP item 5's general `tune` mode is this store grown one
# namespace per policy knob.

def tuned_path(cache_dir: str, namespace: str, key: str) -> str:
    return os.path.join(cache_dir, "tuned", f"{namespace}-{key}.json")


def load_tuned(cache_dir: str, namespace: str, key: str) -> Optional[Dict]:
    """The persisted decision document, or None on miss/any failure (a
    torn or foreign entry degrades to a re-measure, never an abort — this
    is called mid-Net-construction, where a raise would kill the run). A
    clean miss (no file) is silent; a file that EXISTS but cannot be
    parsed is logged loudly, because it means a writer died mid-write or
    the store was hand-edited — the entry will be re-measured and
    rewritten."""
    if not cache_dir:
        return None
    path = tuned_path(cache_dir, namespace, key)
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        from .metrics import log
        log(f"compile_cache: tuned entry {namespace}-{key} at {path} is "
            f"torn/unreadable ({type(e).__name__}: {e}); treating as a "
            f"miss — will re-measure and overwrite")
        return None


def save_tuned(cache_dir: str, namespace: str, key: str,
               doc: Dict) -> Optional[str]:
    """Persist a decision document (atomic tmp + rename). Best-effort:
    returns the path, or None when the store is disabled/unwritable."""
    if not cache_dir:
        return None
    path = tuned_path(cache_dir, namespace, key)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path
    except OSError as e:
        from .metrics import log
        log(f"compile_cache: tuned entry {namespace}-{key} not persisted "
            f"({e}); will re-measure next process")
        return None
