"""Layout evidence: transposes extracted from the compiled train step.

The net-level NHWC plan (core/net.py) claims a transpose-free spatial
chain: activations enter channels-last once, every conv/pool/LRN/concat
runs natively, and layout converts back to canonical NCHW only at genuine
boundaries (FC flatten, blob export). This module makes that claim
compiler-verifiable without hardware — the analog of ``hlo_comm.py`` for
the layout plan: parse the program text, count the layout transposes, and
let ``bench.py`` / ``scripts/aot_tpu_check.py`` emit the number next to
``nhwc_speedup`` (the round-3 shim lost 1.9x precisely because the
per-op boundary transposes did NOT cancel; a count pins the regression).

Two program levels are parsed by the same entry points:

- **StableHLO** (``jit(f).lower(...).as_text()``): the compiler's INPUT —
  exactly the transposes OUR program asks for, on any backend. This is
  the tier-1 CPU assertion level.
- **Optimized HLO** (``...compile().as_text()``): what the backend kept.
  On the TPU compiler (AOT for an abstract v5e via
  ``jax.experimental.topologies`` — no hardware needed) this is the
  acceptance-grade count; the CPU backend is NOT meaningful here (its
  conv canonicalization materializes its own transposes for every conv
  gradient, ~77 for NCHW AlexNet, independent of our layout plan).

What counts as a LAYOUT transpose: a rank-4 transpose whose permutation
reorders non-degenerate (size > 1) dims. Rank-5+ transposes are excluded —
they are grouped-conv weight-gradient internals jax emits under either
layout — as are degenerate permutations (e.g. (N,1,1,C) -> (N,C,1,1)),
which every backend folds to a bitcast.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional

# optimized HLO:  %t.1 = f32[4,6,6,256]{3,2,1,0} transpose(%p), dimensions={0,3,1,2}
_HLO_RE = re.compile(
    r"= [a-z0-9]+\[([\d,]*)\](?:\{[\d,]*\})? transpose\(")
_HLO_DIMS_RE = re.compile(r"dimensions=\{([\d,]+)\}")
# StableHLO:  %1 = stablehlo.transpose %0, dims = [0, 3, 1, 2] :
#             (tensor<4x6x6x256xf32>) -> tensor<4x256x6x6xf32>
_SHLO_RE = re.compile(
    r"stablehlo\.transpose .*?dims = \[([\d, ]+)\].*?-> tensor<([^>]+)>")


@dataclass
class TransposeOp:
    out_shape: tuple
    perm: tuple

    @property
    def rank(self) -> int:
        return len(self.perm)

    @property
    def nontrivial(self) -> bool:
        """Reorders dims that actually have extent (> 1)?"""
        # operand dim d has size out_shape[i] where perm[i] == d
        op_size = {d: self.out_shape[i] for i, d in enumerate(self.perm)}
        nondeg = [d for d in self.perm if op_size.get(d, 1) > 1]
        return nondeg != sorted(nondeg)

    @property
    def is_layout(self) -> bool:
        return self.rank == 4 and self.nontrivial


def parse_transposes(text: str) -> List[TransposeOp]:
    """Every transpose op in an optimized-HLO or StableHLO module text."""
    out: List[TransposeOp] = []
    for line in text.splitlines():
        m = _HLO_RE.search(line)
        if m is not None:
            dims = tuple(int(x) for x in m.group(1).split(",") if x)
            d = _HLO_DIMS_RE.search(line)
            perm = (tuple(int(x) for x in d.group(1).split(","))
                    if d else tuple(range(len(dims))))
            out.append(TransposeOp(out_shape=dims, perm=perm))
            continue
        s = _SHLO_RE.search(line)
        if s is not None:
            perm = tuple(int(x) for x in s.group(1).replace(" ", "").split(","))
            shape = tuple(int(x) for x in s.group(2).split("x")[:-1])
            out.append(TransposeOp(out_shape=shape, perm=perm))
    return out


def count_layout_transposes(text: str) -> int:
    """Rank-4, non-degenerate transposes — the activation layout changes."""
    return sum(1 for t in parse_transposes(text) if t.is_layout)


def layout_report(text: str) -> Dict:
    """The evidence row: total / layout / per-shape detail."""
    ops = parse_transposes(text)
    layout_ops = [t for t in ops if t.is_layout]
    return {
        "transposes_total": len(ops),
        "layout_transposes": len(layout_ops),
        "layout_transpose_shapes": [
            {"shape": list(t.out_shape), "perm": list(t.perm)}
            for t in layout_ops],
    }


def build_plain_step(net, sp, input_layout: Optional[str] = None):
    """A mesh-free optimizer step (grad + solver update) over ``net`` —
    jit-compilable on any backend including an abstract AOT topology,
    with none of the shard_map machinery that would distract the count.
    Returns ``step(params, solver_state, batch, rng)``."""
    import jax

    from ..parallel.trainer import param_mults
    from ..solvers.updates import make_update_fn

    if input_layout is None:
        input_layout = net.conv_layout
    update_fn = make_update_fn(sp, param_mults(net))

    def step(params, state, batch, rng):
        def loss_fn(p):
            return net.apply(p, batch, train=True, rng=rng,
                             input_layout=input_layout).loss

        grads = jax.grad(loss_fn)(params)
        return update_fn(params, grads, state)

    return step


def step_avals(net, per_dev_batch: int, image: int,
               input_layout: Optional[str] = None, sharding=None):
    """(params, state, batch, rng) abstract values for ``build_plain_step``
    — enough to ``jit(...).lower(...)`` without materializing anything.
    ``sharding`` (e.g. a NamedSharding over an abstract v5e mesh) tags
    every aval for AOT compilation against a TPU topology."""
    import jax
    import jax.numpy as jnp

    from ..solvers.updates import init_state

    if input_layout is None:
        input_layout = net.conv_layout

    def aval(shape, dtype=jnp.float32):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

    pshape = jax.eval_shape(net.init, jax.ShapeDtypeStruct((2,), jnp.uint32))
    params = jax.tree_util.tree_map(lambda x: aval(x.shape), pshape)
    state = jax.tree_util.tree_map(
        lambda x: aval(x.shape, x.dtype),
        jax.eval_shape(lambda: init_state(params)))
    data = ((per_dev_batch, image, image, 3) if input_layout == "NHWC"
            else (per_dev_batch, 3, image, image))
    batch = {"data": aval(data), "label": aval((per_dev_batch,), jnp.int32)}
    rng = aval((2,), jnp.uint32)
    return params, state, batch, rng


def net_transpose_report(net, sp=None, per_dev_batch: int = 4,
                         image: int = 227, optimized: bool = False,
                         sharding=None) -> Dict:
    """Lower (and optionally backend-compile) one full optimizer step of
    ``net`` and report its layout-transpose counts. With ``sharding`` from
    an abstract TPU topology and ``optimized=True`` this is the
    no-hardware v5e acceptance check; without it, the StableHLO-level
    count on the local backend (the tier-1 test)."""
    import jax

    from ..proto.messages import SolverParameter

    sp = sp or SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    step = build_plain_step(net, sp)
    avals = step_avals(net, per_dev_batch, image, sharding=sharding)
    lowered = jax.jit(step).lower(*avals)
    text = lowered.compile().as_text() if optimized else lowered.as_text()
    rep = layout_report(text)
    rep["level"] = "optimized_hlo" if optimized else "stablehlo"
    rep["conv_layout"] = net.conv_layout
    return rep
