"""Checkpoint/resume for the LM model family (transformer + MoE pytrees).

The CNN engine snapshots through the wire-compatible `.caffemodel` path
(`runtime/checkpoint.py`, the analog of the reference's Snapshot/Restore,
solver.cpp:654-667). The LM family's parameters are plain pytrees that may
live in a parallelism-specific layout (tp head-major splits, pp stacked
layers, or both for 3-D). Snapshots here are always written in the
CANONICAL layout (per-block dicts, single-device shapes) so a checkpoint
taken under any parallelism mode resumes under any other — the LM analog of
the CNN path's cross-mode `coerce_state` (SSP<->sync, flat<->two-tier).

Atomicity follows the same tmp+rename rule as the engine snapshots: with
replicated (or canonically gathered) state every rank writes identical
bytes, so the last rename wins with valid content."""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..solvers.updates import SolverState
from .checkpoint import _flatten, _unflatten


def _canonicalize(tree: Dict, cfg, layout: Sequence[str]) -> Dict:
    """Undo layout transforms in reverse application order: a tree built as
    ``to_pp_layout(to_tp_layout(plain))`` has layout ("tp", "pp")."""
    from ..models.transformer import from_pp_layout, from_tp_layout
    undo = {"tp": from_tp_layout, "pp": from_pp_layout}
    for name in reversed(tuple(layout)):
        tree = undo[name](tree, cfg)
    return tree


def _apply_layout(tree: Dict, cfg, layout: Sequence[str]) -> Dict:
    from ..models.transformer import to_pp_layout, to_tp_layout
    redo = {"tp": to_tp_layout, "pp": to_pp_layout}
    for name in tuple(layout):
        tree = redo[name](tree, cfg)
    return tree


def save_lm(prefix: str, params: Dict, state: SolverState, cfg, *,
            layout: Sequence[str] = ()) -> str:
    """Write ``<prefix>_iter_N.lmstate.npz`` in canonical layout.

    ``layout`` names the transforms the live pytrees carry, in application
    order — () for sp/ep runs (params are canonical already), ("tp",) /
    ("pp",) for 2-D tp/pp, ("tp", "pp") for the 3-D recipe. The momentum
    history mirrors the param tree, so the same undo applies."""
    params = jax.device_get(_canonicalize(params, cfg, layout))
    history = jax.device_get(_canonicalize(state.history, cfg, layout))
    it = int(state.it)
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    path = f"{prefix}_iter_{it}.lmstate.npz"
    arrays = {"iter": np.asarray(it)}
    arrays.update({f"params/{k}": v for k, v in _flatten(params).items()})
    arrays.update({f"history/{k}": v for k, v in _flatten(history).items()})
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
    os.replace(tmp, path)
    return path


def restore_lm(path: str, cfg, *,
               layout: Sequence[str] = ()) -> Tuple[Dict, SolverState]:
    """Rebuild (params, SolverState) from a canonical snapshot, re-applying
    ``layout`` for the resuming topology (which need not match the saving
    one)."""
    groups: Dict[str, Dict[str, np.ndarray]] = {"params": {}, "history": {}}
    with np.load(path) as z:
        it = int(z["iter"])
        for key in z.files:
            head, _, rest = key.partition("/")
            if head in groups:
                groups[head][rest] = z[key]
    params = _apply_layout(_unflatten(groups["params"]), cfg, layout)
    history = _apply_layout(_unflatten(groups["history"]), cfg, layout)
    import jax.numpy as jnp
    state = SolverState(it=jnp.asarray(it, jnp.int32), history=history)
    return params, state


def latest_lm_snapshot(prefix: str) -> Optional[str]:
    from .checkpoint import latest_snapshot
    return latest_snapshot(prefix, suffix=".lmstate.npz")
