"""Shared retry policy: exponential backoff with full jitter.

Every transient-failure loop in the runtime (async-SSP client connect and
reconnect, cluster rendezvous) routes through this one helper so the policy
— capped exponential backoff, full jitter (sleep ~ U(0, min(cap, base*2^k)),
the AWS-architecture-blog rule that avoids reconnect thundering herds after
a parameter-service restart) — lives in exactly one place. The previous
client connect loop was a fixed 50 ms poll against a wall-clock deadline;
under a mass reconnect (service restart with N workers) that synchronizes
every worker's retry into the same 50 ms slots.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type, TypeVar

__all__ = ["retry_with_backoff"]

T = TypeVar("T")


def retry_with_backoff(
    fn: Callable[[], T],
    *,
    deadline: float,
    base: float = 0.05,
    cap: float = 2.0,
    jitter: bool = True,
    rng: Optional[random.Random] = None,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    should_stop: Optional[Callable[[], bool]] = None,
) -> T:
    """Call ``fn()`` until it returns, the ``deadline`` (seconds from now)
    passes, or ``should_stop()`` goes true.

    Sleep before attempt k+1 is ``U(0, min(cap, base * 2**k))`` (full
    jitter); with ``jitter=False`` it is the deterministic envelope
    ``min(cap, base * 2**k)``. Exceptions outside ``retry_on`` propagate
    immediately; on deadline exhaustion the LAST retryable exception is
    re-raised (never swallowed). ``rng`` makes the jitter stream
    deterministic for tests (e.g. ``random.Random(worker_id)``)."""
    rng = rng or random.Random()
    t_end = time.monotonic() + deadline
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on:
            now = time.monotonic()
            if now >= t_end or (should_stop is not None and should_stop()):
                raise
            envelope = min(cap, base * (2.0 ** attempt))
            delay = rng.uniform(0.0, envelope) if jitter else envelope
            time.sleep(min(delay, max(0.0, t_end - now)))
            attempt += 1
