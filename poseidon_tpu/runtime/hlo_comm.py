"""Measured comm accounting: collectives extracted from the compiled step.

The static table (comm_stats.py) predicts what each layer's strategy should
move per step. This module closes the loop by reading what XLA *actually
emitted*: the optimized HLO of the compiled train step, with every
all-reduce / all-gather / reduce-scatter / collective-permute, its payload
shape, dtype (so a bf16 wire is visible), and replica groups (so the
ici/dcn tier split is visible). The analog of the reference's runtime stats
(bg oplog bytes serialized, server push bytes — stats.hpp) for a compiled
SPMD program, where the data plane is fixed at compile time.

Usage:
    compiled = ts.lowerable.lower(params, state, batch, rng).compile()
    colls = parse_collectives(compiled.as_text())
    summary = measured_comm_summary(colls)
    # -> totals comparable against comm_stats.comm_summary()
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

_DTYPE_BYTES = {
    "f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
    "s64": 8, "s32": 4, "s16": 2, "s8": 1,
    "u64": 8, "u32": 4, "u16": 2, "u8": 1, "pred": 1,
    "c64": 8, "c128": 16,
}

# e.g.:  %all-reduce.12 = f32[500,300]{1,0} all-reduce(...), replica_groups={{0,1},{2,3}}
# XLA's combiner may merge several small collectives into one tuple-shaped
# op: %ar = (f32[500,300]{1,0}, f32[500]{0}) all-reduce(...)
_OP_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(?:-start)?\(")
_SHAPE_RE = re.compile(r"\b(\w+)\[([\d,]*)\](?:\{[\d,]*\})?")
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
_IOTA_GROUPS_RE = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
# collective-permute carries source_target_pairs instead of replica_groups
# (e.g. source_target_pairs={{0,1},{1,2},...}); without parsing it the op
# fell to group_size=1 and the summary FILTERED the whole ring out —
# caught by the round-5 long-context capture reporting 0 collectives for
# a program with 48 ring permutes.
_PAIRS_RE = re.compile(r"source_target_pairs=\{(\{[^=]*?\})\}")


@dataclass
class Collective:
    kind: str            # all-reduce | all-gather | ...
    dtype: str           # dtype of the (first) payload
    shape: tuple         # shape of the (first) payload
    payload_bytes: int   # logical FULL payload (see _payload in the parser)
    group_size: int      # participants per replica group (1 = trivial)
    n_groups: int

    def wire_bytes_per_device(self) -> float:
        """Bytes each participant moves, ring-algorithm convention (the same
        convention comm_stats.py bills). ``payload_bytes`` is normalized by
        the parser to the FULL logical payload per kind: the reduced tensor
        (all-reduce), the gathered result (all-gather), the full input
        (reduce-scatter / all-to-all), the sent shard (permute)."""
        n = self.group_size
        if n <= 1:
            return 0.0
        if self.kind == "all-reduce":
            return 2.0 * (n - 1) / n * self.payload_bytes
        if self.kind in ("all-gather", "reduce-scatter", "all-to-all"):
            return (n - 1) / n * self.payload_bytes
        return float(self.payload_bytes)  # collective-permute


def _payload(kind: str, is_start: bool, tuple_bytes: float, n: int) -> float:
    """Normalize a parsed LHS byte sum to the FULL logical payload.

    Sync ops' LHS is the result alone (possibly a combined tuple of
    results); async ``-start`` ops carry (operands..., results...) — the
    operand buffers must not be double-counted. reduce-scatter's sync LHS
    is the per-device SHARD, so the full input is shard x n."""
    if n <= 1:
        return tuple_bytes
    if kind == "all-reduce":
        # operand == result, so -start tuples hold each payload twice
        return tuple_bytes / 2 if is_start else tuple_bytes
    if kind == "all-gather":
        # start tuple = operand (1/n of result) + result
        return tuple_bytes * n / (n + 1) if is_start else tuple_bytes
    if kind == "reduce-scatter":
        # start tuple = full operand + shard result; sync LHS = shard only
        return tuple_bytes * n / (n + 1) if is_start else tuple_bytes * n
    # collective-permute-start: (in, out, [u32 contexts]); all-to-all-start:
    # (in, out). in == out, contexts are scalar-sized noise.
    return tuple_bytes / 2 if is_start else tuple_bytes


def parse_collectives(hlo_text: str) -> List[Collective]:
    """All collectives in an (optimized) HLO module text, with payloads.

    Start/done pairs are collapsed (only ``-start`` ops carry the payload;
    plain ops appear in unoptimized HLO). Scalar payloads (e.g. the psum of
    ones behind a mean) are kept — filter by payload_bytes if unwanted."""
    out: List[Collective] = []
    for line in hlo_text.splitlines():
        if "-done" in line or " = " not in line:
            continue
        m = _OP_RE.search(line)
        if m is None:
            continue
        kind = m.group(1)
        is_start = line[m.start():m.end()].rstrip("(").endswith("-start")
        # sum every dtype[dims] between "= " and the op keyword (a single
        # shape, or the elements of a combined/async tuple); _payload then
        # normalizes to the full logical payload per kind
        lhs = line[line.index(" = ") + 3:m.start()]
        payload = 0
        first: Optional[tuple] = None
        for dm in _SHAPE_RE.finditer(lhs):
            dtype, dims = dm.group(1), dm.group(2)
            if dtype not in _DTYPE_BYTES:
                continue
            shape = tuple(int(d) for d in dims.split(",")) if dims else ()
            payload += (int(np.prod(shape)) if shape else 1) * \
                _DTYPE_BYTES[dtype]
            if first is None:
                first = (dtype, shape)
        if first is None:
            continue
        g = _GROUPS_RE.search(line)
        gi = _IOTA_GROUPS_RE.search(line)
        gp = _PAIRS_RE.search(line)
        if g:
            groups = [grp for grp in g.group(1).split("},{")]
            group_size = len(groups[0].strip("{}").split(","))
            n_groups = len(groups)
        elif gi:  # iota form: replica_groups=[n_groups,group_size]<=[N]
            n_groups, group_size = int(gi.group(1)), int(gi.group(2))
        elif gp:  # permute ring: participants = distinct devices in pairs
            devs = {d for pair in gp.group(1).split("},{")
                    for d in pair.strip("{}").split(",")}
            group_size, n_groups = max(len(devs), 2), 1
        else:
            group_size, n_groups = 1, 1
        out.append(Collective(kind=kind, dtype=first[0], shape=first[1],
                              payload_bytes=int(_payload(
                                  kind, is_start, payload, group_size)),
                              group_size=group_size,
                              n_groups=n_groups))
    return out


def count_gradient_all_reduces(hlo_text: str,
                               min_payload_bytes: int = 1024) -> int:
    """Gradient all-reduces in a compiled step: all-reduce ops with a
    non-trivial replica group and a payload big enough to be a gradient
    (the metrics / mean-divisor psums are scalars and fall under the
    threshold). This is the flat-parameter-arena acceptance counter: the
    data-parallel step must carry <= ceil(total_grad_bytes /
    arena_bucket_mb) of these, vs one per leaf on the per-leaf path."""
    return sum(1 for c in parse_collectives(hlo_text)
               if c.kind == "all-reduce" and c.group_size > 1
               and c.payload_bytes >= min_payload_bytes)


# one stablehlo.all_reduce op, non-greedy to ITS result type: the reduction
# region between the op and its `-> tensor<...>` signature contains no `->`
_STABLEHLO_AR_RE = re.compile(
    r'"stablehlo\.all_reduce".*?\)\s*->\s*tensor<([0-9x]*)f32>', re.S)

# every collective kind the SPMD planner schedules, with its result type
# (all_reduce's region makes the result sit after the region's `->`; the
# others are plain one-line ops). bf16/f16 wires count too.
_STABLEHLO_COLL_RE = re.compile(
    r'"stablehlo\.(all_reduce|reduce_scatter|all_gather)"'
    r'.*?->\s*tensor<([0-9x]*)(f32|bf16|f16)>', re.S)


def collective_census_stablehlo(text: str,
                                min_elements: int = 256) -> Dict[str, int]:
    """Counts of all_reduce / reduce_scatter / all_gather ops in a LOWERED
    (pre-XLA) program whose payload is at least ``min_elements`` elements
    — the cheap, combiner-proof census the SPMD planner's
    ``collective_schedule`` is diffed against (analysis/contracts.py).
    Lowered counts are exact for the planned schedule: the arena's
    chained buckets cannot legally merge, and XLA only ever merges,
    never splits."""
    out = {"all_reduce": 0, "reduce_scatter": 0, "all_gather": 0}
    for m in _STABLEHLO_COLL_RE.finditer(text):
        dims = m.group(2).rstrip("x")
        elems = int(np.prod([int(d) for d in dims.split("x")])) \
            if dims else 1
        if elems >= min_elements:
            out[m.group(1)] += 1
    return out


def count_gradient_all_reduces_stablehlo(text: str,
                                         min_elements: int = 256) -> int:
    """Gradient all-reduces in a LOWERED (pre-XLA) program — the cheap
    counter for tests that cannot afford a multi-minute CPU compile of a
    big net. Counts ``stablehlo.all_reduce`` ops whose f32 payload is big
    enough to be a gradient (metrics / mean-divisor psums are scalars).
    An upper bound on the compiled count: XLA's combiner may merge
    all-reduces but never splits one — and the arena's chained bucket
    psums cannot legally merge at all (the chain would cycle), which
    ``count_gradient_all_reduces`` pins on the compiled text where the
    compile is affordable."""
    n = 0
    for m in _STABLEHLO_AR_RE.finditer(text):
        dims = m.group(1).rstrip("x")
        elems = int(np.prod([int(d) for d in dims.split("x")])) if dims else 1
        if elems >= min_elements:
            n += 1
    return n


def measured_comm_summary(colls: List[Collective],
                          min_payload_bytes: int = 16) -> Dict:
    """Totals comparable against comm_stats.comm_summary(): per-device wire
    bytes by collective kind and dtype, scalars filtered out."""
    total = 0.0
    by_kind: Dict[str, float] = {}
    by_dtype: Dict[str, float] = {}
    n_colls = 0
    for c in colls:
        if c.payload_bytes < min_payload_bytes or c.group_size <= 1:
            continue
        w = c.wire_bytes_per_device()
        total += w
        by_kind[c.kind] = by_kind.get(c.kind, 0.0) + w
        by_dtype[c.dtype] = by_dtype.get(c.dtype, 0.0) + w
        n_colls += 1
    return {
        "measured_bytes_per_step": int(total),
        "n_collectives": n_colls,
        "by_kind": {k: int(v) for k, v in sorted(by_kind.items())},
        "by_dtype": {k: int(v) for k, v in sorted(by_dtype.items())},
    }


def compare_static_vs_measured(static_summary: Dict,
                               measured: Dict) -> Dict:
    """The validation row for docs/performance-guide.md: static prediction
    vs compiled-program measurement and their ratio."""
    s = float(static_summary.get("total_bytes_per_step", 0))
    m = float(measured.get("measured_bytes_per_step", 0))
    return {
        "static_bytes_per_step": int(s),
        "measured_bytes_per_step": int(m),
        "measured_over_static": round(m / s, 4) if s else None,
    }
