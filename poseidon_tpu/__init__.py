"""poseidon_tpu — a TPU-native distributed CNN training framework.

Brand-new implementation of the capabilities of petuum/poseidon (PMLS-Caffe):
prototxt-defined CNN training, Caffe-exact solvers, distributed data
parallelism with DWBP-style communication/compute overlap, sufficient-factor
broadcasting for FC gradients, and bounded-staleness synchronization — built
on JAX/XLA/pjit for TPU meshes. See ARCHITECTURE.md for the design map.
"""

__version__ = "0.1.0"

from . import config  # noqa: F401
