"""Pure-Python snappy codec (block format) — no C dependency in this image.

LevelDB compresses SSTable blocks with snappy; reading Caffe's default-backend
databases therefore needs a decompressor. Format (public spec): a varint32
uncompressed length, then tagged elements — literals (tag & 3 == 0) and
back-references (copy-1/2/4 byte offsets). The compressor emits the trivial
all-literals encoding (valid snappy, no compression), enough for writing
databases other LevelDB readers accept.
"""

from __future__ import annotations


from .varint import VarintError, read_varint, write_varint


class SnappyError(ValueError):
    pass


def _read_varint32(buf: bytes, pos: int):
    try:
        return read_varint(buf, pos, max_shift=32)
    except VarintError as e:
        raise SnappyError(str(e)) from e


def uncompress(buf: bytes) -> bytes:
    # fast path: the C++ decoder in the native data plane, when built
    try:
        from .native import snappy_uncompress
        out = snappy_uncompress(buf)
        if out is not None:
            return out
    except ValueError as e:
        raise SnappyError(str(e)) from e
    except Exception:
        pass  # native layer unavailable/broken: pure-Python path below
    return _uncompress_py(buf)


def _uncompress_py(buf: bytes) -> bytes:
    expected, pos = _read_varint32(buf, 0)
    out = bytearray()
    n = len(buf)
    while pos < n:
        tag = buf[pos]
        pos += 1
        elem_type = tag & 3
        if elem_type == 0:  # literal
            length = tag >> 2
            if length >= 60:
                extra = length - 59
                if pos + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(buf[pos:pos + extra], "little")
                pos += extra
            length += 1
            if pos + length > n:
                raise SnappyError("truncated literal")
            out += buf[pos:pos + length]
            pos += length
            continue
        if elem_type == 1:  # copy, 1-byte offset
            length = 4 + ((tag >> 2) & 0x7)
            if pos >= n:
                raise SnappyError("truncated copy-1")
            offset = ((tag >> 5) << 8) | buf[pos]
            pos += 1
        elif elem_type == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise SnappyError("truncated copy-2")
            offset = int.from_bytes(buf[pos:pos + 2], "little")
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise SnappyError("truncated copy-4")
            offset = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        if offset == 0 or offset > len(out):
            raise SnappyError("bad copy offset")
        start = len(out) - offset
        if offset >= length:
            # disjoint: one slice copy
            out += out[start:start + length]
        else:
            # overlapping copy: the source region repeats; double it up
            # (chunk + chunk, not +=: in-place extend from itself raises
            # BufferError on bytearray)
            chunk = out[start:]
            while len(chunk) < length:
                chunk = chunk + chunk
            out += chunk[:length]
    if len(out) != expected:
        raise SnappyError(f"length mismatch: {len(out)} != {expected}")
    return bytes(out)


def compress(data: bytes) -> bytes:
    """All-literals encoding: valid snappy output, no actual compression."""
    out = bytearray()
    write_varint(out, len(data))
    pos = 0
    while pos < len(data):
        chunk = min(len(data) - pos, 1 << 24)
        length = chunk - 1
        if length < 60:
            out.append(length << 2)
        elif length < (1 << 8):
            out.append(60 << 2)
            out += length.to_bytes(1, "little")
        elif length < (1 << 16):
            out.append(61 << 2)
            out += length.to_bytes(2, "little")
        else:
            out.append(62 << 2)
            out += length.to_bytes(3, "little")
        out += data[pos:pos + chunk]
        pos += chunk
    return bytes(out)
