"""ctypes binding for the native data plane (native/poseidon_dataplane.cc).

Builds the shared library on first use (g++, no external deps) and exposes
``NativeLMDBBatcher``: indexed batch assembly (LMDB read + Datum decode +
crop/mirror/mean/scale) running multithreaded in C++ with the GIL released —
the reference's C++ data-layer role. Falls back cleanly when no compiler is
available (``available()`` returns False and callers use the Python path).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "poseidon_dataplane.cc")
_LIB = os.path.join(_REPO_ROOT, "native", "build",
                    "libposeidon_dataplane.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


class _TransformSpec(ctypes.Structure):
    _fields_ = [
        ("crop_size", ctypes.c_int32),
        ("mirror", ctypes.c_int32),
        ("train", ctypes.c_int32),
        ("scale", ctypes.c_float),
        ("mean_mode", ctypes.c_int32),
        ("mean", ctypes.POINTER(ctypes.c_float)),
    ]


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_LIB):
            if not os.path.exists(_SRC):
                _build_failed = True
                return None
            try:
                os.makedirs(os.path.dirname(_LIB), exist_ok=True)
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-fPIC", "-pthread", "-Wall",
                     "-shared", "-o", _LIB, _SRC],
                    check=True, capture_output=True)
            except (subprocess.CalledProcessError, FileNotFoundError):
                _build_failed = True
                return None
        lib = ctypes.CDLL(_LIB)
        lib.pdp_open.restype = ctypes.c_void_p
        lib.pdp_open.argtypes = [ctypes.c_char_p]
        lib.pdp_error.restype = ctypes.c_char_p
        lib.pdp_error.argtypes = [ctypes.c_void_p]
        lib.pdp_count.restype = ctypes.c_int64
        lib.pdp_count.argtypes = [ctypes.c_void_p]
        lib.pdp_shape.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_int32)] * 3
        lib.pdp_batch.restype = ctypes.c_int32
        lib.pdp_batch.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
            ctypes.POINTER(_TransformSpec), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32,
        ]
        lib.pdp_close.argtypes = [ctypes.c_void_p]
        if hasattr(lib, "pdp_batch_u8"):  # stale prebuilt .so tolerance
            lib.pdp_batch_u8.restype = ctypes.c_int32
            lib.pdp_batch_u8.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int32,
            ]
        # newer symbol: a stale prebuilt .so may predate it — the batcher
        # must keep working, only the snappy fast path degrades
        if hasattr(lib, "pdp_snappy_uncompress"):
            lib.pdp_snappy_uncompress.restype = ctypes.c_int64
            lib.pdp_snappy_uncompress.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
            ]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


# A corrupt header must not force a huge zero-filled allocation before the
# body is ever validated; LevelDB blocks are ~4-64 KiB, so this is generous.
_SNAPPY_MAX_OUT = 256 << 20


def snappy_uncompress(buf: bytes) -> Optional[bytes]:
    """Native snappy decode; None when the library is unavailable, raises
    on malformed input (same contract as the pure-Python codec)."""
    lib = _load()
    if lib is None or not hasattr(lib, "pdp_snappy_uncompress"):
        return None
    need = lib.pdp_snappy_uncompress(buf, len(buf), None, 0)
    if need < 0:
        raise ValueError("native snappy: malformed header")
    if need > _SNAPPY_MAX_OUT:
        raise ValueError(
            f"native snappy: declared size {need} exceeds the "
            f"{_SNAPPY_MAX_OUT}-byte block cap (corrupt header?)")
    out = (ctypes.c_uint8 * need)()
    got = lib.pdp_snappy_uncompress(buf, len(buf), out, need)
    if got != need:
        raise ValueError(f"native snappy: malformed stream (rc={got})")
    return bytes(out)


class NativeLMDBBatcher:
    def __init__(self, path: str, *, crop_size: int = 0, mirror: bool = False,
                 train: bool = True, scale: float = 1.0,
                 mean: Optional[np.ndarray] = None,
                 mean_values: Optional[np.ndarray] = None,
                 n_threads: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native data plane unavailable (no compiler?)")
        self._lib = lib
        self._h = lib.pdp_open(path.encode())
        err = lib.pdp_error(self._h)
        if err:
            msg = err.decode()
            lib.pdp_close(self._h)
            self._h = None
            raise IOError(f"{path}: {msg}")
        c = ctypes.c_int32()
        h = ctypes.c_int32()
        w = ctypes.c_int32()
        lib.pdp_shape(self._h, ctypes.byref(c), ctypes.byref(h),
                      ctypes.byref(w))
        self.record_shape = (c.value, h.value, w.value)
        self.n = int(lib.pdp_count(self._h))
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)

        if crop_size and (crop_size > self.record_shape[1]
                          or crop_size > self.record_shape[2]):
            self._lib.pdp_close(self._h)
            self._h = None
            raise ValueError(
                f"crop_size {crop_size} exceeds record "
                f"{self.record_shape[1]}x{self.record_shape[2]}")
        mean_mode = 0
        self._mean_buf = None
        if mean is not None:
            m = np.ascontiguousarray(np.asarray(mean, np.float32).reshape(-1))
            if m.size != int(np.prod(self.record_shape)):
                raise ValueError("mean array size mismatch")
            self._mean_buf = m
            mean_mode = 2
        elif mean_values is not None and len(mean_values):
            m = np.asarray(mean_values, np.float32)
            if m.size == 1:
                m = np.repeat(m, self.record_shape[0])
            if m.size != self.record_shape[0]:
                raise ValueError("mean_values arity mismatch")
            self._mean_buf = np.ascontiguousarray(m)
            mean_mode = 1
        mean_ptr = self._mean_buf.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)) if self._mean_buf is not None \
            else ctypes.POINTER(ctypes.c_float)()
        self._spec = _TransformSpec(
            crop_size=crop_size, mirror=int(mirror), train=int(train),
            scale=scale, mean_mode=mean_mode, mean=mean_ptr)
        ch, hh, ww = self.record_shape
        self.out_shape = (ch, crop_size or hh, crop_size or ww)

    def __len__(self) -> int:
        return self.n

    def supports_u8(self) -> bool:
        return hasattr(self._lib, "pdp_batch_u8")

    def batch_u8(self, indices: np.ndarray,
                 seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        """Decode + crop + mirror to uint8 — mean/scale happen on device
        (see pipeline.device_transform). Same crop/mirror RNG stream as
        ``batch``, so the two paths see identical pixels. Raises IOError
        on float_data-backed records (rc=-4): callers fall back to f32."""
        idx = np.ascontiguousarray(indices, np.int64)
        n = len(idx)
        data = np.empty((n,) + self.out_shape, np.uint8)
        labels = np.empty((n,), np.int32)
        rc = self._lib.pdp_batch_u8(
            self._h, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            self._spec.crop_size, self._spec.mirror, self._spec.train,
            ctypes.c_uint64(seed),
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self.n_threads)
        if rc == -2:
            raise IndexError("batch index out of range")
        if rc == -3:
            raise ValueError("crop_size exceeds record dimensions")
        if rc == -4:
            raise IOError("float_data records cannot ship as uint8")
        if rc != 0:
            raise IOError(f"native batch failed: bad record (rc={rc})")
        return data, labels

    def batch(self, indices: np.ndarray,
              seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
        idx = np.ascontiguousarray(indices, np.int64)
        n = len(idx)
        data = np.empty((n,) + self.out_shape, np.float32)
        labels = np.empty((n,), np.int32)
        rc = self._lib.pdp_batch(
            self._h, idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n,
            ctypes.byref(self._spec), ctypes.c_uint64(seed),
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            self.n_threads)
        if rc == -2:
            raise IndexError("batch index out of range")
        if rc == -3:
            raise ValueError("crop_size exceeds record dimensions")
        if rc != 0:
            raise IOError(f"native batch failed: bad record (rc={rc})")
        return data, labels

    def close(self):
        if self._h is not None:
            self._lib.pdp_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
