from .pipeline import BatchPipeline, build_source, layer_batch_size  # noqa: F401
from .sources import (  # noqa: F401
    HDF5Source, ImageListSource, LMDBSource, MemorySource, Source,
    SyntheticSource,
)
from .transformer import DataTransformer  # noqa: F401
from .workload import Shard, contiguous_range, shard_indices  # noqa: F401
