"""Read-only, dependency-free LMDB reader (mmap + B+tree walk).

The reference ingests training data from LMDB/LevelDB databases of serialized
``Datum`` records (``src/caffe/layers/data_layer.cpp``, ``caffe.proto:444``).
This image has no ``lmdb`` C binding, so this module implements the LMDB file
format directly: meta-page selection by transaction id, B+tree traversal of
the main DB, overflow-page reassembly. Enough for the data-loading access
pattern (sequential scan + indexed lookup); no write support.

Format reference: LMDB is public domain (OpenLDAP); the on-disk layout is
page-size-aligned pages with a 16-byte header, meta pages 0 and 1, and
branch/leaf nodes carrying 48-bit page numbers / 32-bit data sizes.
"""

from __future__ import annotations

import mmap
import os
import struct
from typing import Iterator, List, Optional, Tuple

MDB_MAGIC = 0xBEEFC0DE

P_BRANCH = 0x01
P_LEAF = 0x02
P_OVERFLOW = 0x04
P_META = 0x08
P_LEAF2 = 0x20

F_BIGDATA = 0x01


class LMDBError(IOError):
    pass


class LMDBReader:
    def __init__(self, path: str):
        if os.path.isdir(path):
            path = os.path.join(path, "data.mdb")
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        self._parse_meta()
        self._index: Optional[List[Tuple[int, int]]] = None  # (page, node idx)

    # ------------------------------------------------------------------ #
    def _parse_meta(self):
        # Try both supported page sizes to locate meta page 1.
        best = None
        for psize in (4096, 8192, 16384, 32768):
            try:
                m0 = self._read_meta(0, psize)
                m1 = self._read_meta(1, psize)
            except (LMDBError, struct.error):
                continue
            meta = m0 if m0["txnid"] >= m1["txnid"] else m1
            best = (psize, meta)
            break
        if best is None:
            raise LMDBError("not an LMDB file (no valid meta page)")
        self.page_size, meta = best
        self.root = meta["main_root"]
        self.entries = meta["main_entries"]

    def _read_meta(self, pageno: int, psize: int) -> dict:
        off = pageno * psize
        buf = self._mm[off:off + psize]
        if len(buf) < 112:
            raise LMDBError("truncated meta page")
        # MDB_page header: pgno(8) pad(2) flags(2) lower(2) upper(2)
        flags = struct.unpack_from("<H", buf, 10)[0]
        if not flags & P_META:
            raise LMDBError("not a meta page")
        # MDB_meta at offset 16: magic(4) version(4) address(8) mapsize(8)
        magic, version = struct.unpack_from("<II", buf, 16)
        if magic != MDB_MAGIC:
            raise LMDBError("bad magic")
        # mm_dbs[2]: each MDB_db is 48 bytes:
        # pad(4) flags(2) depth(2) branch(8) leaf(8) overflow(8) entries(8) root(8)
        db_off = 16 + 4 + 4 + 8 + 8  # after magic/version/address/mapsize
        free_db = struct.unpack_from("<IHHQQQQq", buf, db_off)
        main_db = struct.unpack_from("<IHHQQQQq", buf, db_off + 48)
        last_pg, txnid = struct.unpack_from("<QQ", buf, db_off + 96)
        return {
            "txnid": txnid,
            "main_entries": main_db[6],
            "main_root": main_db[7],
        }

    # ------------------------------------------------------------------ #
    def _page(self, pgno: int) -> bytes:
        off = pgno * self.page_size
        return self._mm[off:off + self.page_size]

    def _page_header(self, buf: bytes) -> Tuple[int, int, int]:
        flags, lower, upper = struct.unpack_from("<HHH", buf, 10)
        return flags, lower, upper

    def _node_offsets(self, buf: bytes) -> List[int]:
        _, lower, _ = self._page_header(buf)
        n = (lower - 16) // 2
        return list(struct.unpack_from(f"<{n}H", buf, 16)) if n else []

    def _leaf_node(self, pgno: int, idx: int) -> Tuple[bytes, bytes]:
        """Return (key, value) for node idx of leaf page pgno."""
        buf = self._page(pgno)
        offsets = self._node_offsets(buf)
        off = offsets[idx]
        lo, hi, flags, ksize = struct.unpack_from("<HHHH", buf, off)
        datasize = lo | (hi << 16)
        key = buf[off + 8:off + 8 + ksize]
        if flags & F_BIGDATA:
            (ovpg,) = struct.unpack_from("<Q", buf, off + 8 + ksize)
            return key, self._read_overflow(ovpg, datasize)
        data_start = off + 8 + ksize
        return key, buf[data_start:data_start + datasize]

    def _read_overflow(self, pgno: int, size: int) -> bytes:
        start = pgno * self.page_size + 16
        return self._mm[start:start + size]

    # ------------------------------------------------------------------ #
    def _walk_leaves(self, pgno: int) -> Iterator[int]:
        """Yield leaf page numbers left-to-right."""
        buf = self._page(pgno)
        flags, _, _ = self._page_header(buf)
        if flags & P_LEAF:
            yield pgno
            return
        if not flags & P_BRANCH:
            raise LMDBError(f"unexpected page flags {flags:#x} at {pgno}")
        for off in self._node_offsets(buf):
            lo, hi, nflags, ksize = struct.unpack_from("<HHHH", buf, off)
            child = lo | (hi << 16) | (nflags << 32)  # 48-bit pgno
            yield from self._walk_leaves(child)

    def _build_index(self):
        if self._index is not None:
            return
        index: List[Tuple[int, int]] = []
        if self.root >= 0:
            for leaf in self._walk_leaves(self.root):
                buf = self._page(leaf)
                for i in range(len(self._node_offsets(buf))):
                    index.append((leaf, i))
        self._index = index

    # ------------------------------------------------------------------ #
    def __len__(self) -> int:
        return self.entries

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        self._build_index()
        for pgno, i in self._index:
            yield self._leaf_node(pgno, i)

    def value_at(self, i: int) -> bytes:
        self._build_index()
        pgno, idx = self._index[i]
        return self._leaf_node(pgno, idx)[1]

    def key_at(self, i: int) -> bytes:
        self._build_index()
        pgno, idx = self._index[i]
        return self._leaf_node(pgno, idx)[0]

    def close(self):
        self._mm.close()
        self._f.close()


# --------------------------------------------------------------------------- #
# Minimal LMDB *writer* for tool parity (convert_imageset / partition_data
# equivalents must emit databases Caffe itself could read). Writes a fresh
# single-txn database: meta pages + sequential leaf pages, no free list.
# --------------------------------------------------------------------------- #

class LMDBWriter:
    PAGE = 4096

    def __init__(self, path: str):
        os.makedirs(path, exist_ok=True)
        self.path = os.path.join(path, "data.mdb")
        self.items: List[Tuple[bytes, bytes]] = []

    def put(self, key: bytes, value: bytes):
        self.items.append((key, value))

    def close(self):
        items = sorted(self.items)
        pages: List[bytes] = []  # data pages, numbered from 2
        leaf_pages: List[Tuple[bytes, int]] = []  # (first key, pgno)

        def new_pgno() -> int:
            return 2 + len(pages)

        # Build leaves: pack as many nodes as fit per page.
        i = 0
        while i < len(items):
            nodes = []
            used = 16
            first_key = items[i][0]
            page_entries: List[Tuple[bytes, bytes, Optional[int]]] = []
            while i < len(items):
                key, value = items[i]
                big = 8 + len(key) + len(value) > self.PAGE - 16 - 2 or \
                    len(value) > self.PAGE // 2
                node_size = 8 + len(key) + (8 if big else len(value))
                node_size += node_size & 1
                if used + 2 + node_size > self.PAGE and page_entries:
                    break
                ovpg = None
                if big:
                    ovpg = new_pgno()
                    npages = (16 + len(value) + self.PAGE - 1) // self.PAGE
                    blob = struct.pack("<QHHHH", ovpg, 0, P_OVERFLOW, 0, 0)
                    blob += value
                    blob += b"\0" * (npages * self.PAGE - len(blob))
                    for p in range(npages):
                        pages.append(blob[p * self.PAGE:(p + 1) * self.PAGE])
                page_entries.append((key, value, ovpg))
                used += 2 + node_size
                i += 1
            pgno = new_pgno()
            pages.append(self._build_leaf(pgno, page_entries))
            leaf_pages.append((first_key, pgno))

        # Branch pages (single level is enough for tool-scale DBs; build
        # recursively otherwise).
        def build_branch(children: List[Tuple[bytes, int]]) -> int:
            if len(children) == 1:
                return children[0][1]
            level: List[Tuple[bytes, int]] = []
            j = 0
            while j < len(children):
                group = []
                used = 16
                first_key = children[j][0]
                while j < len(children):
                    key, child = children[j]
                    ksize = 0 if not group else len(key)
                    node_size = 8 + ksize
                    node_size += node_size & 1
                    if used + 2 + node_size > self.PAGE and group:
                        break
                    group.append((key, child))
                    used += 2 + node_size
                    j += 1
                pgno = new_pgno()
                pages.append(self._build_branch(pgno, group))
                level.append((first_key, pgno))
            return build_branch(level)

        root = build_branch(leaf_pages) if leaf_pages else -1

        meta = self._build_meta(root, len(items), last_pg=1 + len(pages))
        with open(self.path, "wb") as f:
            f.write(meta)
            for p in pages:
                f.write(p)

    def _build_leaf(self, pgno: int, entries) -> bytes:
        header_nodes: List[bytes] = []
        bodies: List[bytes] = []
        # lay out nodes from the top of the page downward
        offsets = []
        upper = self.PAGE
        for key, value, ovpg in entries:
            if ovpg is not None:
                node = struct.pack("<HHHH", len(value) & 0xFFFF,
                                   (len(value) >> 16) & 0xFFFF,
                                   F_BIGDATA, len(key))
                node += key + struct.pack("<Q", ovpg)
            else:
                node = struct.pack("<HHHH", len(value) & 0xFFFF,
                                   (len(value) >> 16) & 0xFFFF, 0, len(key))
                node += key + value
            if len(node) & 1:
                node += b"\0"
            upper -= len(node)
            offsets.append(upper)
            bodies.append(node)
        lower = 16 + 2 * len(entries)
        page = bytearray(self.PAGE)
        struct.pack_into("<QHHHH", page, 0, pgno, 0, P_LEAF, lower, upper)
        struct.pack_into(f"<{len(offsets)}H", page, 16, *offsets)
        for off, node in zip(offsets, bodies):
            page[off:off + len(node)] = node
        return bytes(page)

    def _build_branch(self, pgno: int, children) -> bytes:
        offsets = []
        bodies: List[bytes] = []
        upper = self.PAGE
        for idx, (key, child) in enumerate(children):
            k = b"" if idx == 0 else key
            node = struct.pack("<HHHH", child & 0xFFFF, (child >> 16) & 0xFFFF,
                               (child >> 32) & 0xFFFF, len(k))
            node += k
            if len(node) & 1:
                node += b"\0"
            upper -= len(node)
            offsets.append(upper)
            bodies.append(node)
        lower = 16 + 2 * len(children)
        page = bytearray(self.PAGE)
        struct.pack_into("<QHHHH", page, 0, pgno, 0, P_BRANCH, lower, upper)
        struct.pack_into(f"<{len(offsets)}H", page, 16, *offsets)
        for off, node in zip(offsets, bodies):
            page[off:off + len(node)] = node
        return bytes(page)

    def _build_meta(self, root: int, entries: int, last_pg: int) -> bytes:
        out = bytearray()
        for pageno, txnid in ((0, 0), (1, 1)):
            page = bytearray(self.PAGE)
            struct.pack_into("<QHHHH", page, 0, pageno, 0, P_META, 0, 0)
            struct.pack_into("<II", page, 16, MDB_MAGIC, 1)
            # address(8)=0, mapsize(8)
            struct.pack_into("<QQ", page, 24, 0, 1 << 30)
            db_off = 40
            # free DB: empty
            struct.pack_into("<IHHQQQQq", page, db_off, 0, 0, 0, 0, 0, 0, 0, -1)
            # main DB
            depth = 1 if root >= 0 else 0
            struct.pack_into("<IHHQQQQq", page, db_off + 48, 0, 0, depth,
                             0, 0, 0, entries, root)
            struct.pack_into("<QQ", page, db_off + 96, last_pg, txnid)
            out += page
        return bytes(out)
