"""Shared LEB128 varint helpers (LevelDB + snappy wire formats)."""

from __future__ import annotations

from typing import Tuple


class VarintError(ValueError):
    pass


def read_varint(buf: bytes, pos: int, max_shift: int = 70) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise VarintError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > max_shift:
            raise VarintError("varint too long")


def write_varint(out: bytearray, v: int) -> None:
    while True:
        bits = v & 0x7F
        v >>= 7
        if v:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return
