"""Batch pipeline: source -> transform -> background prefetch -> device.

The counterpart of ``BasePrefetchingDataLayer`` + ``InternalThread``
(``src/caffe/layers/base_data_layer.cpp:73-103``): a daemon thread keeps a
bounded queue of ready batches (transform applied, numpy, pinned layout) while
the TPU trains on the current one; ``__next__`` hands back host arrays the
trainer device_puts with the batch sharding.

``build_source`` maps a data-layer ``LayerParameter`` to a Source with the
reference's backend selection (data_layer.cpp, layer catalog §2.1) and the
``shared_file_system`` `_k` suffix sharding.
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from ..proto.messages import LayerParameter, TransformationParameter
from .sources import (HDF5Source, ImageListSource, LMDBSource, LevelDBSource,
                      MemorySource, Source)
from .transformer import DataTransformer
from .workload import Shard, shard_indices, sharded_source_path


def _effective_transform(lp: LayerParameter) -> TransformationParameter:
    """Merge the deprecated in-layer fields (scale/mean_file/crop/mirror on
    data_param etc.) into a TransformationParameter, preferring the modern
    transform_param when set (upgrade_proto.cpp behavior)."""
    tp = lp.transform_param
    legacy = None
    t = lp.canonical_type()
    if t == "DATA":
        legacy = lp.data_param
    elif t == "IMAGE_DATA":
        legacy = lp.image_data_param
    elif t == "WINDOW_DATA":
        legacy = lp.window_data_param
    if legacy is not None:
        merged = TransformationParameter(
            scale=tp.scale if tp.scale != 1.0 else legacy.scale,
            mirror=tp.mirror or legacy.mirror,
            crop_size=tp.crop_size or legacy.crop_size,
            mean_file=tp.mean_file or legacy.mean_file,
            mean_value=list(tp.mean_value),
        )
        return merged
    return tp


def build_source(lp: LayerParameter, shard: Shard,
                 memory_data: Optional[Dict[str, np.ndarray]] = None) -> Source:
    t = lp.canonical_type()
    if t == "DATA":
        dp = lp.data_param
        path = sharded_source_path(dp.source, shard.index,
                                   dp.shared_file_system)
        if dp.backend == "LMDB":
            return LMDBSource(path)
        # LEVELDB (the default). Tolerate a converted LMDB at the same path.
        try:
            return LevelDBSource(path)
        except Exception:
            return LMDBSource(path)
    if t == "IMAGE_DATA":
        ip = lp.image_data_param
        path = sharded_source_path(ip.source, shard.index,
                                   ip.shared_file_system)
        return ImageListSource(path, ip.root_folder, ip.new_height,
                               ip.new_width, ip.shuffle)
    if t == "HDF5_DATA":
        return HDF5Source(lp.hdf5_data_param.source)
    if t == "MEMORY_DATA":
        if memory_data is None:
            raise ValueError(
                f"layer {lp.name!r}: MEMORY_DATA requires arrays passed via "
                f"memory_data={{'data': ..., 'label': ...}}")
        return MemorySource(memory_data["data"], memory_data["label"])
    raise ValueError(f"layer {lp.name!r}: {t} is not a batch source")


def layer_batch_size(lp: LayerParameter) -> int:
    t = lp.canonical_type()
    return {
        "DATA": lp.data_param.batch_size,
        "IMAGE_DATA": lp.image_data_param.batch_size,
        "HDF5_DATA": lp.hdf5_data_param.batch_size,
        "MEMORY_DATA": lp.memory_data_param.batch_size,
        "WINDOW_DATA": lp.window_data_param.batch_size,
    }[t]


class BatchPipeline:
    """Iterates {top_name: np.ndarray} batches forever (epoch wraparound),
    prefetching `prefetch` batches ahead on a daemon thread."""

    def __init__(
        self,
        lp: LayerParameter,
        phase: str,
        batch_size: int,
        shard: Shard = Shard(0, 1),
        prefetch: int = 3,
        seed: int = 0,
        shuffle: Optional[bool] = None,
        memory_data: Optional[Dict[str, np.ndarray]] = None,
        use_native: bool = True,
        device_transform: bool = False,
    ):
        self.lp = lp
        self.phase = phase
        self.batch_size = batch_size
        self.shard = shard
        self.seed = seed
        self.shuffle = (phase == "TRAIN") if shuffle is None else shuffle
        self.tops = list(lp.top)
        # device_transform: ship uint8 crops and let the compiled step do
        # (x - mean) * scale on the accelerator — 4x fewer host->device
        # bytes and no per-pixel float math on the host (the TPU-native
        # split of DataTransformer's work). Engaged only when the native
        # batcher supports it; ``device_transform_spec`` is then the
        # {mean, scale} the training side must apply.
        self.device_transform_spec: Optional[Dict] = None
        self._want_device_transform = device_transform

        self.window = None
        if lp.canonical_type() == "WINDOW_DATA":
            from .window import WindowDataSource
            self.window = WindowDataSource(
                lp, phase, seed=seed * shard.count + shard.index)
            self.native = None
            self.source = None
            self._n_records = len(self.window.fg) + len(self.window.bg)
            self.data_shape = (batch_size,) + self.window.record_shape
            self._queue = queue.Queue(maxsize=prefetch)
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._stop = threading.Event()
            self._thread.start()
            return
        self.native = self._try_native(lp, phase, shard) if use_native else None
        self._u8 = False
        if self.native is not None:
            self.source = None
            self._n_records = len(self.native)
            self.data_shape = (batch_size,) + self.native.out_shape
            tp = _effective_transform(lp)
            # exactness constraint: a full mean_file is subtracted at the
            # per-sample SOURCE crop position (data_transformer.cpp indexes
            # the mean by h_off/w_off), which the device cannot see — only
            # mean_value/no-mean configs move on-device
            if (self._want_device_transform and not tp.mean_file
                    and self.native.supports_u8() and self._n_records):
                # probe a spread of records: float_data-backed Datums cannot
                # ship as uint8 (rc=-4), and a MIXED byte/float DB detected
                # here gets the host f32 path for the whole pipeline — the
                # only moment the wire contract can still change (once the
                # step compiles against the uint8 spec, a mid-epoch float
                # record can only be re-quantized, lossily). IndexError
                # covers a DB that vanished between len() and here; the
                # empty-DB case is excluded by _n_records above.
                n = self._n_records
                probe = np.unique(np.linspace(0, n - 1, num=min(n, 8),
                                              dtype=np.int64))
                try:
                    self.native.batch_u8(probe)
                    self._u8 = True
                except (IOError, IndexError):
                    self._u8 = False
            if self._u8:
                mv = (np.asarray(tp.mean_value, np.float32)
                      if tp.mean_value else None)
                if mv is not None and mv.size == 1:
                    mv = np.repeat(mv, self.native.out_shape[0])
                self.device_transform_spec = {
                    "mean_values": mv, "scale": float(tp.scale)}
        else:
            self.source = build_source(lp, shard, memory_data)
            self._n_records = len(self.source)
            self.transformer = DataTransformer(_effective_transform(lp), phase,
                                               seed=seed)
            c, h, w = self.source.record_shape
            self.data_shape = (batch_size,) + \
                self.transformer.output_shape(c, h, w)
        self._queue: queue.Queue = queue.Queue(maxsize=prefetch)
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._stop = threading.Event()
        self._thread.start()

    def _try_native(self, lp: LayerParameter, phase: str, shard: Shard):
        """C++ fast path for LMDB-backed DATA layers (native/...dataplane.cc);
        any failure falls back to the Python source."""
        if lp.canonical_type() != "DATA":
            return None
        try:
            from .native import NativeLMDBBatcher, available
            if not available():
                return None
            dp = lp.data_param
            path = sharded_source_path(dp.source, shard.index,
                                       dp.shared_file_system)
            tp = _effective_transform(lp)
            mean = None
            if tp.mean_file:
                from ..proto.wire import read_blob_file
                mean = read_blob_file(tp.mean_file)[0]
            return NativeLMDBBatcher(
                path, crop_size=tp.crop_size, mirror=tp.mirror,
                train=(phase == "TRAIN"), scale=tp.scale, mean=mean,
                mean_values=np.asarray(tp.mean_value, np.float32)
                if tp.mean_value else None)
        except Exception:
            return None

    # ------------------------------------------------------------------ #
    def _index_stream(self) -> Iterator[int]:
        epoch = 0
        while True:
            idx = shard_indices(self._n_records, self.shard, epoch,
                                self.shuffle, self.seed)
            if len(idx) == 0:
                raise RuntimeError("shard received zero records")
            yield from idx
            epoch += 1

    def _worker(self):
        if self.window is not None:
            try:
                while not self._stop.is_set():
                    data, labels = self.window.batch(self.batch_size)
                    batch = {self.tops[0]: data}
                    if len(self.tops) > 1:
                        batch[self.tops[1]] = labels
                    self._queue.put(batch)
            except Exception as e:
                self._queue.put(e)
            return
        stream = self._index_stream()
        batch_no = 0
        self._warned_mixed = False
        try:
            while not self._stop.is_set():
                idx = np.fromiter((next(stream)
                                   for _ in range(self.batch_size)),
                                  np.int64, count=self.batch_size)
                if self.native is not None:
                    seed = self.seed * 1_000_003 + batch_no
                    if self._u8:
                        try:
                            data, labels = self.native.batch_u8(idx, seed=seed)
                        except IOError:
                            # mixed byte/float DB: the init probe saw record 0
                            # byte-backed, but THIS batch hit a float_data
                            # Datum (rc=-4). Keep the uint8 wire contract by
                            # undoing the host transform's (x - mean) * scale
                            # (same seed -> same crop/mirror), instead of
                            # killing the prefetch worker mid-epoch.
                            data, labels = self.native.batch(idx, seed=seed)
                            spec = self.device_transform_spec or {}
                            raw = data / (spec.get("scale") or 1.0)
                            mv = spec.get("mean_values")
                            if mv is not None:
                                raw = raw + mv.reshape(1, -1, 1, 1)
                            data = np.clip(np.rint(raw), 0, 255) \
                                .astype(np.uint8)
                            if not self._warned_mixed:
                                self._warned_mixed = True
                                import sys
                                print("WARNING: mixed byte/float LMDB under "
                                      "--device_transform; float_data "
                                      "records are re-quantized to uint8 "
                                      "per batch (lossy for values outside "
                                      "[0,255])", file=sys.stderr, flush=True)
                    else:
                        data, labels = self.native.batch(idx, seed=seed)
                else:
                    raw = np.empty(
                        (self.batch_size,) + self.source.record_shape,
                        np.float32)
                    labels = np.empty((self.batch_size,), np.int32)
                    for i, j in enumerate(idx):
                        arr, label = self.source.read(int(j))
                        raw[i] = arr
                        labels[i] = label
                    data = self.transformer(raw)
                batch_no += 1
                batch = {self.tops[0]: data}
                if len(self.tops) > 1:
                    batch[self.tops[1]] = labels
                self._queue.put(batch)
        except Exception as e:  # surface worker death to the consumer
            self._queue.put(e)

    def __iter__(self):
        return self

    def __next__(self) -> Dict[str, np.ndarray]:
        item = self._queue.get()
        if isinstance(item, Exception):
            raise item
        return item

    def close(self):
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass


def place_batch(value: np.ndarray, sharding):
    """Host array -> device array under the trainer's batch sharding: THE
    placement rule, shared by the engine's inline feed, the device
    prefetcher, and the tools path. Multi-process assembles the global
    array from this process's local rows; ``sharding=None`` is a plain
    default-device put. jax is imported lazily so this module stays
    importable from jax-free socket-tier processes."""
    import jax
    if sharding is None:
        return jax.device_put(value)
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(sharding, value)
    return jax.device_put(value, sharding)


class DevicePrefetcher:
    """Device-side half of the input pipeline: a background stage that
    ``jax.device_put``s the next ``depth`` host batches with the trainer's
    batch sharding while the current step runs, so the train thread only
    ever dequeues device-RESIDENT arrays (the host->device copy is off the
    critical path, like the reference's prefetch thread hides decode).

    Wraps a list of :class:`BatchPipeline`-like iterators (their per-top
    dicts are merged into one batch, the ``Engine._next_batch`` contract)
    and owns one daemon thread. Exceptions from the underlying pipelines
    (a dead prefetch worker, a vanished DB) propagate to the consumer on
    ``__next__`` instead of wedging the queue. jax is imported lazily so
    this module stays importable from jax-free socket-tier processes.

    ``passthrough`` resolves per-backend by default (the conv_layout=auto
    pattern): on the CPU backend ``device_put`` moves no bytes over any
    link, so a background put thread is pure core oversubscription —
    measured ~10% per-step LOSS on a 2-core host — and the stage degrades
    to inline assembly with the same contract (sharded placement, sticky
    error surfacing). Accelerator backends get the real thread.
    """

    def __init__(self, pipes, sharding, depth: int = 2,
                 passthrough: Optional[bool] = None):
        self.pipes = list(pipes)
        self.sharding = sharding
        self.depth = max(1, int(depth))
        self.passthrough = (self._auto_passthrough() if passthrough is None
                            else bool(passthrough))
        self._error: Optional[Exception] = None
        self._thread = None
        if not self.passthrough:
            self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()

    @staticmethod
    def _auto_passthrough() -> bool:
        import jax
        return jax.default_backend() == "cpu"

    def _worker(self):
        try:
            while not self._stop.is_set():
                host: Dict[str, np.ndarray] = {}
                for pipe in self.pipes:
                    host.update(next(pipe))
                batch = {k: place_batch(v, self.sharding)
                         for k, v in host.items()}
                # bounded put that still honors close(): a full queue must
                # not pin this thread forever after the consumer left
                while not self._stop.is_set():
                    try:
                        self._queue.put(batch, timeout=0.1)
                        break
                    except queue.Full:
                        continue
        except Exception as e:  # surface pipeline death to the consumer
            self._error = e  # sticky BEFORE the sentinel: set-then-put
            self._queue.put(e)

    def __iter__(self):
        return self

    def __next__(self):
        if self.passthrough:
            if self._error is not None:
                raise self._error
            try:
                host: Dict[str, np.ndarray] = {}
                for pipe in self.pipes:
                    host.update(next(pipe))
                return {k: place_batch(v, self.sharding)
                        for k, v in host.items()}
            except Exception as e:
                self._error = e  # same sticky-death contract as threaded
                raise
        # drain queued batches first (the FIFO puts the death sentinel
        # after every good batch); then a dead worker is dead for good —
        # every subsequent dequeue re-raises instead of blocking forever
        # on the empty queue of a thread that already exited (a retried
        # train() fails loudly)
        try:
            item = self._queue.get_nowait()
        except queue.Empty:
            if self._error is not None:
                raise self._error
            item = self._queue.get()
        if isinstance(item, Exception):
            self._error = item
            raise item
        return item

    def close(self):
        if self._thread is None:
            return
        self._stop.set()
        try:
            while True:
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=2.0)


def build_phase_pipelines(net_param, phase: str, batch_multiplier: int,
                          shard: Shard = Shard(0, 1),
                          memory_data: Optional[Dict[str, np.ndarray]] = None,
                          seed: int = 0, device_transform: bool = False):
    """Build a BatchPipeline per data layer of `net_param` at `phase`.

    Returns (pipelines, source_shapes) where source_shapes carry the
    PER-DEVICE batch (the prototxt batch_size) and each pipeline yields
    batch_size * batch_multiplier rows (the caller's per-host batch).
    Shared by Engine, `test`, and `extract_features` so batch semantics stay
    in one place.
    """
    from ..core.layers import DATA_SOURCE_TYPES
    from ..core.net import filter_net
    from ..proto.messages import NetState

    pipes = []
    shapes: Dict[str, tuple] = {}
    for lp in filter_net(net_param, NetState(phase=phase)):
        if lp.canonical_type() not in DATA_SOURCE_TYPES:
            continue
        per_dev = layer_batch_size(lp)
        if per_dev <= 0:
            raise ValueError(f"layer {lp.name!r}: batch_size must be set")
        pipe = BatchPipeline(lp, phase, per_dev * batch_multiplier,
                             shard=shard, memory_data=memory_data, seed=seed,
                             device_transform=device_transform)
        pipes.append(pipe)
        shapes[lp.top[0]] = (per_dev,) + tuple(pipe.data_shape[1:])
        if len(lp.top) > 1:
            shapes[lp.top[1]] = (per_dev,)
    return pipes, shapes
