"""Deterministic data partitioning across hosts and workers.

The reference splits work two ways: ``shared_file_system`` makes client k open
``<source>_k`` (pre-partitioned by tools/partition_data, caffe.proto:445,
docs/distributed-guide.md:37-43), and the ML library's WorkloadManager
computes contiguous (client x thread) index ranges over a record count
(ps/src/ml/include/ml/util/workload_manager.hpp:23-55). Both reduce to a
shard function over [0, n); this module provides the range math plus an epoch
permutation so every shard sees a disjoint, reshuffled slice per epoch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

import numpy as np


@dataclass(frozen=True)
class Shard:
    index: int
    count: int

    def __post_init__(self):
        if not (0 <= self.index < self.count):
            raise ValueError(f"shard {self.index} of {self.count}")


def contiguous_range(n: int, shard: Shard) -> Tuple[int, int]:
    """WorkloadManager-style contiguous [begin, end) split; remainder goes to
    the leading shards one element each."""
    base = n // shard.count
    rem = n % shard.count
    begin = shard.index * base + min(shard.index, rem)
    end = begin + base + (1 if shard.index < rem else 0)
    return begin, end


def shard_indices(n: int, shard: Shard, epoch: int = 0,
                  shuffle: bool = True, seed: int = 0) -> np.ndarray:
    """Indices this shard reads for the given epoch. All shards use the same
    epoch permutation (seeded identically) so shards stay disjoint."""
    if shuffle:
        perm = np.random.RandomState(seed + epoch).permutation(n)
    else:
        perm = np.arange(n)
    begin, end = contiguous_range(n, shard)
    return perm[begin:end]


def sharded_source_path(source: str, shard_index: int,
                        shared_file_system: bool) -> str:
    """The reference's `_k` suffix convention for pre-partitioned databases."""
    if shared_file_system:
        return f"{source}_{shard_index}"
    return source
