"""Deterministic data partitioning across hosts and workers.

The reference splits work two ways: ``shared_file_system`` makes client k open
``<source>_k`` (pre-partitioned by tools/partition_data, caffe.proto:445,
docs/distributed-guide.md:37-43), and the ML library's WorkloadManager
computes contiguous (client x thread) index ranges over a record count
(ps/src/ml/include/ml/util/workload_manager.hpp:23-55). Both reduce to a
shard function over [0, n); this module provides the range math plus an epoch
permutation so every shard sees a disjoint, reshuffled slice per epoch.

Elastic membership (the async-SSP tier admits/retires workers mid-run)
keys the assignment by the CURRENT member list instead of a launch-time
(rank, world): :func:`member_shard` maps a worker id to its position in
the sorted member list, so a 1 -> 3 -> 2 scale sequence partitions the
record space cleanly at every membership — for any fixed (members, epoch)
the shards are disjoint and cover [0, n), and a membership change simply
re-cuts the same epoch permutation into the new number of ranges.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterable, Tuple

import numpy as np


@dataclass(frozen=True)
class Shard:
    index: int
    count: int

    def __post_init__(self):
        if not (0 <= self.index < self.count):
            raise ValueError(f"shard {self.index} of {self.count}")


def contiguous_range(n: int, shard: Shard) -> Tuple[int, int]:
    """WorkloadManager-style contiguous [begin, end) split; remainder goes to
    the leading shards one element each."""
    base = n // shard.count
    rem = n % shard.count
    begin = shard.index * base + min(shard.index, rem)
    end = begin + base + (1 if shard.index < rem else 0)
    return begin, end


def shard_indices(n: int, shard: Shard, epoch: int = 0,
                  shuffle: bool = True, seed: int = 0) -> np.ndarray:
    """Indices this shard reads for the given epoch. All shards use the same
    epoch permutation (seeded identically) so shards stay disjoint."""
    if shuffle:
        perm = np.random.RandomState(seed + epoch).permutation(n)
    else:
        perm = np.arange(n)
    begin, end = contiguous_range(n, shard)
    return perm[begin:end]


def member_shard(members: Iterable[int], worker: int) -> Shard:
    """The elastic assignment: worker ``worker``'s shard under the CURRENT
    member list. Position in the sorted member list is the shard index and
    the member count is the shard count, so the mapping depends only on
    the membership SET — every member computes the identical partition
    with no coordination beyond knowing who is in the fleet."""
    ms = sorted(set(members))
    if worker not in ms:
        raise ValueError(f"worker {worker} not in member list {ms}")
    return Shard(ms.index(worker), len(ms))


def elastic_shard_indices(n: int, worker: int, members: Iterable[int],
                          epoch: int = 0, shuffle: bool = True,
                          seed: int = 0) -> np.ndarray:
    """Indices ``worker`` reads for ``epoch`` under the current member
    list. Keyed by (members, epoch): the epoch permutation is shared by
    every member (seeded identically, membership-independent), and the
    member list only decides how many contiguous ranges it is cut into —
    so shards are disjoint and cover [0, n) for ANY membership, and a
    scale event mid-epoch re-cuts the SAME permutation (rows move between
    workers; none are duplicated or dropped by the re-cut itself)."""
    return shard_indices(n, member_shard(members, worker), epoch=epoch,
                         shuffle=shuffle, seed=seed)


def sharded_source_path(source: str, shard_index: int,
                        shared_file_system: bool) -> str:
    """The reference's `_k` suffix convention for pre-partitioned databases."""
    if shared_file_system:
        return f"{source}_{shard_index}"
    return source
