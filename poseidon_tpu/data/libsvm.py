"""libsvm-format parsing + sparse feature vectors (the reference's ML library:
ps/src/ml/include/ml/feature/, ps/src/ml/util/data_loading.hpp).

Provides dense and sparse feature containers and a libsvm reader usable as a
training Source for non-vision workloads (logistic regression-style apps the
Petuum ML library served).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np


@dataclass
class SparseFeatures:
    """CSR-ish batch: concatenated (index, value) runs per row."""
    indices: np.ndarray   # int32 (nnz,)
    values: np.ndarray    # float32 (nnz,)
    offsets: np.ndarray   # int64 (rows+1,)
    dim: int

    def to_dense(self) -> np.ndarray:
        rows = len(self.offsets) - 1
        out = np.zeros((rows, self.dim), np.float32)
        for r in range(rows):
            lo, hi = self.offsets[r], self.offsets[r + 1]
            out[r, self.indices[lo:hi]] = self.values[lo:hi]
        return out


def read_libsvm(path: str, feature_dim: int = 0, one_based: bool = True
                ) -> Tuple[SparseFeatures, np.ndarray]:
    """Parse a libsvm file -> (features, labels). With feature_dim=0 the
    dimensionality is inferred from the max index seen."""
    labels: List[float] = []
    indices: List[int] = []
    values: List[float] = []
    offsets: List[int] = [0]
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                idx_s, val_s = tok.split(":", 1)
                idx = int(idx_s) - (1 if one_based else 0)
                if idx < 0:
                    raise ValueError(f"{path}: bad feature index {idx_s}")
                indices.append(idx)
                values.append(float(val_s))
                max_idx = max(max_idx, idx)
            offsets.append(len(indices))
    dim = feature_dim or (max_idx + 1)
    return (SparseFeatures(np.asarray(indices, np.int32),
                           np.asarray(values, np.float32),
                           np.asarray(offsets, np.int64), dim),
            np.asarray(labels, np.float32))
