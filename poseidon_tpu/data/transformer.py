"""DataTransformer: crop / mirror / mean-subtract / scale, Caffe-exact.

Spec: ``src/caffe/data_transformer.cpp`` —
- random crop offsets in [0, dim - crop) at TRAIN, center crop at TEST
- mirror flips the width axis (requires crop in the reference; supported
  standalone here)
- mean handling: a full-size mean array is indexed at the *source* (cropped)
  position; per-channel mean_values broadcast; then (x - mean) * scale.

Vectorized over the batch with numpy on the host; the result is what gets
device_put into the traced graph.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..proto.messages import TransformationParameter


class DataTransformer:
    def __init__(self, param: TransformationParameter, phase: str,
                 mean: Optional[np.ndarray] = None, seed: int = 0):
        self.param = param
        self.phase = phase
        self.rng = np.random.RandomState(seed)
        self.mean = None
        if param.mean_file:
            from ..proto.wire import read_blob_file
            self.mean = read_blob_file(param.mean_file)[0]  # (C, H, W)
        elif mean is not None:
            self.mean = np.asarray(mean, np.float32)
        self.mean_values = np.asarray(param.mean_value, np.float32) \
            if param.mean_value else None

    def output_shape(self, channels: int, height: int, width: int):
        c = self.param.crop_size
        if c:
            return (channels, c, c)
        return (channels, height, width)

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        """batch: (N, C, H, W) float32 raw datum values (never mutated)."""
        x = np.array(batch, np.float32)  # copy: the mirror path writes in place
        n, c, h, w = x.shape
        crop = self.param.crop_size
        train = self.phase == "TRAIN"

        if crop:
            if crop > h or crop > w:
                raise ValueError(f"crop_size {crop} exceeds image {h}x{w}")
            if train and (h > crop or w > crop):
                h_off = self.rng.randint(0, h - crop + 1, size=n)
                w_off = self.rng.randint(0, w - crop + 1, size=n)
            else:
                h_off = np.full(n, (h - crop) // 2)
                w_off = np.full(n, (w - crop) // 2)
            idx_h = h_off[:, None] + np.arange(crop)[None, :]
            idx_w = w_off[:, None] + np.arange(crop)[None, :]
            cropped = x[np.arange(n)[:, None, None, None],
                        np.arange(c)[None, :, None, None],
                        idx_h[:, None, :, None],
                        idx_w[:, None, None, :]]
            if self.mean is not None:
                # mean indexed at the source crop position (reference behavior)
                m = self.mean[np.arange(c)[None, :, None, None],
                              idx_h[:, None, :, None],
                              idx_w[:, None, None, :]]
                cropped = cropped - m
            elif self.mean_values is not None:
                cropped = cropped - self._mv(c)
            x = cropped
        else:
            if self.mean is not None:
                x = x - self.mean[None]
            elif self.mean_values is not None:
                x = x - self._mv(c)

        if self.param.mirror and train:
            flip = self.rng.randint(0, 2, size=n).astype(bool)
            x[flip] = x[flip, :, :, ::-1]

        if self.param.scale != 1.0:
            x = x * self.param.scale
        return np.ascontiguousarray(x, np.float32)

    def _mv(self, channels: int) -> np.ndarray:
        mv = self.mean_values
        if mv.size == 1:
            mv = np.repeat(mv, channels)
        if mv.size != channels:
            raise ValueError(
                f"mean_value: specify 1 or {channels} values, got {mv.size}")
        return mv.reshape(1, channels, 1, 1)
