"""Dependency-free LevelDB reader/writer (the reference's default backend).

Caffe's DataParameter defaults to ``backend: LEVELDB`` (caffe.proto:444); the
image has no leveldb binding, so this module implements the on-disk format
directly (the format is public domain, OpenLDAP-style clean-room from the
spec):

- **SSTables** (``*.ldb``/``*.sst``): footer → index block → data blocks;
  per-block snappy (data/snappy.py) or raw; prefix-compressed keys with
  restart points; internal keys carry an 8-byte (sequence<<8|type) trailer.
- **Write-ahead log** (``*.log``): 32 KB physical blocks of
  crc/len/type-framed fragments; logical records are WriteBatches. A
  freshly-written, never-compacted Caffe database keeps its newest entries
  only here, so replay is required for correctness.
- **MANIFEST/CURRENT**: VersionEdit log naming the live files.

Reading merges SSTables + log by user key, newest sequence wins, deletions
drop. ``LevelDBWriter`` emits a single-SSTable database (+ manifest/current)
that standard LevelDB implementations accept — used by the dataset tools for
backend parity.
"""

from __future__ import annotations

import os
import struct
from typing import Dict, Iterator, List, Optional, Tuple

from .snappy import compress as snappy_compress
from .snappy import uncompress as snappy_uncompress

TABLE_MAGIC = 0xDB4775248B80FB57

TYPE_DELETION = 0
TYPE_VALUE = 1

LOG_FULL, LOG_FIRST, LOG_MIDDLE, LOG_LAST = 1, 2, 3, 4
LOG_BLOCK = 32768
LOG_HEADER = 7


class LevelDBError(IOError):
    pass


# --------------------------------------------------------------------------- #
# varints & crc32c
# --------------------------------------------------------------------------- #

from .varint import VarintError, read_varint as _shared_read_varint
from .varint import write_varint as _write_varint


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    try:
        return _shared_read_varint(buf, pos)
    except VarintError as e:
        raise LevelDBError(str(e)) from e


_CRC_TABLE: List[int] = []


def _crc32c_init():
    poly = 0x82F63B78
    for i in range(256):
        crc = i
        for _ in range(8):
            crc = (crc >> 1) ^ (poly if crc & 1 else 0)
        _CRC_TABLE.append(crc)


_crc32c_init()


def crc32c(data: bytes, crc: int = 0) -> int:
    crc ^= 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def crc32c_masked(data: bytes) -> int:
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --------------------------------------------------------------------------- #
# SSTable reading
# --------------------------------------------------------------------------- #

def _parse_block(data: bytes) -> Iterator[Tuple[bytes, bytes]]:
    """Yield (key, value) from a decoded block (prefix-compressed entries)."""
    if len(data) < 4:
        return
    n_restarts = struct.unpack_from("<I", data, len(data) - 4)[0]
    limit = len(data) - 4 - 4 * n_restarts
    pos = 0
    key = b""
    while pos < limit:
        shared, pos = _read_varint(data, pos)
        non_shared, pos = _read_varint(data, pos)
        value_len, pos = _read_varint(data, pos)
        key = key[:shared] + data[pos:pos + non_shared]
        pos += non_shared
        value = data[pos:pos + value_len]
        pos += value_len
        yield key, value


def _read_block(buf: bytes, offset: int, size: int) -> bytes:
    data = buf[offset:offset + size]
    if len(data) != size or offset + size + 1 > len(buf):
        raise LevelDBError("truncated block")
    block_type = buf[offset + size]
    if block_type == 0:
        return data
    if block_type == 1:
        return snappy_uncompress(data)
    raise LevelDBError(f"unknown block compression {block_type}")


class SSTable:
    """One .ldb/.sst file, mmap'd; blocks decode on demand."""

    def __init__(self, path: str):
        import mmap
        self.path = path
        self._f = open(path, "rb")
        self.buf = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        if len(self.buf) < 48:
            raise LevelDBError(f"{path}: too small for an sstable")
        footer = self.buf[-48:]
        magic = struct.unpack_from("<Q", footer, 40)[0]
        if magic != TABLE_MAGIC:
            raise LevelDBError(f"{path}: bad table magic")
        pos = 0
        _, pos = _read_varint(footer, pos)       # metaindex offset
        _, pos = _read_varint(footer, pos)       # metaindex size
        index_off, pos = _read_varint(footer, pos)
        index_size, pos = _read_varint(footer, pos)
        index = _read_block(self.buf, index_off, index_size)
        self.block_handles: List[Tuple[int, int]] = []
        for _, handle in _parse_block(index):
            hpos = 0
            boff, hpos = _read_varint(handle, hpos)
            bsize, hpos = _read_varint(handle, hpos)
            self.block_handles.append((boff, bsize))

    def block_entries(self, handle: Tuple[int, int]
                      ) -> List[Tuple[bytes, int, int, bytes]]:
        """Decoded (user_key, seq, type, value) list for one data block."""
        block = _read_block(self.buf, handle[0], handle[1])
        out = []
        for ikey, value in _parse_block(block):
            if len(ikey) < 8:
                raise LevelDBError(f"{self.path}: internal key too short")
            trailer = struct.unpack("<Q", ikey[-8:])[0]
            out.append((ikey[:-8], trailer >> 8, trailer & 0xFF, value))
        return out


def read_sstable(path: str) -> Iterator[Tuple[bytes, int, int, bytes]]:
    """Yield (user_key, sequence, type, value) from one .ldb/.sst file."""
    table = SSTable(path)
    for handle in table.block_handles:
        yield from table.block_entries(handle)


# --------------------------------------------------------------------------- #
# Log reading (write-ahead log replay)
# --------------------------------------------------------------------------- #

def _log_records(buf: bytes) -> Iterator[bytes]:
    pos = 0
    pending = bytearray()
    while pos + LOG_HEADER <= len(buf):
        block_left = LOG_BLOCK - (pos % LOG_BLOCK)
        if block_left < LOG_HEADER:
            pos += block_left  # trailer padding
            continue
        length, rtype = struct.unpack_from("<HB", buf, pos + 4)
        payload = buf[pos + LOG_HEADER:pos + LOG_HEADER + length]
        if rtype == 0 and length == 0:
            break  # zeroed preallocated tail
        pos += LOG_HEADER + length
        if rtype == LOG_FULL:
            yield bytes(payload)
        elif rtype == LOG_FIRST:
            pending = bytearray(payload)
        elif rtype == LOG_MIDDLE:
            pending += payload
        elif rtype == LOG_LAST:
            pending += payload
            yield bytes(pending)
            pending = bytearray()
        else:
            return  # corrupt tail: stop like leveldb's recovery does


def read_log(path: str) -> Iterator[Tuple[bytes, int, int, bytes]]:
    """Yield (user_key, sequence, type, value) from a write-ahead log."""
    with open(path, "rb") as f:
        buf = f.read()
    for record in _log_records(buf):
        if len(record) < 12:
            continue
        seq = struct.unpack_from("<Q", record, 0)[0]
        count = struct.unpack_from("<I", record, 8)[0]
        pos = 12
        for i in range(count):
            if pos >= len(record):
                break
            op = record[pos]
            pos += 1
            klen, pos = _read_varint(record, pos)
            key = record[pos:pos + klen]
            pos += klen
            if op == TYPE_VALUE:
                vlen, pos = _read_varint(record, pos)
                value = record[pos:pos + vlen]
                pos += vlen
                yield key, seq + i, TYPE_VALUE, value
            else:
                yield key, seq + i, TYPE_DELETION, b""


# --------------------------------------------------------------------------- #
# MANIFEST / CURRENT
# --------------------------------------------------------------------------- #

def _read_manifest(path: str) -> Tuple[List[int], int]:
    """-> (live sstable file numbers, current log number)."""
    with open(path, "rb") as f:
        buf = f.read()
    live: Dict[int, bool] = {}
    log_number = 0
    for record in _log_records(buf):
        pos = 0
        while pos < len(record):
            tag, pos = _read_varint(record, pos)
            if tag == 1:          # comparator name
                ln, pos = _read_varint(record, pos)
                pos += ln
            elif tag == 2:        # log number
                log_number, pos = _read_varint(record, pos)
            elif tag == 9:        # prev log number
                _, pos = _read_varint(record, pos)
            elif tag == 3:        # next file number
                _, pos = _read_varint(record, pos)
            elif tag == 4:        # last sequence
                _, pos = _read_varint(record, pos)
            elif tag == 5:        # compact pointer: level + internal key
                _, pos = _read_varint(record, pos)
                ln, pos = _read_varint(record, pos)
                pos += ln
            elif tag == 6:        # deleted file: level + number
                _, pos = _read_varint(record, pos)
                num, pos = _read_varint(record, pos)
                live.pop(num, None)
            elif tag == 7:        # new file: level num size smallest largest
                _, pos = _read_varint(record, pos)
                num, pos = _read_varint(record, pos)
                _, pos = _read_varint(record, pos)
                ln, pos = _read_varint(record, pos)
                pos += ln
                ln, pos = _read_varint(record, pos)
                pos += ln
                live[num] = True
            else:
                raise LevelDBError(f"{path}: unknown VersionEdit tag {tag}")
    return sorted(live), log_number


# --------------------------------------------------------------------------- #
# Reader facade
# --------------------------------------------------------------------------- #

class LevelDBReader:
    """Read-only merged view of a LevelDB directory, sorted by key.

    Startup scans every block once to build the key index but keeps only
    locators — (table, block, entry) for SSTable values, inline bytes for
    WAL-resident values — so memory stays proportional to the key count, not
    the dataset. ``value_at`` decodes blocks on demand through a small LRU."""

    BLOCK_CACHE = 16

    def __init__(self, path: str):
        if not os.path.isdir(path):
            raise LevelDBError(f"{path}: not a LevelDB directory")
        names = os.listdir(path)
        if "CURRENT" not in names and not any(
                n.endswith((".ldb", ".sst", ".log")) for n in names):
            raise LevelDBError(f"{path}: no LevelDB files "
                               f"(CURRENT/.ldb/.sst/.log) in directory")

        # key -> (seq, type, locator); locator = (table_idx, block_idx,
        # entry_idx) for sstables, ("mem", value) for WAL entries.
        entries: Dict[bytes, Tuple[int, int, tuple]] = {}

        def absorb(key, seq, typ, locator):
            cur = entries.get(key)
            if cur is None or seq >= cur[0]:
                entries[key] = (seq, typ, locator)

        current = os.path.join(path, "CURRENT")
        sst_numbers: Optional[List[int]] = None
        log_floor = 0
        if os.path.exists(current):
            with open(current) as f:
                manifest = f.read().strip()
            mpath = os.path.join(path, manifest)
            if os.path.exists(mpath):
                sst_numbers, log_floor = _read_manifest(mpath)

        def file_number(name: str) -> int:
            return int(name.split(".")[0].split("-")[0])

        self._tables: List[SSTable] = []
        for name in sorted(names):
            if name.endswith((".ldb", ".sst")):
                if sst_numbers is not None and \
                        file_number(name) not in sst_numbers:
                    continue  # obsolete (compacted-away) table
                table = SSTable(os.path.join(path, name))
                t_idx = len(self._tables)
                self._tables.append(table)
                for b_idx, handle in enumerate(table.block_handles):
                    for e_idx, (key, seq, typ, _value) in enumerate(
                            table.block_entries(handle)):
                        absorb(key, seq, typ, (t_idx, b_idx, e_idx))
        for name in sorted(names):
            if name.endswith(".log"):
                if sst_numbers is not None and file_number(name) < log_floor:
                    continue  # superseded by flushed tables
                for key, seq, typ, value in read_log(
                        os.path.join(path, name)):
                    absorb(key, seq, typ, ("mem", value))

        self._keys = sorted(k for k, (_, typ, _l) in entries.items()
                            if typ == TYPE_VALUE)
        self._entries = entries
        from collections import OrderedDict
        self._cache: "OrderedDict[tuple, list]" = OrderedDict()

    def _value(self, key: bytes) -> bytes:
        locator = self._entries[key][2]
        if locator[0] == "mem":
            return locator[1]
        t_idx, b_idx, e_idx = locator
        cache_key = (t_idx, b_idx)
        block = self._cache.get(cache_key)
        if block is None:
            table = self._tables[t_idx]
            block = table.block_entries(table.block_handles[b_idx])
            self._cache[cache_key] = block
            if len(self._cache) > self.BLOCK_CACHE:
                self._cache.popitem(last=False)
        else:
            self._cache.move_to_end(cache_key)
        return block[e_idx][3]

    def __len__(self) -> int:
        return len(self._keys)

    def __iter__(self) -> Iterator[Tuple[bytes, bytes]]:
        for k in self._keys:
            yield k, self._value(k)

    def key_at(self, i: int) -> bytes:
        return self._keys[i]

    def value_at(self, i: int) -> bytes:
        return self._value(self._keys[i])


# --------------------------------------------------------------------------- #
# Writer: one sorted SSTable + manifest + current
# --------------------------------------------------------------------------- #

class LevelDBWriter:
    BLOCK_SIZE = 4096
    RESTART_INTERVAL = 16

    def __init__(self, path: str, compress: bool = True):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.compress = compress
        self.items: List[Tuple[bytes, bytes]] = []

    def put(self, key: bytes, value: bytes) -> None:
        self.items.append((key, value))

    # -- block building ------------------------------------------------- #
    def _build_block(self, entries: List[Tuple[bytes, bytes]]) -> bytes:
        out = bytearray()
        restarts = []
        prev_key = b""
        for i, (key, value) in enumerate(entries):
            if i % self.RESTART_INTERVAL == 0:
                restarts.append(len(out))
                shared = 0
            else:
                shared = 0
                limit = min(len(prev_key), len(key))
                while shared < limit and key[shared] == prev_key[shared]:
                    shared += 1
            _write_varint(out, shared)
            _write_varint(out, len(key) - shared)
            _write_varint(out, len(value))
            out += key[shared:]
            out += value
            prev_key = key
        for r in restarts:
            out += struct.pack("<I", r)
        out += struct.pack("<I", len(restarts))
        return bytes(out)

    def _emit_block(self, f, raw: bytes) -> bytes:
        """Write block (+type+crc); return the BlockHandle."""
        offset = f.tell()
        if self.compress:
            data, btype = snappy_compress(raw), 1
        else:
            data, btype = raw, 0
        f.write(data)
        f.write(bytes([btype]))
        f.write(struct.pack("<I", crc32c_masked(data + bytes([btype]))))
        handle = bytearray()
        _write_varint(handle, offset)
        _write_varint(handle, len(data))
        return bytes(handle)

    def close(self) -> None:
        # last put wins for duplicate keys; stock LevelDB orders duplicate
        # user keys by DESCENDING sequence, which a single-sequence-per-key
        # table sidesteps entirely.
        items = sorted(dict(self.items).items())
        table_no, manifest_no, log_no = 2, 1, 3
        table_path = os.path.join(self.path, f"{table_no:06d}.ldb")
        index_entries: List[Tuple[bytes, bytes]] = []
        seq = 1
        with open(table_path, "wb") as f:
            block: List[Tuple[bytes, bytes]] = []
            block_bytes = 0
            for key, value in items:
                ikey = key + struct.pack("<Q", (seq << 8) | TYPE_VALUE)
                seq += 1
                block.append((ikey, value))
                block_bytes += len(ikey) + len(value) + 8
                if block_bytes >= self.BLOCK_SIZE:
                    handle = self._emit_block(f, self._build_block(block))
                    index_entries.append((block[-1][0], handle))
                    block, block_bytes = [], 0
            if block:
                handle = self._emit_block(f, self._build_block(block))
                index_entries.append((block[-1][0], handle))
            metaindex_handle = self._emit_block(f, self._build_block([]))
            index_handle = self._emit_block(f, self._build_block(index_entries))
            footer = bytearray()
            footer += metaindex_handle
            footer += index_handle
            footer += b"\0" * (40 - len(footer))
            footer += struct.pack("<Q", TABLE_MAGIC)
            f.write(footer)
            table_size = f.tell()

        # Manifest: one VersionEdit declaring the table + an empty live log.
        edit = bytearray()
        _write_varint(edit, 1)
        comparator = b"leveldb.BytewiseComparator"
        _write_varint(edit, len(comparator))
        edit += comparator
        _write_varint(edit, 2)
        _write_varint(edit, log_no)
        _write_varint(edit, 3)
        _write_varint(edit, log_no + 1)
        _write_varint(edit, 4)
        _write_varint(edit, seq)
        if items:
            smallest = items[0][0] + struct.pack("<Q", (1 << 8) | TYPE_VALUE)
            largest = items[-1][0] + struct.pack(
                "<Q", ((seq - 1) << 8) | TYPE_VALUE)
            _write_varint(edit, 7)
            _write_varint(edit, 0)          # level
            _write_varint(edit, table_no)
            _write_varint(edit, table_size)
            _write_varint(edit, len(smallest))
            edit += smallest
            _write_varint(edit, len(largest))
            edit += largest

        with open(os.path.join(self.path, f"MANIFEST-{manifest_no:06d}"),
                  "wb") as f:
            payload = bytes(edit)
            header = struct.pack(
                "<IHB",
                crc32c_masked(bytes([LOG_FULL]) + payload),
                len(payload), LOG_FULL)
            f.write(header + payload)
        with open(os.path.join(self.path, f"{log_no:06d}.log"), "wb"):
            pass
        with open(os.path.join(self.path, "CURRENT"), "w") as f:
            f.write(f"MANIFEST-{manifest_no:06d}\n")
