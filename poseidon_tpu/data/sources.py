"""Data sources: the host-side counterparts of the reference's data layers.

Each source yields (image_array, label) records; batching, augmentation and
device transfer are layered on top (pipeline.py). Backends mirror the layer
catalog: DATA (LMDB and LevelDB via our readers), IMAGE_DATA (file lists +
PIL/cv2 decode), HDF5_DATA, MEMORY_DATA, plus synthetic sources for
benchmarks. Reference: ``src/caffe/layers/{data,image_data,hdf5_data,
memory_data}_layer.cpp`` and ``include/caffe/data_layers.hpp:73-122``.
"""

from __future__ import annotations

import os
from typing import List, Tuple

import numpy as np

from ..proto.wire import decode_datum


class Source:
    """Random-access record source."""

    def __len__(self) -> int:
        raise NotImplementedError

    def read(self, index: int) -> Tuple[np.ndarray, int]:
        """-> ((C, H, W) float32 raw values, int label)."""
        raise NotImplementedError

    @property
    def record_shape(self) -> Tuple[int, int, int]:
        arr, _ = self.read(0)
        return tuple(arr.shape)  # type: ignore[return-value]


class LMDBSource(Source):
    def __init__(self, path: str):
        from .lmdb_reader import LMDBReader
        self.db = LMDBReader(path)

    def __len__(self) -> int:
        return len(self.db)

    def read(self, index: int) -> Tuple[np.ndarray, int]:
        d = decode_datum(self.db.value_at(index))
        return d.to_array(), d.label


class LevelDBSource(Source):
    """DATA backend LEVELDB (the caffe.proto default), via the pure-Python
    SSTable/log/manifest reader in leveldb_reader.py."""

    def __init__(self, path: str):
        from .leveldb_reader import LevelDBReader
        self.db = LevelDBReader(path)

    def __len__(self) -> int:
        return len(self.db)

    def read(self, index: int) -> Tuple[np.ndarray, int]:
        d = decode_datum(self.db.value_at(index))
        return d.to_array(), d.label


class ImageListSource(Source):
    """IMAGE_DATA: a text file of '<path> <label>' lines, decoded on read."""

    def __init__(self, source: str, root_folder: str = "",
                 new_height: int = 0, new_width: int = 0,
                 shuffle: bool = False, seed: int = 0,
                 color: bool = True):
        self.entries: List[Tuple[str, int]] = []
        with open(source) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                path, label = line.rsplit(None, 1)
                self.entries.append((os.path.join(root_folder, path),
                                     int(label)))
        if shuffle:
            np.random.RandomState(seed).shuffle(self.entries)
        self.new_height = new_height
        self.new_width = new_width
        self.color = color

    def __len__(self) -> int:
        return len(self.entries)

    def read(self, index: int) -> Tuple[np.ndarray, int]:
        from PIL import Image
        path, label = self.entries[index]
        img = Image.open(path)
        img = img.convert("RGB" if self.color else "L")
        if self.new_height and self.new_width:
            img = img.resize((self.new_width, self.new_height))
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        # Caffe stores images BGR, HWC -> CHW
        arr = arr[:, :, ::-1] if self.color else arr
        return np.ascontiguousarray(arr.transpose(2, 0, 1)), label


class HDF5Source(Source):
    """HDF5_DATA: 'source' is a text file listing .h5 files with datasets
    'data' and 'label' (hdf5_data_layer.cpp)."""

    def __init__(self, source: str):
        import h5py
        with open(source) as f:
            names = [l.strip() for l in f if l.strip()]
        data: List[np.ndarray] = []
        labels: List[np.ndarray] = []
        for name in names:
            with h5py.File(name, "r") as h:
                data.append(np.asarray(h["data"], np.float32))
                labels.append(np.asarray(h["label"]).reshape(-1))
        self.data_cat = np.concatenate(data)
        self.labels_cat = np.concatenate(labels)

    def __len__(self) -> int:
        return len(self.data_cat)

    def read(self, index: int) -> Tuple[np.ndarray, int]:
        arr = self.data_cat[index]
        if arr.ndim == 1:
            arr = arr[:, None, None]
        return arr, int(self.labels_cat[index])


class MemorySource(Source):
    """MEMORY_DATA: arrays handed in by the caller (memory_data_layer.cpp)."""

    def __init__(self, data: np.ndarray, labels: np.ndarray):
        self.data = np.asarray(data, np.float32)
        self.labels = np.asarray(labels).reshape(-1)
        if len(self.data) != len(self.labels):
            raise ValueError("data/label count mismatch")

    def __len__(self) -> int:
        return len(self.data)

    def read(self, index: int) -> Tuple[np.ndarray, int]:
        return self.data[index], int(self.labels[index])


class SyntheticSource(Source):
    """Deterministic learnable task for tests/benchmarks: class templates plus
    Gaussian noise."""

    def __init__(self, shape: Tuple[int, int, int], num_classes: int,
                 size: int = 1 << 16, noise: float = 0.3, seed: int = 0):
        rs = np.random.RandomState(seed)
        self.templates = rs.randn(num_classes, *shape).astype(np.float32)
        self.noise = noise
        self.size = size
        self.num_classes = num_classes
        self.shape = shape

    def __len__(self) -> int:
        return self.size

    def read(self, index: int) -> Tuple[np.ndarray, int]:
        rs = np.random.RandomState(index)
        label = index % self.num_classes
        return (self.templates[label]
                + self.noise * rs.randn(*self.shape).astype(np.float32),
                label)
