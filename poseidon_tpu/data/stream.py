"""Out-of-core streaming: the ML library's DiskStreamer analog.

The reference streams datasets larger than RAM through a rotating set of
byte buffers: one IO thread reads files (a directory, an explicit list, or a
numbered ``prefix_N`` sequence, optionally snappy-compressed) into a bounded
MultiBuffer; worker threads pull parsed records N at a time
(ps/src/ml/disk_stream/{disk_streamer,multi_buffer,disk_reader}.hpp,
parsers/libsvm_parser.hpp). Memory stays proportional to
``num_buffers x file size`` regardless of dataset size, and ``num_passes``
supports multi-epoch streaming (0 = infinite).

This module reproduces that shape with a Python IO thread + bounded queue:
``DiskStreamer(config, parser).get_next_data(n)`` returns up to n parsed
records, an empty list meaning end-of-stream — the same contract as the
reference's ``GetNextData``. ``LibSVMParser`` is the stock parser; any
callable ``bytes -> list`` works.
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np


@dataclass
class DiskStreamConfig:
    """DiskReaderConfig + DiskStreamerConfig merged (one worker thread —
    the SPMD step consumes batches; there are no per-core worker threads to
    coordinate with a barrier here)."""
    num_buffers: int = 2          # bound on in-flight file buffers
    num_passes: int = 1           # 0 = infinite
    snappy_compressed: bool = False
    # exactly one of the three read modes:
    dir_path: str = ""            # every regular file under a directory
    file_list: Sequence[str] = field(default_factory=tuple)
    file_seq_prefix: str = ""     # prefix_<id> for id in [begin, begin+num)
    seq_id_begin: int = 0
    num_files: int = 0

    def files(self) -> List[str]:
        if self.dir_path:
            return sorted(
                os.path.join(self.dir_path, n)
                for n in os.listdir(self.dir_path)
                if os.path.isfile(os.path.join(self.dir_path, n)))
        if self.file_list:
            return list(self.file_list)
        if self.file_seq_prefix:
            return [f"{self.file_seq_prefix}_{i}"
                    for i in range(self.seq_id_begin,
                                   self.seq_id_begin + self.num_files)]
        raise ValueError("DiskStreamConfig: no read mode configured")


class DiskStreamer:
    """Background IO thread + bounded buffer queue + pull-based parsing."""

    _EOS = object()

    def __init__(self, config: DiskStreamConfig,
                 parser: Callable[[bytes], list]):
        self.config = config
        self.parser = parser
        self._files = config.files()
        if not self._files:
            raise ValueError("DiskStreamer: no input files")
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1,
                                                         config.num_buffers))
        self._pending: list = []
        self._done = False
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._io = threading.Thread(target=self._io_loop, daemon=True)
        self._io.start()

    # -- IO thread: the DiskReader ------------------------------------- #
    def _io_loop(self):
        passes = 0
        try:
            while not self._stop.is_set():
                for path in self._files:
                    if self._stop.is_set():
                        return
                    with open(path, "rb") as f:
                        buf = f.read()
                    if self.config.snappy_compressed:
                        from .snappy import uncompress
                        buf = uncompress(buf)
                    # blocks when num_buffers are already in flight: the
                    # MultiBuffer bound that keeps memory constant
                    self._put(buf)
                passes += 1
                if self.config.num_passes and \
                        passes >= self.config.num_passes:
                    break
        except BaseException as e:  # noqa: BLE001 — surface on the worker
            self._error = e
        finally:
            self._put(self._EOS)

    def _put(self, item):
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    # -- worker side ---------------------------------------------------- #
    def get_next_data(self, num_data: int) -> list:
        """Up to ``num_data`` parsed records; [] signals end of stream.
        An IO-thread failure re-raises HERE — a missing/corrupt file must
        never masquerade as a clean (truncated) end of stream."""
        while len(self._pending) < num_data and not self._done:
            item = self._q.get()
            if item is self._EOS:
                self._done = True
                if self._error is not None:
                    raise RuntimeError(
                        f"DiskStreamer IO thread failed: {self._error}"
                    ) from self._error
                break
            self._pending.extend(self.parser(item))
        out, self._pending = (self._pending[:num_data],
                              self._pending[num_data:])
        return out

    def shutdown(self):
        self._stop.set()
        # drain so a blocked _put can observe the stop flag
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._io.join(timeout=5.0)


class LibSVMParser:
    """parsers/libsvm_parser.hpp analog: one buffer -> list of
    (label, indices int32, values float32) rows."""

    def __init__(self, one_based: bool = True):
        self.one_based = one_based

    def __call__(self, buf: bytes) -> list:
        out = []
        off = 1 if self.one_based else 0
        for line in buf.decode().splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            idx = np.empty(len(parts) - 1, np.int32)
            val = np.empty(len(parts) - 1, np.float32)
            for j, tok in enumerate(parts[1:]):
                i_s, v_s = tok.split(":", 1)
                idx[j] = int(i_s) - off
                val[j] = float(v_s)
            out.append((float(parts[0]), idx, val))
        return out


def stream_dense_batches(streamer: DiskStreamer, batch_size: int,
                         feature_dim: int):
    """Generator of (features (B, D) f32, labels (B,) f32) batches from a
    libsvm DiskStreamer — the data_loading.hpp-style convenience on top."""
    while True:
        rows = streamer.get_next_data(batch_size)
        if not rows:
            return
        x = np.zeros((len(rows), feature_dim), np.float32)
        y = np.empty(len(rows), np.float32)
        for r, (label, idx, val) in enumerate(rows):
            x[r, idx] = val
            y[r] = label
        yield x, y
