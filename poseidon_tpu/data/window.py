"""WINDOW_DATA: R-CNN-style detection-window sampling.

Parity with ``src/caffe/layers/window_data_layer.cpp``: the window file lists
images with candidate boxes ('# idx / path / C H W / num / class overlap x1 y1
x2 y2'); boxes with overlap >= fg_threshold are foreground, < bg_threshold are
background (label forced to 0). A batch samples fg_fraction foreground
windows, crops each box plus ``context_pad``, and warps it to crop_size x
crop_size ("warp" mode; "square" takes the tightest square first).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..proto.messages import LayerParameter


@dataclass
class Window:
    image_index: int
    label: int
    overlap: float
    x1: int
    y1: int
    x2: int
    y2: int


def parse_window_file(path: str, fg_threshold: float, bg_threshold: float):
    images: List[Tuple[str, Tuple[int, int, int]]] = []
    fg: List[Window] = []
    bg: List[Window] = []
    with open(path) as f:
        tokens = f.read().split()
    i = 0
    while i < len(tokens):
        if tokens[i] != "#":
            raise ValueError(f"{path}: expected '#', got {tokens[i]!r}")
        img_index = int(tokens[i + 1])
        img_path = tokens[i + 2]
        c, h, w = (int(tokens[i + 3]), int(tokens[i + 4]), int(tokens[i + 5]))
        num_windows = int(tokens[i + 6])
        i += 7
        if img_index != len(images):
            raise ValueError(f"{path}: non-sequential image index {img_index}")
        images.append((img_path, (c, h, w)))
        for _ in range(num_windows):
            label, overlap = int(tokens[i]), float(tokens[i + 1])
            x1, y1, x2, y2 = (int(tokens[i + 2]), int(tokens[i + 3]),
                              int(tokens[i + 4]), int(tokens[i + 5]))
            i += 6
            win = Window(img_index, label, overlap, x1, y1, x2, y2)
            if overlap >= fg_threshold:
                if label <= 0:
                    raise ValueError(f"{path}: foreground window with "
                                     f"label {label}")
                fg.append(win)
            elif overlap < bg_threshold:
                win.label = 0
                win.overlap = 0.0
                bg.append(win)
    return images, fg, bg


class WindowDataSource:
    """Batch sampler for WINDOW_DATA layers. Not index-addressable like other
    sources — batches are stochastic fg/bg mixes, matching the reference."""

    MAX_CACHED_IMAGES = 64  # the reference decodes per window by default

    def __init__(self, lp: LayerParameter, phase: str, seed: int = 0):
        from .pipeline import _effective_transform
        wp = lp.window_data_param
        self.param = wp
        self.phase = phase
        tp = _effective_transform(lp)
        self.crop_size = tp.crop_size
        if not self.crop_size:
            raise ValueError(f"layer {lp.name!r}: WINDOW_DATA needs crop_size")
        self.mirror = tp.mirror
        self.scale = tp.scale
        self.mean_values = np.asarray(tp.mean_value, np.float32) \
            if tp.mean_value else None
        self.mean_patch = None
        if tp.mean_file:
            from ..proto.wire import read_blob_file
            mean = read_blob_file(tp.mean_file)[0]  # (C, H, W)
            # the reference indexes the mean at its center crop
            oh = (mean.shape[1] - self.crop_size) // 2
            ow = (mean.shape[2] - self.crop_size) // 2
            if oh < 0 or ow < 0:
                raise ValueError(f"mean_file smaller than crop_size")
            self.mean_patch = mean[:, oh:oh + self.crop_size,
                                   ow:ow + self.crop_size]
        self.images, self.fg, self.bg = parse_window_file(
            wp.source, wp.fg_threshold, wp.bg_threshold)
        if not self.fg or not self.bg:
            raise ValueError(f"{wp.source}: need both fg and bg windows")
        self.rng = np.random.RandomState(seed)
        from collections import OrderedDict
        self._img_cache: "OrderedDict[int, np.ndarray]" = OrderedDict()
        first = self._load_image(0)
        self.record_shape = (first.shape[0], self.crop_size, self.crop_size)

    def _load_image(self, index: int) -> np.ndarray:
        if index in self._img_cache:
            self._img_cache.move_to_end(index)
            return self._img_cache[index]
        from PIL import Image
        path, (c, h, w) = self.images[index]
        img = Image.open(path).convert("RGB" if c == 3 else "L")
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        elif c == 3:
            arr = arr[:, :, ::-1]  # BGR
        chw = np.ascontiguousarray(arr.transpose(2, 0, 1))
        self._img_cache[index] = chw
        if len(self._img_cache) > self.MAX_CACHED_IMAGES:
            self._img_cache.popitem(last=False)
        return chw

    def _crop_warp(self, win: Window) -> np.ndarray:
        img = self._load_image(win.image_index)
        c, h, w = img.shape
        pad = self.param.context_pad
        x1, y1, x2, y2 = win.x1 - pad, win.y1 - pad, win.x2 + pad, win.y2 + pad
        if self.param.crop_mode == "square":
            cx, cy = (x1 + x2) / 2.0, (y1 + y2) / 2.0
            half = max(x2 - x1, y2 - y1) / 2.0
            x1, x2 = int(cx - half), int(cx + half)
            y1, y2 = int(cy - half), int(cy + half)
        x1c, y1c = max(x1, 0), max(y1, 0)
        x2c, y2c = min(x2, w - 1), min(y2, h - 1)
        patch = img[:, y1c:y2c + 1, x1c:x2c + 1]
        # warp with simple nearest-neighbor (the reference uses cv::resize)
        cs = self.crop_size
        hh, ww = patch.shape[1], patch.shape[2]
        if hh == 0 or ww == 0:
            return np.zeros((c, cs, cs), np.float32)
        yi = np.clip((np.arange(cs) * hh / cs).astype(int), 0, hh - 1)
        xi = np.clip((np.arange(cs) * ww / cs).astype(int), 0, ww - 1)
        return patch[:, yi[:, None], xi[None, :]].astype(np.float32)

    def batch(self, batch_size: int):
        n_fg = int(round(batch_size * self.param.fg_fraction))
        data = np.empty((batch_size,) + self.record_shape, np.float32)
        labels = np.empty((batch_size,), np.int32)
        for i in range(batch_size):
            pool = self.fg if i < n_fg else self.bg
            win = pool[self.rng.randint(len(pool))]
            patch = self._crop_warp(win)
            if self.mean_patch is not None:
                patch = patch - self.mean_patch
            elif self.mean_values is not None:
                patch = patch - self.mean_values.reshape(-1, 1, 1)
            if self.scale != 1.0:
                patch = patch * self.scale
            if self.mirror and self.phase == "TRAIN" and self.rng.randint(2):
                patch = patch[:, :, ::-1]
            data[i] = patch
            labels[i] = win.label
        return data, labels
