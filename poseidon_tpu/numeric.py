"""Global numeric policy (the TPU analog of Caffe's Dtype template parameter).

Parameters and optimizer state stay float32. Forward/backward matmul and conv
inputs are cast to ``compute_dtype`` (bfloat16 for TPU perf configs; the MXU
accumulates bf16 products in f32 internally) and produce compute-dtype
activations — forcing f32 outputs via preferred_element_type breaks conv
transposes under autodiff, so it is used only where autodiff never looks:
custom_vjp backward dots (SFB gradient reconstruction) and softmax/online-
softmax statistics, which are always f32 (``accum_dtype``). Set compute dtype
to float32 (the default) for Caffe-parity numerics; matmul precision is then
forced to HIGHEST (see ``matmul_precision``).

This module owns the jax dependency; ``config`` re-exports everything here
lazily so the socket-tier processes (async-SSP workers, the fault proxy)
can import ``poseidon_tpu`` without paying the jax import.
"""

from __future__ import annotations

from contextlib import contextmanager
from dataclasses import dataclass

import jax.numpy as jnp


@dataclass
class Policy:
    param_dtype: object = jnp.float32
    compute_dtype: object = jnp.float32  # flipped to bfloat16 by perf configs
    accum_dtype: object = jnp.float32
    # Internal activation layout — a GRAPH-level choice, not a per-op one:
    # core/net.py reads this at Net construction (overridable per net via
    # Net(conv_layout=...)) and plans the WHOLE graph in that layout —
    # "NHWC" runs every conv/pool/LRN/elementwise/concat natively
    # channels-last (TPU-preferred) and converts only at genuine
    # boundaries (FC flatten, blob export). The external/prototxt contract
    # stays NCHW: logical shapes, params, grads and checkpoints are always
    # canonical, so snapshots are layout-portable. Ops take explicit
    # layout arguments; nothing reads this field at trace time. (The old
    # per-op transpose shim this replaces lost 1.9x: its boundary pairs
    # did not cancel across pool/LRN/concat seams.)
    conv_layout: str = "NCHW"
    # "auto" resolves per-backend at Net construction (resolve_conv_layout):
    # explicit "nchw"/"nhwc" always win.
    # Space-to-depth stem transform: rewrite few-channel strided convs
    # (AlexNet/GoogLeNet conv1: 3 input channels use 3/128 MXU lanes) as an
    # exact stride-1 conv over s*s-times more channels. Mathematically
    # exact up to float summation order; off by default so golden-value
    # tests compare the direct formulation.
    conv_s2d: bool = False
    # Conv lowering strategy — per-LAYER, not global (the Caffe con Troll
    # result: measured per-layer strategy choice is worth 3-4x in the
    # small-filter regime). "" = legacy (conv_s2d decides), "auto" =
    # measure direct/im2col/s2d per conv layer at Net construction with
    # short micro-runs and persist the winner keyed by (layer shape,
    # backend, device kind) — ops/conv_tune.py; a concrete value forces
    # one strategy net-wide. Net(conv_strategy=...) overrides per net.
    conv_strategy: str = ""


# --bf16 accuracy guardrail (the documented tolerance the LeNet smoke in
# tests/test_kernels.py pins): after BF16_SMOKE_ITERS LeNet steps on
# identical data, the mean of the last 5 bf16 losses must sit within
# BF16_SMOKE_RTOL (relative) + BF16_SMOKE_ATOL (absolute) of the f32 run's.
# Parameters/optimizer state/softmax statistics stay f32 under the bf16
# policy, so the trajectories track closely — drift beyond this band means
# a kernel is accumulating below f32 somewhere it must not.
BF16_SMOKE_ITERS = 30
BF16_SMOKE_RTOL = 0.10
BF16_SMOKE_ATOL = 0.05


def resolve_conv_layout(layout: str, backend: str = None,
                        consult_plan: bool = True) -> str:
    """Resolve a conv_layout choice ("NCHW" | "NHWC" | "auto") against the
    backend actually running the net.

    "auto" first consults the active :mod:`runtime.tuned_plan` resolution:
    when a measured TunedPlan is loaded for this run, its conv_layout
    winner IS the auto answer — the per-backend table below became one
    measured row of the plan (ROADMAP item 5). Without a plan (or with
    ``consult_plan=False`` — the tune search uses this to build the
    default arm) auto falls back to the built-in table:

    - **tpu**: NCHW. The NHWC plan wins the HLO-transpose count (exactly
      the fc-boundary pair) but MEASURED 0.53x on the real v5e
      (``nhwc_speedup`` in BENCH_r05) — the TPU compiler's own layout
      assignment beats our forced channels-last plan for these nets, so
      auto stays NCHW until a measured plan shows >= 1.0.
    - **gpu**: NHWC (tensor-core native conv layout).
    - **cpu** (and anything unknown): NCHW — the Caffe-parity default the
      golden-value suites run under.

    Explicit "NCHW"/"NHWC" pass through untouched (case-insensitive)."""
    lay = (layout or "NCHW").upper()
    if lay != "AUTO":
        return lay
    if consult_plan:
        from .runtime.tuned_plan import active_plan_value
        measured = active_plan_value("conv_layout")
        if measured:
            return str(measured).upper()
    if backend is None:
        import jax
        backend = jax.default_backend()
    return "NHWC" if backend == "gpu" else "NCHW"


_policy = Policy()


def policy() -> Policy:
    return _policy


def matmul_precision():
    """float32 compute means Caffe-parity numerics: force exact f32 passes.
    bfloat16 compute means MXU-native: let XLA use its fast default."""
    import jax.lax
    if _policy.compute_dtype == jnp.float32:
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT


def set_policy(**kwargs) -> None:
    for k, v in kwargs.items():
        if not hasattr(_policy, k):
            raise AttributeError(k)
        setattr(_policy, k, v)


def set_perf_policy(**overrides) -> None:
    """THE bf16 perf config, in one place (bench.py and ``train --bf16``
    both route here): MXU-native bfloat16 compute plus the space-to-depth
    stem rewrite — conv1's 3 input channels use 3/128 MXU lanes, and the
    rewrite is exact up to float summation order, so it rides every perf
    run by default. Caffe-parity (f32) runs never come through here, so
    golden-value comparisons keep the direct conv1 formulation.

    This IS the documented ``--bf16`` training path: params, optimizer
    state and softmax/online-softmax statistics stay f32; only
    matmul/conv inputs and activations drop to bfloat16 (the MXU
    accumulates bf16 products in f32 internally). Its accuracy guardrail
    is the BF16_SMOKE_* tolerance band above, pinned by the LeNet
    bf16-vs-f32 loss-trajectory smoke in tests/test_kernels.py."""
    cfg = dict(compute_dtype=jnp.bfloat16, conv_s2d=True)
    cfg.update(overrides)
    set_policy(**cfg)


@contextmanager
def policy_scope(**kwargs):
    saved = {k: getattr(_policy, k) for k in kwargs}
    set_policy(**kwargs)
    try:
        yield
    finally:
        set_policy(**saved)
