"""KV-cached generation: cache path == full forward, and it actually works."""

import jax
import jax.numpy as jnp
import numpy as np

from poseidon_tpu.models.generate import generate
from poseidon_tpu.models.transformer import (
    TransformerConfig, forward, init_params, lm_loss, transformer_mults)
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.solvers.updates import init_state, make_update_fn

from conftest import pattern_batch

CFG = TransformerConfig(vocab_size=16, d_model=32, n_heads=2, n_layers=2,
                        d_ff=64, max_seq=48)


def test_cached_decode_matches_full_forward():
    """Each decode tick's logits must equal re-running the uncached
    forward() on the growing sequence — the cache is a pure optimization."""
    params = init_params(CFG, jax.random.PRNGKey(0))
    rs = np.random.RandomState(1)
    prompt = jnp.asarray(rs.randint(0, CFG.vocab_size, size=(2, 5),
                                    dtype=np.int32))
    max_new = 6
    toks, logits = generate(params, CFG, prompt, max_new)

    seq = np.asarray(prompt)
    for t in range(max_new):
        ref = np.asarray(forward(params, CFG, jnp.asarray(seq))[:, -1])
        np.testing.assert_allclose(np.asarray(logits[:, t]), ref,
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"step {t}")
        assert np.array_equal(np.asarray(toks[:, t]), ref.argmax(-1)), t
        seq = np.concatenate([seq, np.asarray(toks[:, t:t + 1])], axis=1)


def test_overfit_model_generates_the_pattern():
    """Train on t[i+1] = (3 t[i] + 1) mod V until near-memorized, then
    greedy decoding must continue the pattern exactly."""
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", momentum=0.9)
    params = init_params(CFG, jax.random.PRNGKey(2))
    upd = make_update_fn(sp, transformer_mults(params))
    state = init_state(params)
    rs = np.random.RandomState(3)

    def batch(b, s):
        return pattern_batch(rs, b, s, CFG.vocab_size)

    @jax.jit
    def step(p, st, tokens, targets):
        loss, grads = jax.value_and_grad(
            lambda q: lm_loss(forward(q, CFG, tokens), targets))(p)
        p, st = upd(p, grads, st)
        return p, st, loss

    loss = None
    for _ in range(150):
        tokens, targets = batch(8, 32)
        params, state, loss = step(params, state, tokens, targets)
    assert float(loss) < 0.1, float(loss)

    start = np.array([[4], [11]], np.int32)
    want = []
    cur = start
    for _ in range(10):
        cur = (cur * 3 + 1) % CFG.vocab_size
        want.append(cur)
    want = np.concatenate(want, axis=1)
    toks, _ = generate(params, CFG, jnp.asarray(start), 10)
    np.testing.assert_array_equal(np.asarray(toks), want)


def test_sampling_temperature_zero_equals_greedy_and_sampling_varies():
    params = init_params(CFG, jax.random.PRNGKey(4))
    rs = np.random.RandomState(5)
    prompt = jnp.asarray(rs.randint(0, CFG.vocab_size, size=(1, 4),
                                    dtype=np.int32))
    t0, _ = generate(params, CFG, prompt, 8)
    t0b, _ = generate(params, CFG, prompt, 8)
    np.testing.assert_array_equal(np.asarray(t0), np.asarray(t0b))
    s1, _ = generate(params, CFG, prompt, 8, temperature=2.0,
                     rng=jax.random.PRNGKey(6))
    s2, _ = generate(params, CFG, prompt, 8, temperature=2.0,
                     rng=jax.random.PRNGKey(7))
    assert not np.array_equal(np.asarray(s1), np.asarray(s2))


def test_moe_cached_decode_matches_moe_forward():
    """MoE decode: each tick's logits equal the uncached moe_forward on the
    growing sequence (same routing, same capacity semantics per call)."""
    from poseidon_tpu.models.moe import MoEConfig, init_moe_params, moe_forward
    # dropless on both sides: decode forces capacity = per-call tokens,
    # and the reference gets an explicit capacity covering the full run
    mcfg = MoEConfig(base=CFG, n_experts=4, capacity=64, aux_weight=0.0)
    params = init_moe_params(mcfg, jax.random.PRNGKey(8))
    rs = np.random.RandomState(9)
    prompt = jnp.asarray(rs.randint(0, CFG.vocab_size, size=(2, 5),
                                    dtype=np.int32))
    max_new = 5
    toks, logits = generate(params, mcfg, prompt, max_new)

    seq = np.asarray(prompt)
    for t in range(max_new):
        ref_logits, _ = moe_forward(params, mcfg, jnp.asarray(seq))
        ref = np.asarray(ref_logits[:, -1])
        np.testing.assert_allclose(np.asarray(logits[:, t]), ref,
                                   rtol=1e-4, atol=1e-5,
                                   err_msg=f"step {t}")
        seq = np.concatenate([seq, np.asarray(toks[:, t:t + 1])], axis=1)
