"""Transformer LM on a 2-D (data x seq) mesh: DP and SP compose."""

import jax
import numpy as np
import pytest

from poseidon_tpu.models.transformer import (
    TransformerConfig, build_dp_sp_train_step, forward, init_params, lm_loss)
from poseidon_tpu.parallel.mesh import make_mesh
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.solvers.updates import init_state

from conftest import pattern_batch

CFG = TransformerConfig(vocab_size=32, d_model=64, n_heads=4, n_layers=2,
                        d_ff=128, max_seq=64)
B, S = 4, 32  # global batch/sequence; mesh (data=2, seq=4)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(axes=("data", "seq"), shape=(2, 4))


def _pattern_batch(rs, b, s):
    return pattern_batch(rs, b, s, CFG.vocab_size)


def test_forward_shapes_and_causality():
    params = init_params(CFG, jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    tokens, _ = _pattern_batch(rs, 2, 16)
    logits = forward(params, CFG, tokens)
    assert logits.shape == (2, 16, CFG.vocab_size)
    # causality: changing a future token must not affect earlier logits
    tokens2 = tokens.at[:, 10].set((tokens[:, 10] + 1) % CFG.vocab_size)
    logits2 = forward(params, CFG, tokens2)
    np.testing.assert_allclose(np.asarray(logits[:, :10]),
                               np.asarray(logits2[:, :10]), rtol=1e-5)
    assert not np.allclose(np.asarray(logits[:, 10:]),
                           np.asarray(logits2[:, 10:]))


def test_remat_gradients_match():
    """jax.checkpoint per block must not change values or gradients."""
    import dataclasses
    params = init_params(CFG, jax.random.PRNGKey(0))
    rs = np.random.RandomState(1)
    tokens, targets = _pattern_batch(rs, 2, 16)
    cfg_r = dataclasses.replace(CFG, remat=True)

    def loss(p, cfg):
        return lm_loss(forward(p, cfg, tokens), targets)

    l0, g0 = jax.value_and_grad(loss)(params, CFG)
    l1, g1 = jax.value_and_grad(loss)(params, cfg_r)
    assert float(l0) == pytest.approx(float(l1), rel=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-7), g0, g1)


def test_dp_sp_training_converges(mesh):
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", momentum=0.9)
    params = init_params(CFG, jax.random.PRNGKey(0))
    state = init_state(params)
    step = build_dp_sp_train_step(CFG, sp, mesh)
    rs = np.random.RandomState(0)
    first = last = None
    for i in range(60):
        tokens, targets = _pattern_batch(rs, B, S)
        params, state, m = step(params, state, tokens, targets,
                                jax.random.PRNGKey(i))
        if first is None:
            first = float(m["loss"])
        last = float(m["loss"])
    assert first > 3.0  # ~ln(32)
    assert last < 0.5, f"LM did not learn the pattern: {first} -> {last}"


def test_dp_sp_matches_single_device_gradstep(mesh):
    sp = SolverParameter(base_lr=0.05, lr_policy="fixed")
    params = init_params(CFG, jax.random.PRNGKey(1))
    rs = np.random.RandomState(1)
    tokens, targets = _pattern_batch(rs, B, S)

    step = build_dp_sp_train_step(CFG, sp, mesh, donate=False)
    p_sharded, _, m = step(params, init_state(params), tokens, targets,
                           jax.random.PRNGKey(0))

    # single-device reference: full-batch mean loss
    def loss_fn(p):
        return lm_loss(forward(p, CFG, tokens), targets)

    from poseidon_tpu.models.transformer import transformer_mults
    from poseidon_tpu.solvers.updates import make_update_fn
    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd = make_update_fn(sp, transformer_mults(params))
    p_ref, _ = upd(params, grads, init_state(params))

    assert float(m["loss"]) == pytest.approx(float(loss), rel=1e-4)
    for lname in p_ref:
        for k in p_ref[lname]:
            np.testing.assert_allclose(
                np.asarray(p_sharded[lname][k]), np.asarray(p_ref[lname][k]),
                rtol=2e-3, atol=2e-5, err_msg=f"{lname}/{k}")


def test_dp_tp_matches_single_device_gradstep():
    """Megatron-style tensor parallelism over a (data=2, model=4) mesh:
    one optimizer step must match the single-device reference — attention
    heads and FFN columns are split across ranks, partial outputs psum'd,
    replicated-param grads psum'd, so the math is a re-layout, not an
    approximation."""
    from poseidon_tpu.models.transformer import (
        build_dp_tp_train_step, from_tp_layout, to_tp_layout,
        transformer_mults)
    from poseidon_tpu.solvers.updates import make_update_fn

    sp = SolverParameter(base_lr=0.05, lr_policy="fixed")
    params = init_params(CFG, jax.random.PRNGKey(1))
    rs = np.random.RandomState(2)
    tokens, targets = _pattern_batch(rs, B, S)

    mesh_tp = make_mesh(axes=("data", "model"), shape=(2, 4))
    tp_params = to_tp_layout(params, CFG)
    step = build_dp_tp_train_step(CFG, sp, mesh_tp, tp_params, donate=False)
    p_tp, _, m = step(tp_params, init_state(tp_params), tokens, targets,
                      jax.random.PRNGKey(0))
    p_tp = from_tp_layout(p_tp, CFG)

    def loss_fn(p):
        return lm_loss(forward(p, CFG, tokens), targets)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd = make_update_fn(sp, transformer_mults(params))
    p_ref, _ = upd(params, grads, init_state(params))

    assert float(m["loss"]) == pytest.approx(float(loss), rel=1e-4)
    for lname in p_ref:
        for k in p_ref[lname]:
            np.testing.assert_allclose(
                np.asarray(p_tp[lname][k]), np.asarray(p_ref[lname][k]),
                rtol=2e-3, atol=2e-5, err_msg=f"{lname}/{k}")


def test_tp_layout_roundtrip():
    from poseidon_tpu.models.transformer import from_tp_layout, to_tp_layout
    params = init_params(CFG, jax.random.PRNGKey(3))
    rt = from_tp_layout(to_tp_layout(params, CFG), CFG)
    for lname in params:
        for k in params[lname]:
            np.testing.assert_array_equal(np.asarray(params[lname][k]),
                                          np.asarray(rt[lname][k]))


def test_dp_pp_matches_single_device_gradstep():
    """GPipe-style pipeline over a (data=2, stage=4) mesh: the scheduled
    scan + ppermute ring must reproduce the single-device optimizer step —
    pipelining is a re-scheduling of the same math, not an approximation."""
    import dataclasses
    from poseidon_tpu.models.transformer import (
        build_dp_pp_train_step, from_pp_layout, to_pp_layout,
        transformer_mults)
    from poseidon_tpu.solvers.updates import make_update_fn

    cfg = dataclasses.replace(CFG, n_layers=4)
    sp = SolverParameter(base_lr=0.05, lr_policy="fixed")
    params = init_params(cfg, jax.random.PRNGKey(5))
    rs = np.random.RandomState(6)
    tokens, targets = _pattern_batch(rs, B, S)

    mesh_pp = make_mesh(axes=("data", "stage"), shape=(2, 4))
    pp_params = to_pp_layout(params, cfg)
    step = build_dp_pp_train_step(cfg, sp, mesh_pp, pp_params,
                                  microbatches=2, donate=False)
    p_pp, _, m = step(pp_params, init_state(pp_params), tokens, targets,
                      jax.random.PRNGKey(0))
    p_pp = from_pp_layout(p_pp, cfg)

    def loss_fn(p):
        return lm_loss(forward(p, cfg, tokens), targets)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd = make_update_fn(sp, transformer_mults(params))
    p_ref, _ = upd(params, grads, init_state(params))

    assert float(m["loss"]) == pytest.approx(float(loss), rel=1e-4)
    for lname in p_ref:
        for k in p_ref[lname]:
            np.testing.assert_allclose(
                np.asarray(p_pp[lname][k]), np.asarray(p_ref[lname][k]),
                rtol=2e-3, atol=2e-5, err_msg=f"{lname}/{k}")


def test_pp_layout_roundtrip():
    from poseidon_tpu.models.transformer import from_pp_layout, to_pp_layout
    params = init_params(CFG, jax.random.PRNGKey(7))
    rt = from_pp_layout(to_pp_layout(params, CFG), CFG)
    for lname in params:
        for k in params[lname]:
            np.testing.assert_array_equal(np.asarray(params[lname][k]),
                                          np.asarray(rt[lname][k]))


def test_dp_pp_converges():
    """The pipelined step must actually train (60 iters on the pattern
    task), exercising the reversed-ring backward repeatedly."""
    import dataclasses
    from poseidon_tpu.models.transformer import (
        build_dp_pp_train_step, to_pp_layout)

    cfg = dataclasses.replace(CFG, n_layers=4)
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", momentum=0.9)
    mesh_pp = make_mesh(axes=("data", "stage"), shape=(2, 4))
    p = to_pp_layout(init_params(cfg, jax.random.PRNGKey(8)), cfg)
    step = build_dp_pp_train_step(cfg, sp, mesh_pp, p, microbatches=2,
                                  donate=False)
    s = init_state(p)
    rs = np.random.RandomState(9)
    tokens, targets = _pattern_batch(rs, B, S)
    first = last = None
    for it in range(60):
        p, s, m = step(p, s, tokens, targets, jax.random.PRNGKey(it))
        last = float(m["loss"])
        first = first if first is not None else last
    assert last < 0.1 * first, (first, last)


def test_dp_pp_tp_3d_matches_single_device_gradstep():
    """3-D parallelism (data=2 x stage=2 x model=2): pipeline microbatch
    scheduling composed with tensor-parallel blocks (f/g collectives over
    "model" inside each pipeline tick) must still reproduce the
    single-device optimizer step exactly."""
    import dataclasses
    from poseidon_tpu.models.transformer import (
        build_dp_pp_train_step, from_pp_layout, from_tp_layout,
        to_pp_layout, to_tp_layout, transformer_mults)
    from poseidon_tpu.solvers.updates import make_update_fn

    cfg = dataclasses.replace(CFG, n_layers=2, n_heads=2)
    sp = SolverParameter(base_lr=0.05, lr_policy="fixed")
    params = init_params(cfg, jax.random.PRNGKey(10))
    rs = np.random.RandomState(11)
    tokens, targets = _pattern_batch(rs, B, S)

    mesh3d = make_mesh(axes=("data", "stage", "model"), shape=(2, 2, 2))
    p3d = to_pp_layout(to_tp_layout(params, cfg), cfg)
    step = build_dp_pp_train_step(cfg, sp, mesh3d, p3d, microbatches=2,
                                  tp_axis="model", donate=False)
    p_out, _, m = step(p3d, init_state(p3d), tokens, targets,
                       jax.random.PRNGKey(0))
    p_out = from_tp_layout(from_pp_layout(p_out, cfg), cfg)

    def loss_fn(p):
        return lm_loss(forward(p, cfg, tokens), targets)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd = make_update_fn(sp, transformer_mults(params))
    p_ref, _ = upd(params, grads, init_state(params))

    assert float(m["loss"]) == pytest.approx(float(loss), rel=1e-4)
    for lname in p_ref:
        for k in p_ref[lname]:
            np.testing.assert_allclose(
                np.asarray(p_out[lname][k]), np.asarray(p_ref[lname][k]),
                rtol=2e-3, atol=2e-5, err_msg=f"{lname}/{k}")


def test_dp_sp_tp_3d_matches_single_device_gradstep():
    """The long-context 3-D combo (data=2 x seq=2 x model=2): ring
    attention over sequence shards composed with tensor-parallel heads
    must still reproduce the single-device optimizer step."""
    import dataclasses
    from poseidon_tpu.models.transformer import (
        build_dp_tp_train_step, from_tp_layout, to_tp_layout,
        transformer_mults)
    from poseidon_tpu.solvers.updates import make_update_fn

    cfg = dataclasses.replace(CFG, n_heads=2)
    sp = SolverParameter(base_lr=0.05, lr_policy="fixed")
    params = init_params(cfg, jax.random.PRNGKey(12))
    rs = np.random.RandomState(13)
    tokens, targets = _pattern_batch(rs, B, S)

    mesh3d = make_mesh(axes=("data", "seq", "model"), shape=(2, 2, 2))
    tp_params = to_tp_layout(params, cfg)
    step = build_dp_tp_train_step(cfg, sp, mesh3d, tp_params,
                                  seq_axis="seq", donate=False)
    p_out, _, m = step(tp_params, init_state(tp_params), tokens, targets,
                       jax.random.PRNGKey(0))
    p_out = from_tp_layout(p_out, cfg)

    def loss_fn(p):
        return lm_loss(forward(p, cfg, tokens), targets)

    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd = make_update_fn(sp, transformer_mults(params))
    p_ref, _ = upd(params, grads, init_state(params))

    assert float(m["loss"]) == pytest.approx(float(loss), rel=1e-4)
    for lname in p_ref:
        for k in p_ref[lname]:
            np.testing.assert_allclose(
                np.asarray(p_out[lname][k]), np.asarray(p_ref[lname][k]),
                rtol=2e-3, atol=2e-5, err_msg=f"{lname}/{k}")
