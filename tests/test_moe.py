"""MoE transformer: expert parallelism over a (data x expert) mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.models.moe import (
    MoEConfig, build_dp_ep_train_step, init_moe_params, moe_ffn, moe_forward)
from poseidon_tpu.models.transformer import (
    TransformerConfig, lm_loss, transformer_mults)
from poseidon_tpu.parallel.mesh import make_mesh
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.solvers.updates import init_state, make_update_fn

from conftest import pattern_batch

BASE = TransformerConfig(vocab_size=32, d_model=32, n_heads=2, n_layers=2,
                         d_ff=64, max_seq=32)
CFG = MoEConfig(base=BASE, n_experts=8, capacity=16, aux_weight=0.0)
B, S = 8, 16  # global batch/seq; mesh (data=2, expert=4) -> 16 tokens/device


def _pattern_batch(rs, b, s):
    return pattern_batch(rs, b, s, BASE.vocab_size)


def test_dp_ep_matches_single_device_gradstep():
    """With capacity high enough that nothing drops, expert-parallel
    routing over all_to_all must equal the all-experts-local reference:
    the exchange is a relayout of the same token->expert assignment."""
    sp = SolverParameter(base_lr=0.05, lr_policy="fixed")
    params = init_moe_params(CFG, jax.random.PRNGKey(1))
    rs = np.random.RandomState(2)
    tokens, targets = _pattern_batch(rs, B, S)

    mesh = make_mesh(axes=("data", "expert"), shape=(2, 4))
    step = build_dp_ep_train_step(CFG, sp, mesh, params, donate=False)
    p_ep, _, m = step(params, init_state(params), tokens, targets,
                      jax.random.PRNGKey(0))

    # reference: same math, all experts local, capacity covering the full
    # global batch (neither side drops, so capacities need not match)
    cfg_ref = dataclasses.replace(CFG, capacity=B * S)

    def loss_fn(p):
        logits, aux = moe_forward(p, cfg_ref, tokens)
        return lm_loss(logits, targets) + aux

    loss, grads = jax.value_and_grad(loss_fn)(params)
    upd = make_update_fn(sp, transformer_mults(params))
    p_ref, _ = upd(params, grads, init_state(params))

    assert float(m["loss"]) == pytest.approx(float(loss), rel=1e-4)
    for lname in p_ref:
        for k in p_ref[lname]:
            np.testing.assert_allclose(
                np.asarray(p_ep[lname][k]), np.asarray(p_ref[lname][k]),
                rtol=2e-3, atol=2e-5, err_msg=f"{lname}/{k}")


def test_aux_loss_value_with_flat_router():
    """With wg = 0 the gates are uniform (1/E) and every argmax lands on
    expert 0, so frac = (1,0,..), mean_gate = 1/E and the switch aux loss
    reduces to exactly aux_weight per MoE layer."""
    cfg = dataclasses.replace(CFG, aux_weight=0.01)
    params = init_moe_params(cfg, jax.random.PRNGKey(3))
    for i in range(BASE.n_layers):
        params[f"block{i}"]["wg"] = jnp.zeros_like(params[f"block{i}"]["wg"])
    rs = np.random.RandomState(4)
    tokens, _ = _pattern_batch(rs, 2, 8)
    _, aux = moe_forward(params, cfg, tokens)
    assert float(aux) == pytest.approx(0.01 * BASE.n_layers, rel=1e-5)


def test_capacity_drops_tokens():
    """Tokens beyond an expert's capacity contribute zero output (they ride
    the residual only) — the fixed-shape analog of a dispatch queue."""
    rs = np.random.RandomState(5)
    t, d, e, cap = 6, 8, 4, 2
    x = jnp.asarray(np.abs(rs.randn(t, d)).astype(np.float32))
    wg = jnp.zeros((e, d), jnp.float32).at[0].set(10.0)  # all -> expert 0
    w1e = jnp.asarray(rs.randn(e, 16, d).astype(np.float32))
    w2e = jnp.asarray(rs.randn(e, d, 16).astype(np.float32))
    cfg = MoEConfig(base=BASE, n_experts=e, capacity=cap, aux_weight=0.0)
    y, _ = moe_ffn(x, wg, w1e, w2e, cfg)
    y = np.asarray(y)
    assert np.abs(y[:cap]).sum() > 0
    np.testing.assert_array_equal(y[cap:], np.zeros_like(y[cap:]))


def test_dp_ep_converges():
    """The expert-parallel step must actually train (the router gradient
    flows through the gate scale, the expert grads through all_to_all)."""
    cfg = dataclasses.replace(CFG, aux_weight=0.01)
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", momentum=0.9)
    mesh = make_mesh(axes=("data", "expert"), shape=(2, 4))
    p = init_moe_params(cfg, jax.random.PRNGKey(6))
    step = build_dp_ep_train_step(cfg, sp, mesh, p, donate=False)
    s = init_state(p)
    rs = np.random.RandomState(7)
    tokens, targets = _pattern_batch(rs, B, S)
    first = last = None
    for it in range(60):
        p, s, m = step(p, s, tokens, targets, jax.random.PRNGKey(it))
        last = float(m["loss"])
        first = first if first is not None else last
    assert last < 0.3 * first, (first, last)


def test_moe_remat_gradients_match():
    """cfg.base.remat must be honored by moe_forward (checkpointed blocks)
    without changing values or gradients."""
    cfg_r = dataclasses.replace(
        CFG, base=dataclasses.replace(BASE, remat=True), aux_weight=0.01)
    cfg_n = dataclasses.replace(CFG, aux_weight=0.01)
    params = init_moe_params(cfg_n, jax.random.PRNGKey(8))
    rs = np.random.RandomState(9)
    tokens, targets = _pattern_batch(rs, 2, 8)

    def loss(p, cfg):
        logits, aux = moe_forward(p, cfg, tokens)
        return lm_loss(logits, targets) + aux

    l0, g0 = jax.value_and_grad(loss)(params, cfg_n)
    l1, g1 = jax.value_and_grad(loss)(params, cfg_r)
    assert float(l0) == pytest.approx(float(l1), rel=1e-6)
    for lname in g0:
        for k in g0[lname]:
            np.testing.assert_allclose(
                np.asarray(g0[lname][k]), np.asarray(g1[lname][k]),
                rtol=1e-5, atol=1e-7, err_msg=f"{lname}/{k}")
