"""Observability spine tests (ISSUE 7): per-layer attribution, span
timeline, and the live metrics endpoint.

- the TF-free xplane wire parser round-trips a canned XSpace built with
  the shared varint helpers;
- a canned trace fixture attributes to a stable table: named rows, the
  honest residual row, self-time nesting, the FLOPs join;
- ``jax.named_scope`` layer names survive jit+compile on CPU for LeNet
  forward AND backward (the whole join hangs on this);
- the span recorder's dump is valid Chrome trace-event JSON, the engine's
  --trace_out timeline carries dispatch/hard-sync/snapshot/prefetch
  spans, and a real 2-worker async exchange records push/pull/gate/admit;
- enabling spans costs <2% of a CPU LeNet step, and trace capture stays
  AFTER the timed loop (the bench.py:718 discipline, now in
  runtime/attribution.measure_then_trace);
- --metrics_port serves the live registry mid-train; stats.yaml lands
  atomically at every display boundary.
"""

import glob
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from poseidon_tpu.data.varint import write_varint
from poseidon_tpu.runtime import attribution as A
from poseidon_tpu.runtime.metrics import MetricsServer, StatsRegistry
from poseidon_tpu.runtime.spans import SpanRecorder, recorder as global_rec


# --------------------------------------------------------------------------- #
# canned xplane: a tiny protobuf writer (wire format only, test-local)
# --------------------------------------------------------------------------- #

def _tag(out, fno, wt):
    write_varint(out, (fno << 3) | wt)


def _bytes_field(out, fno, payload: bytes):
    _tag(out, fno, 2)
    write_varint(out, len(payload))
    out.extend(payload)


def _varint_field(out, fno, v: int):
    _tag(out, fno, 0)
    write_varint(out, v)


def _map_entry(fno_key: int, key: int, val: bytes) -> bytes:
    out = bytearray()
    _varint_field(out, 1, key)
    _bytes_field(out, 2, val)
    return bytes(out)


def _canned_xspace() -> bytes:
    """One plane, one line, two events: metadata-named 'dot.7' with an
    hlo_op stat, and 'misc.1' with no stat (residual candidate)."""
    emeta1 = bytearray()
    _varint_field(emeta1, 1, 7)
    _bytes_field(emeta1, 2, b"dot.7")
    emeta2 = bytearray()
    _varint_field(emeta2, 1, 8)
    _bytes_field(emeta2, 2, b"misc.1")
    smeta = bytearray()
    _varint_field(smeta, 1, 3)
    _bytes_field(smeta, 2, b"hlo_op")

    stat = bytearray()                       # XStat: hlo_op = "dot.7"
    _varint_field(stat, 1, 3)
    _bytes_field(stat, 5, b"dot.7")

    ev1 = bytearray()                        # XEvent
    _varint_field(ev1, 1, 7)                 # metadata_id
    _varint_field(ev1, 2, 1_000_000)         # offset_ps
    _varint_field(ev1, 3, 2_500_000)         # duration_ps = 2.5 us
    _bytes_field(ev1, 4, bytes(stat))
    ev2 = bytearray()
    _varint_field(ev2, 1, 8)
    _varint_field(ev2, 2, 5_000_000)
    _varint_field(ev2, 3, 1_000_000)

    line = bytearray()                       # XLine
    _bytes_field(line, 2, b"thread-0")
    _varint_field(line, 3, 123)              # timestamp_ns
    _bytes_field(line, 4, bytes(ev1))
    _bytes_field(line, 4, bytes(ev2))

    plane = bytearray()                      # XPlane
    _bytes_field(plane, 2, b"/host:CPU")
    _bytes_field(plane, 3, bytes(line))
    _bytes_field(plane, 4, _map_entry(4, 7, bytes(emeta1)))
    _bytes_field(plane, 4, _map_entry(4, 8, bytes(emeta2)))
    _bytes_field(plane, 5, _map_entry(5, 3, bytes(smeta)))

    space = bytearray()                      # XSpace
    _bytes_field(space, 1, bytes(plane))
    return bytes(space)


def test_xplane_parser_roundtrips_canned_space():
    planes = A.parse_xspace(_canned_xspace())
    assert len(planes) == 1
    p = planes[0]
    assert p["name"] == "/host:CPU"
    (line,) = p["lines"]
    assert line["name"] == "thread-0"
    assert line["timestamp_ns"] == 123
    e1, e2 = line["events"]
    assert e1["name"] == "dot.7"
    assert e1["dur_ps"] == 2_500_000
    assert e1["offset_ps"] == 1_000_000
    assert e1["stats"] == {"hlo_op": "dot.7"}
    assert e2["name"] == "misc.1"
    assert e2["stats"] == {}


def test_load_trace_events_reads_canned_xplane(tmp_path):
    run = tmp_path / "plugins" / "profile" / "2026_01_01"
    run.mkdir(parents=True)
    (run / "host.xplane.pb").write_bytes(_canned_xspace())
    evs = A.load_trace_events(str(tmp_path))
    assert len(evs) == 2
    assert evs[0]["name"] == "dot.7"
    assert evs[0]["dur_us"] == pytest.approx(2.5)
    assert evs[0]["stats"]["hlo_op"] == "dot.7"


# --------------------------------------------------------------------------- #
# the canned-table contract
# --------------------------------------------------------------------------- #

def _ev(name, t0, dur, line="t0", hlo=True, plane="p"):
    return {"name": name, "t0_us": t0, "dur_us": dur, "plane": plane,
            "line": line, "stats": {"hlo_op": name} if hlo else {}}


def test_canned_trace_attributes_to_stable_table():
    scope_map = {"dot.1": ("conv1", "fwd"), "dot.2": ("conv1", "bwd"),
                 "fusion.1": ("ip1", "fwd")}
    events = [
        _ev("dot.1", 0, 100),
        _ev("dot.2", 200, 300),
        _ev("fusion.1", 600, 100),
        _ev("mystery.9", 800, 100),          # -> residual
        {"name": "python_noise", "t0_us": 0, "dur_us": 99999,
         "plane": "p", "line": "t9", "stats": {}},   # excluded entirely
    ]
    out = A.attribute(events, scope_map,
                      cost_table={"conv1": {"flops": 4e9, "bytes": 1e6,
                                            "intensity": 4000.0}},
                      peak_flops=1e12)
    by_name = {r["layer"]: r for r in out["rows"]}
    assert by_name["conv1"]["fwd_ms"] == pytest.approx(0.1)
    assert by_name["conv1"]["bwd_ms"] == pytest.approx(0.3)
    assert by_name["conv1"]["flops"] == 4e9
    assert by_name["conv1"]["mfu"] == pytest.approx(4e9 / 0.4e-3 / 1e12,
                                                    rel=1e-3)
    assert by_name["ip1"]["total_ms"] == pytest.approx(0.1)
    # residual row is honest: named + residual == total
    assert out["residual"]["total_ms"] == pytest.approx(0.1)
    assert out["total_ms"] == pytest.approx(0.6)
    assert out["coverage"] == pytest.approx(5 / 6, abs=1e-3)
    assert out["residual"]["top_ops"][0]["op"] == "mystery.9"
    # rows sorted by total desc -> top sinks
    assert out["top_sinks"][0] == "conv1"


def test_attribute_self_time_never_double_counts_nesting():
    """A while op containing its body ops on the same line is billed only
    its SELF time (flame-graph accounting)."""
    scope_map = {"while.1": ("pool1", "bwd"), "body.1": ("pool1", "bwd"),
                 "other.1": ("conv1", "fwd")}
    events = [
        _ev("while.1", 0, 1000),             # parent
        _ev("body.1", 100, 600),             # nested child
        _ev("other.1", 2000, 500),           # disjoint
    ]
    out = A.attribute(events, scope_map)
    assert out["total_ms"] == pytest.approx(1.5)  # 1000 + 500, not 1600+500
    by_name = {r["layer"]: r for r in out["rows"]}
    assert by_name["pool1"]["bwd_ms"] == pytest.approx(1.0)


def test_attribute_normalizes_decorated_device_event_names():
    """TPU device events sometimes decorate instruction names ('%fusion.3',
    an extra trailing '.<n>'); the join must strip and retry before
    consigning them to the residual row."""
    scope_map = {"fusion.3": ("conv1", "fwd")}
    events = [
        {"name": "%fusion.3", "t0_us": 0, "dur_us": 100,
         "plane": "/device:TPU:0", "line": "XLA Ops", "stats": {}},
        {"name": "fusion.3.7", "t0_us": 200, "dur_us": 100,
         "plane": "/device:TPU:0", "line": "XLA Ops", "stats": {}},
    ]
    out = A.attribute(events, scope_map)
    assert out["coverage"] == pytest.approx(1.0)
    assert out["rows"][0]["layer"] == "conv1"
    assert out["rows"][0]["fwd_ms"] == pytest.approx(0.2)


def test_attribute_ignores_device_module_and_step_lines():
    """TPU device planes carry whole-step 'XLA Modules'/'Steps' lines
    whose events span the entire dispatch; only the op line may feed the
    denominator, or coverage halves on perfectly-named programs."""
    scope_map = {"dot.1": ("conv1", "fwd")}
    events = [
        {"name": "dot.1", "t0_us": 0, "dur_us": 100,
         "plane": "/device:TPU:0", "line": "XLA Ops", "stats": {}},
        {"name": "unknown.9", "t0_us": 200, "dur_us": 50,
         "plane": "/device:TPU:0", "line": "XLA Ops", "stats": {}},
        {"name": "jit_train_step", "t0_us": 0, "dur_us": 10_000,
         "plane": "/device:TPU:0", "line": "XLA Modules", "stats": {}},
        {"name": "step 3", "t0_us": 0, "dur_us": 10_000,
         "plane": "/device:TPU:0", "line": "Steps", "stats": {}},
    ]
    out = A.attribute(events, scope_map)
    assert out["total_ms"] == pytest.approx(0.15)
    assert out["residual"]["total_ms"] == pytest.approx(0.05)
    assert out["coverage"] == pytest.approx(100 / 150, abs=1e-3)


def test_attribute_strips_tracer_overhead_per_event():
    scope_map = {"a.1": ("l1", "fwd"), "b.1": ("l2", "fwd")}
    events = [_ev("a.1", 0, 100), _ev("b.1", 200, 100)]
    out = A.attribute(events, scope_map, tracer_overhead_ms=0.1)
    # 0.1 ms across 2 events = 50 us each
    by_name = {r["layer"]: r for r in out["rows"]}
    assert by_name["l1"]["total_ms"] == pytest.approx(0.05)
    assert out["tracer_overhead_ms_stripped"] == pytest.approx(0.1)


def test_scope_of_peels_autodiff_wrappers_and_slashed_names():
    layers = {"conv1", "inception_3a/1x1"}
    assert A.scope_of("jit(f)/jit(main)/jvp(conv1)/dot", layers) == \
        ("conv1", "fwd")
    assert A.scope_of("jit(f)/transpose(jvp(conv1))/dot", layers) == \
        ("conv1", "bwd")
    assert A.scope_of("jit(f)/jvp(inception_3a)/1x1/conv", layers) == \
        ("inception_3a/1x1", "fwd")
    # what jax ACTUALLY emits for a slashed layer name: the wrapper opens
    # and closes in DIFFERENT '/'-components — per-component peeling used
    # to mangle this into 'jvp(inception_3a' + '1x1)' and every wrapped
    # GoogLeNet op fell into the residual row
    assert A.scope_of("jit(f)/jvp(inception_3a/1x1)/conv", layers) == \
        ("inception_3a/1x1", "fwd")
    assert A.scope_of("jit(f)/transpose(jvp(inception_3a/1x1))/conv",
                      layers) == ("inception_3a/1x1", "bwd")
    assert A.scope_of("jit(f)/arena_pack/concatenate", layers,
                      {"arena_pack"}) == ("arena_pack", "misc")
    assert A.scope_of("jit(f)/unrelated/op", layers) == (None, None)
    # a call frame whose function name collides with a layer must still
    # NOT attribute (jit(conv1) is the traced function, not the layer)
    assert A.scope_of("jit(conv1)/add", layers) == (None, None)


# --------------------------------------------------------------------------- #
# named scopes survive jit (LeNet fwd + bwd on CPU)
# --------------------------------------------------------------------------- #

def _lenet_net(batch=4):
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.models import zoo
    return Net(zoo.lenet(with_accuracy=False), "TRAIN",
               source_shapes=zoo.lenet_shapes(batch))


def test_named_scopes_survive_jit_lenet_fwd_bwd():
    import jax

    net = _lenet_net()
    params = net.init(jax.random.PRNGKey(0))
    inputs = {"data": np.zeros((4, 1, 28, 28), np.float32),
              "label": np.zeros((4,), np.int32)}

    def loss(p):
        return net.apply(p, inputs, train=True,
                         rng=jax.random.PRNGKey(1)).loss

    txt = jax.jit(jax.grad(loss)).lower(params).compile().as_text()
    smap = A.hlo_scope_map(txt, {layer.name for layer in net.layers})
    phases = {}
    for scope, phase in smap.values():
        phases.setdefault(scope, set()).add(phase)
    # every parameterized layer appears, forward AND backward
    for lname in ("conv1", "conv2", "ip1", "ip2"):
        assert lname in phases, f"{lname} missing from compiled metadata"
        assert "fwd" in phases[lname], f"{lname}: no forward ops"
        assert "bwd" in phases[lname], f"{lname}: no backward ops"


def test_real_cpu_trace_attributes_lenet(tmp_path):
    """End-to-end smoke on the REAL profiler: one traced LeNet grad step
    parses into a table whose named rows carry most of the op time."""
    import jax

    net = _lenet_net(8)
    params = net.init(jax.random.PRNGKey(0))
    inputs = {"data": np.random.RandomState(0).randn(
        8, 1, 28, 28).astype(np.float32),
        "label": np.zeros((8,), np.int32)}

    def loss(p):
        return net.apply(p, inputs, train=True,
                         rng=jax.random.PRNGKey(1)).loss

    compiled = jax.jit(jax.grad(loss)).lower(params).compile()

    def run():
        jax.block_until_ready(
            jax.tree_util.tree_leaves(compiled(params))[0])

    timing = A.measure_then_trace(run, str(tmp_path), iters=2)
    events = A.load_trace_events(str(tmp_path))
    if not events:
        pytest.skip("profiler produced no parseable trace on this box")
    smap = A.hlo_scope_map(compiled.as_text(),
                           {layer.name for layer in net.layers})
    out = A.attribute(
        events, smap, cost_table=A.layer_cost_table(net),
        tracer_overhead_ms=max(
            timing["traced_step_ms"] - timing["step_ms"], 0.0))
    assert out["total_ms"] > 0
    assert out["coverage"] > 0.5, (out["coverage"],
                                   out["residual"]["top_ops"])
    named = {r["layer"] for r in out["rows"]}
    assert "conv2" in named or "ip1" in named


def test_layer_cost_table_conv_and_fc_flops():
    net = _lenet_net(4)
    table = A.layer_cost_table(net)
    # conv1: 20 filters of 1x5x5 over 24x24 outputs, batch 4, x3 fwd+bwd
    assert table["conv1"]["flops"] == pytest.approx(
        3 * 2 * 4 * 24 * 24 * 20 * 25)
    # ip1: 500 x (50*4*4) weights, batch 4
    assert table["ip1"]["flops"] == pytest.approx(
        3 * 2 * 4 * 500 * 50 * 4 * 4)
    assert table["conv1"]["intensity"] > 1.0


# --------------------------------------------------------------------------- #
# spans: Chrome JSON validity, overhead, capture-after-timing
# --------------------------------------------------------------------------- #

def test_span_dump_is_valid_chrome_trace_json(tmp_path):
    rec = SpanRecorder()
    rec.enable()
    with rec.span("dispatch", "step", {"iter": 3}):
        with rec.span("inner", "step"):
            pass
    rec.instant("marker", "sync")
    path = rec.dump(str(tmp_path / "spans.json"))
    with open(path) as f:
        doc = json.load(f)
    evs = doc["traceEvents"]
    assert {e["name"] for e in evs} == {"dispatch", "inner", "marker"}
    for e in evs:
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], (int, float))
        assert "pid" in e and "tid" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0
    by = {e["name"]: e for e in evs}
    assert by["dispatch"]["args"] == {"iter": 3}
    # no tmp litter left behind (atomic rename)
    assert not glob.glob(str(tmp_path / "*.tmp.*"))


def test_span_overhead_under_two_percent_of_lenet_step():
    """The <2% guard: per-span cost (enabled) x spans-per-engine-step must
    stay under 2% of a real CPU LeNet step, and the DISABLED path must be
    sub-microsecond (it lives permanently in the hot loop)."""
    import jax

    net = _lenet_net(8)
    params = net.init(jax.random.PRNGKey(0))
    inputs = {"data": np.zeros((8, 1, 28, 28), np.float32),
              "label": np.zeros((8,), np.int32)}

    def loss(p):
        return net.apply(p, inputs, train=True,
                         rng=jax.random.PRNGKey(1)).loss

    compiled = jax.jit(jax.grad(loss)).lower(params).compile()
    jax.block_until_ready(jax.tree_util.tree_leaves(compiled(params))[0])
    t0 = time.perf_counter()
    for _ in range(5):
        out = compiled(params)
    jax.block_until_ready(jax.tree_util.tree_leaves(out)[0])
    step_s = (time.perf_counter() - t0) / 5

    rec = SpanRecorder()
    n = 2000

    def span_cost():
        t0 = time.perf_counter()
        for i in range(n):
            with rec.span("dispatch", "step"):
                pass
        return (time.perf_counter() - t0) / n

    disabled = min(span_cost() for _ in range(3))
    rec.enable()
    enabled = min(span_cost() for _ in range(3))
    # the engine hot loop wears at most ~8 spans per step (prefetch_wait,
    # dispatch, dispatch_window, boundary syncs, async push/pull/gate)
    assert enabled * 8 < 0.02 * step_s, (
        f"span overhead {enabled * 8 * 1e6:.1f}us/step vs "
        f"2% of step = {0.02 * step_s * 1e6:.1f}us")
    assert disabled < 5e-6, f"disabled span path costs {disabled * 1e6:.2f}us"


def test_trace_capture_stays_after_timing(tmp_path, monkeypatch):
    """measure_then_trace runs EVERY timed step before the profiler ever
    starts — attribution can never contaminate the timed loop (the
    bench.py discipline the satellite pins)."""
    import jax

    order = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda *a, **k: order.append("trace_start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: order.append("trace_stop"))
    timing = A.measure_then_trace(lambda: order.append("step"),
                                  str(tmp_path), iters=3)
    assert order == ["step"] * 3 + ["trace_start", "step", "trace_stop"]
    assert timing["step_ms"] >= 0


# --------------------------------------------------------------------------- #
# engine wiring: --trace_out timeline + stats.yaml at display boundaries
# --------------------------------------------------------------------------- #

SMALLNET = """
name: "ObsNet"
layers {
  name: "src" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 12 width: 12 }
}
layers {
  name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "conv1" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label"
  top: "loss" }
"""


def _solver(max_iter=8, display=2, **kw):
    from poseidon_tpu.proto.messages import (SolverParameter,
                                             load_net_from_string)
    return SolverParameter(train_net_param=load_net_from_string(SMALLNET),
                           base_lr=0.01, lr_policy="fixed", momentum=0.9,
                           display=display, max_iter=max_iter,
                           random_seed=3, **kw)


def _md(n=64):
    rs = np.random.RandomState(0)
    return {"data": rs.randn(n, 1, 12, 12).astype(np.float32),
            "label": rs.randint(0, 5, n)}


@pytest.fixture
def clean_recorder():
    global_rec.clear()
    yield global_rec
    global_rec.disable()
    global_rec.clear()


def test_engine_trace_out_records_hot_path_spans(tmp_path, clean_recorder):
    from poseidon_tpu.runtime.engine import Engine

    eng = Engine(_solver(max_iter=6, display=2,
                         snapshot=3, snapshot_prefix="snap/obs"),
                 memory_data=_md(), output_dir=str(tmp_path),
                 trace_out="spans.json")
    try:
        eng.train()
    finally:
        eng.close()
    path = tmp_path / "spans.json"
    assert path.exists()
    with open(path) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"]}
    for want in ("prefetch_wait", "dispatch", "dispatch_window",
                 "hard_sync", "snapshot"):
        assert want in names, f"{want} span missing from {sorted(names)}"
    # boundary args distinguish the sync kinds
    bounds = {e["args"]["boundary"] for e in doc["traceEvents"]
              if e["name"] == "hard_sync"}
    assert "display" in bounds and "final" in bounds
    # stats.yaml landed too (display boundary), atomically
    assert (tmp_path / "stats.yaml").exists()
    assert not glob.glob(str(tmp_path / "stats.yaml.tmp.*"))


def test_stats_yaml_written_at_display_boundary_not_only_exit(tmp_path):
    """The crash-safety satellite: stats.yaml exists after the FIRST
    display boundary even though the run is still mid-flight (end-of-run
    artifact writing is disabled to prove it)."""
    from poseidon_tpu.runtime.engine import Engine

    eng = Engine(_solver(max_iter=4, display=2), memory_data=_md(),
                 output_dir=str(tmp_path))
    eng._write_artifacts = lambda: None          # no exit-time write
    try:
        eng.train()
    finally:
        eng.close()
    stats = (tmp_path / "stats.yaml").read_text()
    assert "counters:" in stats
    assert "train_iters" in stats
    assert "gauges:" in stats and "iteration" in stats
    assert not glob.glob(str(tmp_path / "stats.yaml.tmp.*"))


# --------------------------------------------------------------------------- #
# async tier: push/pull/gate/admit spans from a real 2-worker exchange
# --------------------------------------------------------------------------- #

def test_async_two_worker_run_records_push_pull_gate_admit_spans(
        tmp_path, clean_recorder):
    from poseidon_tpu.parallel.async_ssp import AsyncSSPClient, ParamService

    clean_recorder.enable()
    params = {"fc": {"w": np.zeros((2, 2), np.float32)}}
    svc = ParamService(params, n_workers=2, liveness_timeout_s=0.0)
    clients = []
    try:
        for w in range(2):
            cli = AsyncSSPClient(w, ("127.0.0.1", svc.port), staleness=0,
                                 n_workers=2, heartbeat_s=0.1)
            cli.join()
            clients.append(cli)

        def worker(cli):
            for _ in range(3):
                clock = cli.push(
                    {"fc": {"w": np.ones((2, 2), np.float32)}})
                cli.refresh()
                cli.gate(clock + 1, timeout_s=20.0)

        threads = [threading.Thread(target=worker, args=(c,))
                   for c in clients]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30.0)
        for c in clients:
            c.mark_done()
    finally:
        for c in clients:
            c.close()
        svc.close()
    path = clean_recorder.dump(str(tmp_path / "async_spans.json"))
    with open(path) as f:
        doc = json.load(f)
    by_cat = {}
    for e in doc["traceEvents"]:
        by_cat.setdefault(e["cat"], set()).add(e["name"])
    assert "async" in by_cat
    for want in ("async_push", "async_pull", "async_admit"):
        assert want in by_cat["async"], by_cat["async"]
    # both workers pushed under span cover
    pushers = {e["args"]["worker"] for e in doc["traceEvents"]
               if e["name"] == "async_push"}
    assert pushers == {0, 1}


# --------------------------------------------------------------------------- #
# metrics endpoint
# --------------------------------------------------------------------------- #

def _get(url: str) -> str:
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.read().decode()


def test_metrics_server_serves_registry_contents():
    reg = StatsRegistry()
    reg.add("train_iters", 42)
    reg.add_time("train_step", 1.25)
    reg.set_gauge("iteration", 42)
    reg.set_section("comm", {"summary": {"total_bytes_per_step": 128}})
    srv = MetricsServer(reg, port=0)
    try:
        body = _get(f"http://127.0.0.1:{srv.port}/")
        assert "train_iters=42" in body
        assert "iteration=42" in body
        assert "train_step_sec=1.25" in body
        assert "comm.summary.total_bytes_per_step=128" in body
        # live: a later add is visible on the next poll
        reg.add("train_iters", 1)
        assert "train_iters=43" in _get(f"http://127.0.0.1:{srv.port}/")
    finally:
        srv.close()


def test_metrics_port_serves_live_counters_mid_train(tmp_path):
    """The acceptance pin: curl the endpoint WHILE train() is running and
    see counters advancing."""
    from poseidon_tpu.runtime.engine import Engine

    eng = Engine(_solver(max_iter=400, display=2), memory_data=_md(),
                 output_dir=str(tmp_path), metrics_port=0)
    assert eng.metrics_port and eng.metrics_port > 0
    url = f"http://127.0.0.1:{eng.metrics_port}/"
    seen_mid_train = []
    t = threading.Thread(target=lambda: eng.train(), daemon=True)
    t.start()
    try:
        deadline = time.time() + 60.0
        while time.time() < deadline:
            body = _get(url)
            for ln in body.splitlines():
                if ln.startswith("train_iters="):
                    v = float(ln.split("=")[1])
                    if 0 < v < 400:     # strictly MID-train
                        seen_mid_train.append(v)
            if seen_mid_train:
                break
            time.sleep(0.02)
        assert seen_mid_train, "endpoint never showed mid-train counters"
        body = _get(url)
        assert "input_stall_sec=" in body
    finally:
        t.join(timeout=120.0)
        eng.close()


# --------------------------------------------------------------------------- #
# serving stats growth (executor bucket fill + reloader counters)
# --------------------------------------------------------------------------- #

@pytest.mark.serving
def test_executor_bucket_fill_and_stats_op_growth():
    import jax
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.proto.messages import load_net_from_string
    from poseidon_tpu.serving.executor import BucketedExecutor
    from poseidon_tpu.serving.server import InferenceServer

    deploy = """
name: "obs_deploy"
input: "data"
input_dim: 1 input_dim: 1 input_dim: 8 input_dim: 8
layers { name: "ip" type: INNER_PRODUCT bottom: "data" top: "ip"
  inner_product_param { num_output: 4 weight_filler { type: "xavier" } } }
"""
    net = Net(load_net_from_string(deploy), "TEST")
    ex = BucketedExecutor(net, net.init(jax.random.PRNGKey(0)),
                          buckets=(2, 4))
    ex.infer({"data": np.zeros((1, 1, 8, 8), np.float32)})   # 1/2 fill
    ex.infer({"data": np.zeros((4, 1, 8, 8), np.float32)})   # 4/4 fill
    fill = ex.bucket_fill()
    assert fill[2] == pytest.approx(0.5)
    assert fill[4] == pytest.approx(1.0)
    srv = InferenceServer(ex)
    try:
        snap = srv.stats_snapshot()
        assert snap["executor_bucket_fill"][2] == pytest.approx(0.5)
        assert snap["reloader"] is None      # none attached -> explicit
    finally:
        srv.shutdown()


# --------------------------------------------------------------------------- #
# bench satellites: trace_meta stamping
# --------------------------------------------------------------------------- #

def test_bench_trace_meta_is_self_describing(tmp_path):
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    batch = {"data": np.zeros((4, 3, 8, 8), np.float32),
             "label": np.zeros((4,), np.int32)}
    meta = bench._trace_meta("alexnet", 64, batch, "cpu", "cpu")
    assert meta["model"] == "alexnet"
    assert meta["scan_steps"] == 64
    assert meta["batch_shape"]["data"] == [4, 3, 8, 8]
    assert meta["backend"] == "cpu"
    assert "captured_at" in meta
    bench._write_trace_meta(str(tmp_path), meta)
    with open(tmp_path / "trace_meta.json") as f:
        assert json.load(f) == meta
